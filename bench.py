"""End-of-round benchmark: multi-level arrow SpMM iteration time.

Measures the reference's headline quantity — wall-clock `spmm_time` per
iteration of ``X := A @ X`` through a full arrow decomposition
(reference arrow/arrow_bench.py:111-134, protocol in BASELINE.md) — on
the available accelerator, and compares against the same iterated SpMM
via scipy CSR on the host CPU (the reference's CPU kernel,
SURVEY.md §2 "Device kernel bridge").

Prints ONE JSON line:
  {"metric": "spmm_iter_ms", "value": <tpu ms/iter>, "unit": "ms",
   "vs_baseline": <scipy_ms / tpu_ms>, ...extra diagnostics}
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    # Full-f32 matmul passes: the correctness gate is parity with the
    # host CPU result (BASELINE.md north star); the default TPU bf16-pass
    # matmul costs ~1e-3 relative error for ~10% speed.
    jax.config.update("jax_default_matmul_precision", "highest")

    from arrow_matrix_tpu.decomposition.decompose import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense

    n, m, width, k, iters = 65536, 8, 2048, 16, 10

    t0 = time.perf_counter()
    a = barabasi_albert(n, m, seed=7)
    levels = arrow_decomposition(a, arrow_width=width, max_levels=2,
                                 block_diagonal=True, seed=7,
                                 backend="auto")
    t_decomp = time.perf_counter() - t0

    multi = MultiLevelArrow(levels, width, mesh=None)
    x_host = random_dense(n, k, seed=3)

    # --- Host CPU baseline: scipy CSR through the decomposition (the
    # reference's CPU path: per-level CSRMM + permutations).
    xb = x_host.copy()
    t0 = time.perf_counter()
    for _ in range(iters):
        xb = decomposition_spmm(levels, xb)
    scipy_ms = (time.perf_counter() - t0) / iters * 1e3

    # --- Device path.  Timing protocol for remote/tunneled devices
    # (e.g. the axon TPU relay): block_until_ready without a host fetch
    # can return before the work is actually done, so each measurement
    # chains the iterations and ends with a scalar host fetch (which
    # cannot complete early), and the dispatch+fetch round-trip is
    # measured separately and subtracted.
    x = multi.set_features(x_host)

    def chain(n: int) -> float:
        t0 = time.perf_counter()
        xd = multi.run(x, n) if n else x
        float(np.asarray(xd[0, 0]))  # forced host fetch
        return time.perf_counter() - t0

    chain(iters)  # compile + warmup at the benchmark length
    rtt = min(chain(0) for _ in range(3))  # dispatch+fetch round-trip
    tpu_ms = max((chain(iters) - rtt) / iters, 1e-9) * 1e3

    # --- Correctness gate: one device step vs the scipy golden.
    got = multi.gather_result(multi.step(x))
    want = decomposition_spmm(levels, x_host)
    err = float(np.linalg.norm(got - want) /
                max(np.linalg.norm(want), 1e-30))

    nnz = sum(int(l.matrix.nnz) for l in levels)
    gflops = 2.0 * nnz * k / (tpu_ms * 1e-3) / 1e9

    print(json.dumps({
        "metric": "spmm_iter_ms",
        "value": round(tpu_ms, 3),
        "unit": "ms",
        "vs_baseline": round(scipy_ms / tpu_ms, 3),
        "scipy_cpu_ms": round(scipy_ms, 3),
        "gflops": round(gflops, 2),
        "frobenius_err_vs_cpu": err,
        "platform": jax.devices()[0].platform,
        "config": {"n": n, "edges_nnz": nnz, "width": width, "features": k,
                   "iterations": iters, "levels": len(levels),
                   "decompose_s": round(t_decomp, 2)},
    }))

    # Enforce the correctness gate: a fast-but-wrong kernel must fail the
    # bench, not report a headline speedup (the JSON line above is still
    # emitted so the failure is diagnosable from the recorded output).
    if not np.isfinite(err) or err > 1e-5:
        raise SystemExit(f"correctness gate failed: frobenius err {err:.3e} "
                         f"vs host CPU exceeds 1e-5")


if __name__ == "__main__":
    main()
