"""End-of-round benchmark: multi-level arrow SpMM iteration time.

Measures the reference's headline quantity — wall-clock `spmm_time` per
iteration of ``X := A @ X`` through a full arrow decomposition
(reference arrow/arrow_bench.py:111-134, protocol in BASELINE.md) — on
the available accelerator at protocol scale (>=1M rows, BASELINE.md
configs), and compares against the same iterated SpMM via scipy CSR on
the host CPU (the reference's CPU kernel, SURVEY.md §2 "Device kernel
bridge").

Robustness contract (round-1 and round-2 postmortems):

- The accelerator backend is probed in a *subprocess with a timeout* —
  a hung PJRT plugin (an unreachable TPU tunnel) must degrade to a
  diagnosable CPU run, not hang the bench.
- The PARENT process never initializes the accelerator.  Every device
  touch — each format candidate of the headline race and each kernel
  variant of the comparison — runs in its own subprocess with a hard
  timeout, because a tunneled TPU can wedge *mid-transfer* inside a
  native RPC wait where no signal handler runs (observed: a ~1.3 GB
  block upload wedging the tunnel; SIGALRM alone cannot interrupt it).
  A wedge therefore costs one candidate's timeout, not the bench.
- After any candidate timeout the chip is re-probed; if the probe also
  hangs, the race stops and reports `accelerator_wedged` instead of
  burning the deadline on doomed candidates.
- The headline race runs FIRST (the tunnel is healthiest early); the
  kernel comparison is diagnostics and runs after, inside whatever
  deadline remains.
- Exactly ONE JSON line is always printed, with an "error" field when
  anything failed:

  {"metric": "spmm_iter_ms", "value": N, "unit": "ms",
   "vs_baseline": scipy_ms / device_ms, ...diagnostics}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Peak HBM bandwidth (GB/s) by TPU generation, for the bandwidth
# roofline (public figures; the iterated SpMM is bandwidth-bound: each
# iteration streams the resident blocks once).
PEAK_HBM_GBPS = {
    "v6": 1640.0,
    "v5p": 2765.0,
    "v5e": 819.0,
    "v5lite": 819.0,   # v5e reports device_kind "TPU v5 lite"
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}


def _peak_bw(device_kind: str) -> float | None:
    kind = device_kind.lower().replace(" ", "")
    for key, bw in PEAK_HBM_GBPS.items():
        if key in kind:
            return bw
    return None


def probe_backend(timeout_s: float = 60.0, retries: int = 2
                  ) -> tuple[str, str, str | None]:
    """Initialize-check the default JAX backend (see
    utils.platform.probe_default_backend — one copy of the probe
    contract, shared with the doctor CLI).  On repeated failure
    reports platform "cpu" so the bench still produces a measurement,
    flagged as degraded; the parent process itself never touches a
    backend."""
    from arrow_matrix_tpu.utils.platform import probe_default_backend

    return probe_default_backend(timeout_s=timeout_s, retries=retries)


def probe_backend_laddered(schedule=(60.0, 120.0, 300.0)
                           ) -> tuple[str, str, str | None]:
    """Escalating probe timeouts (round-2 postmortem: a slow-to-wake
    tunnel failed three 60s probes, degrading the whole round to CPU
    — a single 300s rung would have caught it).  Returns on the first
    rung that finds an accelerator; the ladder only costs time when
    the backend is genuinely dead."""
    from arrow_matrix_tpu.utils.platform import (
        classify_probe_error,
        reset_tunnel_state,
    )

    platform = device_kind = "cpu"
    err: str | None = None
    for i, timeout_s in enumerate(schedule):
        platform, device_kind, err = probe_backend(
            timeout_s=timeout_s, retries=1)
        if platform != "cpu":
            return platform, device_kind, None
        _progress(f"probe rung {timeout_s:.0f}s failed: {err}")
        # Recovery between rungs (round-3 postmortem: the system had
        # avoidance but no recovery once wedged): an init-hang with a
        # stale local plugin holder means a half-dead client's claim
        # may be blocking ours server-side — clear it, then give the
        # next rung a fresh chance.  A "no-device" failure skips the
        # remaining rungs entirely (retrying cannot help).
        cls = classify_probe_error(err)
        if cls == "no-device":
            break
        if cls == "init-hang" and i < len(schedule) - 1:
            cleared = reset_tunnel_state(log=_progress)
            if cleared:
                _progress(f"cleared stale plugin holders {cleared}; "
                          f"re-probing")
    return platform, device_kind, err


def _maybe_force_cpu() -> None:
    """Pin this (child) process to the host CPU when either pin flag is
    set — ONE mechanism behind two accepted names (AMT_BENCH_FORCECPU
    set by the parent's spawn helpers, AMT_BENCH_CPU the documented
    manual knob), so a caller setting either gets the same behavior."""
    if (os.environ.get("AMT_BENCH_FORCECPU") == "1"
            or os.environ.get("AMT_BENCH_CPU") == "1"):
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()


def _measure(multi, x, iters: int) -> float:
    """ms/iter via chained on-device iteration (`lax.scan`) ending in a
    scalar host fetch, with the dispatch+fetch round-trip subtracted —
    block_until_ready alone can return early over remote/tunneled
    devices, a host fetch cannot.  The implementation lives in
    arrow_matrix_tpu.obs (shared with the graft-scope smoke harness)."""
    from arrow_matrix_tpu.obs import chained_iteration_ms

    return chained_iteration_ms(multi.run, x, iters)


def _degraded_small(platform: str) -> tuple[bool, bool]:
    """degraded = accelerator unreachable (probe fell back to CPU) —
    the bench still runs the FULL protocol scale with the known-best
    format (an honest fallback number: the fold CPU run beats the
    scipy baseline ~2.5x at n=2^20, and the deadline math holds even
    with a cold decomposition cache).  AMT_BENCH_SMALL=1 requests the
    quick diagnostic scale instead; AMT_BENCH_FULL=1 additionally
    re-enables the full fold/hyb/auto race on CPU (the control-run
    mode)."""
    degraded = platform == "cpu"
    small = os.environ.get("AMT_BENCH_SMALL") == "1"
    return degraded, small


def _cached_levels(n: int, m: int, width: int, seed: int,
                   max_levels: int = 4):
    """Generate+decompose once per (n, m, width, seed), then reload the
    on-disk artifact — the reference's offline/online split
    (decomposition artifacts ARE the resume point, SURVEY.md §5): a
    34s setup at n=1M becomes a sub-second reload on repeat runs."""
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
        save_decomposition,
    )
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    base = os.path.join("bench_cache",
                        f"ba_{n}_{m}_w{width}_s{seed}_L{max_levels}")
    # Completion sentinel: save_decomposition writes many files; a run
    # killed mid-write (subprocess timeouts are SIGKILL) must not leave
    # a loadable-but-truncated artifact that later runs silently
    # benchmark as a smaller problem.
    sentinel = base + ".complete"
    if os.path.exists(sentinel):
        try:
            loaded = load_decomposition(base, width, block_diagonal=True)
            widths = load_level_widths(base, width, block_diagonal=True)
            _progress(f"loaded cached decomposition {base}")
            return as_levels(loaded, widths if widths is not None else width)
        except FileNotFoundError:
            pass
    a = barabasi_albert(n, m, seed=seed)
    levels = arrow_decomposition(a, arrow_width=width,
                                 max_levels=max_levels,
                                 block_diagonal=True, seed=seed,
                                 backend="auto")
    try:
        save_decomposition(levels, base, block_diagonal=True)
        with open(sentinel, "w") as f:
            f.write(f"{len(levels)} levels\n")
    except OSError as e:  # caching is best-effort (read-only dirs etc.)
        _progress(f"decomposition cache write failed: {e}")
    return levels


def _progress(msg: str) -> None:
    """Stage markers on stderr (stdout carries only the JSON line): a
    killed/timed-out run must be diagnosable from its partial output."""
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)
    # Mirror into the flight recorder when one is installed (candidate
    # children): the on-disk ring survives the SIGKILL that erases the
    # stderr pipe's tail.  sys.modules peek, not an import — the parent
    # process never pays for (or triggers) the obs package.
    mod = sys.modules.get("arrow_matrix_tpu.obs.flight")
    if mod is not None:
        mod.record("progress", msg)


_T0 = time.perf_counter()


def _flight_path(name: str) -> str:
    """On-disk flight-recorder artifact for one bench child.  One
    well-known location (override: AMT_FLIGHT_DIR) shared by the child
    that writes it and the parent that points at it on timeout."""
    return os.path.join(
        os.environ.get("AMT_FLIGHT_DIR",
                       os.path.join("bench_cache", "flight")),
        f"{name}.json")


def _install_flight(name: str):
    """Install the black-box recorder in a candidate/variant child: a
    bounded ring of progress events eagerly flushed to disk, so a child
    the parent SIGKILLs on timeout (the observed wedge mode — a native
    RPC wait no signal reaches) still leaves its last-known state
    behind.  Best-effort: a read-only disk or a broken obs install must
    never cost the measurement."""
    try:
        from arrow_matrix_tpu.obs import flight

        return flight.install(_flight_path(name))
    except Exception as e:
        print(f"[bench] flight recorder unavailable: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None


def _bench_config(platform: str, fmt_override: str | None = None) -> dict:
    """One derivation of the benchmark shape from the probed platform,
    shared by the parent (baseline, roofline) and the candidate
    subprocesses (build + measure) via AMT_BENCH_CFG.

    ``fmt_override`` beats the environment (the mid-window upgrade
    passes its candidate list here instead of mutating os.environ,
    which would leak into later _bench_config calls in the same run —
    ADVICE r3)."""
    degraded, small = _degraded_small(platform)
    if small:
        # Quick diagnostic scale: large enough that the folded SELL
        # operator beats the host scipy baseline even on CPU (measured
        # 1.24x at 2^17; at the old 32k smoke scale scipy won), small
        # enough to finish in seconds.
        cfg = dict(n=1 << 17, m=8, width=2048, k=16, iters=5, fmt="fold")
    elif degraded and os.environ.get("AMT_BENCH_FULL") != "1":
        # Accelerator unreachable: full protocol scale, single
        # known-best candidate (racing hyb/auto on one host CPU costs
        # ~15 min for numbers that only restate the fold win).
        cfg = dict(n=1 << 20, m=8, width=2048, k=16, iters=10,
                   fmt="fold")
    else:
        # Protocol scale (BASELINE.md: >=1M rows, features 16, 10 iters).
        cfg = dict(n=1 << 20, m=8, width=2048, k=16, iters=10, fmt="auto")
    cfg["n"] = int(os.environ.get("AMT_BENCH_N", cfg["n"]))
    cfg["fmt"] = fmt_override or os.environ.get("AMT_BENCH_FMT",
                                                cfg["fmt"])
    # max_levels high enough to converge: a capped decomposition leaves
    # a grown last level holding half the nonzeros at near-full-matrix
    # width (measured 657k-wide at n=1M with the old cap of 4), which
    # no kernel can tile well.  At 1M/BA-8 the recursion exhausts after
    # 10 levels, all at the base width.
    cfg["max_levels"] = int(os.environ.get("AMT_BENCH_LEVELS", 12))
    cfg["degraded"] = degraded
    cfg["platform"] = platform
    # k=128 is a chip metric: in degraded (accelerator-unreachable)
    # mode the rerun measures nothing the k=16 CPU number doesn't, and
    # the rehearsal showed it can burn its full 900s timeout of the
    # deadline — default OFF there (AMT_BENCH_K128=1 forces it on).
    k128_default = "0" if degraded else "1"
    cfg["k128"] = (cfg["k"] != 128
                   and os.environ.get("AMT_BENCH_K128",
                                      k128_default) == "1")
    # Chunked overlap schedule (graft-stream): S static feature
    # sub-slabs per step so slab i+1's exchange overlaps slab i's
    # compute.  1 = the serial baseline; must divide k.
    cfg["overlap_slabs"] = max(
        int(os.environ.get("AMT_BENCH_OVERLAP_SLABS", "1")), 1)
    # 2.5D replication factor (graft-repl): fold candidates run the
    # sequential column-group schedule (bit-identical by construction,
    # column-separable SpMM); must divide k.  1 = unreplicated.
    cfg["repl"] = max(int(os.environ.get("AMT_BENCH_REPL", "1")), 1)
    return cfg


#: Headline-race candidate name -> MultiLevelArrow build kwargs.
#: "fold_tight" trades tile-friendly slot alignment for ~17% fewer
#: LOGICAL slots (align 1 / growth 1.1 vs 8 / 1.2 — ops/sell.py
#: measurement); slots are the gather cost, so on chip it should win
#: iff slots/s holds across ~2x the tier count.
CANDIDATE_KWARGS = {
    "fold": dict(fmt="fold"),
    "fold_tight": dict(fmt="fold", fold_growth=1.1, fold_align=1),
    # Fused Pallas SELL kernel over the same fold build (graft-stream):
    # gather->multiply->accumulate in VMEM, no (k, chunk, rows)
    # intermediate.  Races with its own subprocess timeout like every
    # candidate — a Mosaic compile hang costs only this entry.
    "pallas_sell": dict(fmt="fold", kernel="pallas_sell"),
}


def run_one_candidate(fmt: str) -> None:
    """Build + measure ONE headline-race format candidate at the
    configured scale; prints one JSON line with its numbers.

    Runs in a subprocess spawned by the parent race so that a wedging
    accelerator transfer or a pathological compile costs its own
    timeout, not the bench (the observed round-2 failure mode: a large
    block upload hanging inside a native RPC wait, uninterruptible by
    SIGALRM).  ``AMT_BENCH_FORCECPU=1`` pins the subprocess to the
    host CPU for degraded mode."""
    cfg = json.loads(os.environ["AMT_BENCH_CFG"])
    _maybe_force_cpu()
    _install_flight(f"candidate_{fmt}_k128" if cfg.get("k128_run")
                    else f"candidate_{fmt}")
    _progress(f"fmt={fmt} candidate start: n={cfg['n']} "
              f"width={cfg['width']} k={cfg['k']} "
              f"platform={cfg['platform']}")
    import jax

    # Full-f32 matmul passes: the correctness gate is parity with the
    # host CPU result (BASELINE.md north star + the accumulation-order
    # policy in utils/numerics.py); the default TPU bf16-pass matmul
    # costs ~1e-3 relative error for ~10% speed.
    jax.config.update("jax_default_matmul_precision", "highest")
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:   # explicit: env-var pickup varies across jax versions
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)

    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import random_dense
    from arrow_matrix_tpu.utils.platform import (
        device_memory_budget,
        host_load,
    )

    levels = _cached_levels(cfg["n"], cfg["m"], cfg["width"], seed=7,
                            max_levels=cfg["max_levels"])
    budget = device_memory_budget(jax.devices()[0])

    build_kwargs = dict(CANDIDATE_KWARGS.get(fmt, dict(fmt=fmt)))
    slabs = max(int(cfg.get("overlap_slabs", 1)), 1)
    if slabs > 1:
        build_kwargs["overlap_slabs"] = slabs
    # repl composes with the fold schedule only (MultiLevelArrow
    # validates the same) — never silently attach it to hyb/auto.
    repl = max(int(cfg.get("repl", 1)), 1)
    if repl > 1 and build_kwargs.get("fmt") == "fold":
        build_kwargs["repl"] = repl
    t0 = time.perf_counter()
    multi = MultiLevelArrow(levels, cfg["width"], mesh=None,
                            dense_budget=budget, **build_kwargs)
    build_s = time.perf_counter() - t0
    _progress(f"fmt={fmt} built in {build_s:.0f}s; compile+measure")
    out = {
        "build_s": round(build_s, 2),
        "fmts": list(multi.fmts),
        "block_bytes": sum(b.device_nbytes() for b in multi.blocks),
        "total_rows": multi.total_rows,
        "dense_budget_gb": round(budget / 2**30, 2),
        # Measurement hygiene (VERDICT item 6): every committed number
        # carries the host contention it was taken under.
        "host_load": host_load(),
    }
    if slabs > 1:
        out["overlap_slabs"] = slabs
    if "repl" in build_kwargs:
        out["repl"] = build_kwargs["repl"]
    if cfg.get("k128_run"):
        # Second headline feature width (the north-star metric names 16
        # AND 128 features; BASELINE configs 3/5 are k=128), measured
        # ONLY in this winner-rerun mode: inside the race it would
        # triple the full-scale device work (a fresh n x 128 upload per
        # candidate) and could time out a candidate whose k=16 number
        # was valid.  The k=16 measure is skipped here — the race
        # already produced it.  GATED like k=16 (VERDICT r2 item 2):
        # one device step is compared against the host golden and the
        # parent rejects the number if it misses.
        try:
            _progress(f"fmt={fmt}: k=128 measurement")
            x128_host = random_dense(cfg["n"], 128, seed=4)
            x128 = multi.set_features(x128_host)
            out["k128_ms"] = round(_measure(multi, x128, cfg["iters"]), 3)
            # Golden on the first 16 of the 128 columns: SpMM is
            # column-separable, so the slice fully validates the
            # kernel at 1/8 the host-golden cost — the k=128 golden
            # at n=2^20 otherwise costs minutes of scipy time and
            # once pushed this child past its timeout (a SIGKILL
            # mid-TPU-transfer wedges the tunnel).
            out["k128_err"] = numerics.relative_error(
                multi.gather_result(multi.step(x128))[:, :16],
                decomposition_spmm(levels, x128_host[:, :16]))
            if fmt.startswith("fold"):
                # bf16 carriage at k=128 — the regime where gathered
                # rows turn bandwidth-bound (PERFORMANCE.md cost
                # model); feature_dtype only affects set_features, so
                # the same build measures both.  Secondary diagnostic,
                # never the gate.
                from arrow_matrix_tpu.parallel.multi_level import (
                    resolve_feature_dtype,
                )

                prior_dtype = multi.feature_dtype
                try:
                    multi.feature_dtype = resolve_feature_dtype("bf16")
                    xb = multi.set_features(x128_host)
                    out["k128_bf16_ms"] = round(
                        _measure(multi, xb, cfg["iters"]), 3)
                finally:
                    # a measurement added after this block must see
                    # f32 carriage, not silently inherit bf16
                    multi.feature_dtype = prior_dtype
        except Exception as e:   # secondary metric, never the gate
            out["k128_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    else:
        x_host = random_dense(cfg["n"], cfg["k"], seed=3)
        x = multi.set_features(x_host)
        out["ms"] = round(_measure(multi, x, cfg["iters"]), 3)
        want = decomposition_spmm(levels, x_host)
        out["err"] = numerics.relative_error(
            multi.gather_result(multi.step(x)), want)
        # Gather-roofline inputs: padded slots are the ELL-family cost
        # model (PERFORMANCE.md "layout-padding law"), so the roofline
        # is achieved slots/s against a pure-gather rate measured on
        # THIS chip in THIS run — the MFU analog for a gather-bound
        # kernel, and chip-honest unlike a hardcoded constant.
        slots = sum(int(b.n_slots) for b in multi.blocks
                    if hasattr(b, "n_slots"))
        if slots:
            out["gather_slots"] = slots
            try:
                out["peak_gather_rows_s"] = _peak_gather_rate(
                    cfg["n"], cfg["k"])
            except Exception as e:   # roofline is reporting, not gating
                out["peak_gather_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out), flush=True)


def _peak_gather_rate(n: int, k: int, m: int = 8, reps: int = 3) -> float:
    """Reference gather rate (rows/s): a jitted MATERIALIZING take of
    n*m uniform-random rows from an (n, k) f32 array.

    Materializing deliberately: a fused ``take(...).sum()`` probe gets
    algebraically rewritten by XLA (gather+reduce -> weighted matmul)
    and reports impossible rates.  Uniform-random indices make this a
    reproducible *reference point*, not a hard ceiling: a real
    operator whose index distribution has locality (power-law graphs
    gather hub rows repeatedly — HBM-cache hits) can legitimately
    exceed it, so ``roofline_frac`` above 1.0 reads "beats the
    random-gather reference by that factor via index locality"."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    idx = jnp.asarray(rng.integers(0, n, size=n * m, dtype=np.int32))
    x = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32))
    from arrow_matrix_tpu.obs import timed

    f = jax.jit(lambda xx, ii: jnp.take(xx, ii, axis=0))
    f(x, idx).block_until_ready()
    best = min(timed(lambda: f(x, idx)) for _ in range(reps))
    return n * m / best


class _device_busy:
    """Hold ``bench_cache/tpu_busy.lock`` while a device child runs.

    The lock is the cross-process contract with reset_tunnel_state
    (utils/platform.py) and the watcher: a fresh lock means a
    legitimate chip user exists, so staleness recovery must not
    SIGTERM a child that is merely blocked in a long zero-CPU PJRT
    transfer wait.  Refreshing on entry covers driver-launched
    bench.py runs the watcher does not know about."""

    def __init__(self, active: bool = True):
        self.active = active
        # Repo-anchored, NOT cwd-relative: reset_tunnel_state reads
        # the absolute <repo>/bench_cache path, and the driver may
        # launch bench.py from any directory.
        self.path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "bench_cache", "tpu_busy.lock")

    def __enter__(self):
        if self.active:
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                with open(self.path, "w") as f:
                    f.write(f"bench pid {os.getpid()}\n")
            except OSError:
                pass
        return self

    def __exit__(self, *exc):
        if self.active:
            try:
                os.remove(self.path)
            except OSError:
                pass
        return False


def _spawn_candidate(fmt: str, cfg: dict, timeout_s: float) -> dict:
    """One candidate subprocess -> its parsed JSON (or an error dict).
    Every failure shape — nonzero rc, hang, unparseable stdout — is
    contained to the returned dict (one candidate costs one candidate).

    Child stdout is parsed with the shared
    ``utils/artifacts.parse_last_json_line`` (last line is the record,
    anything above it is chatter).

    FORCECPU keys on the probed *platform*: any CPU run — including an
    AMT_BENCH_FULL=1 control run, which is flagged degraded like every
    accelerator-unreachable run — must pin children to the host CPU or
    each would hang in the dead TPU plugin."""
    from arrow_matrix_tpu.utils.artifacts import parse_last_json_line

    env = dict(os.environ, AMT_BENCH_CFG=json.dumps(cfg))
    if cfg["platform"] == "cpu":
        env["AMT_BENCH_FORCECPU"] = "1"
    # Persistent XLA compilation cache shared by every candidate/rerun
    # subprocess: the ~20-40s TPU compiles happen once per program
    # shape per round instead of once per subprocess (round-2
    # postmortem item: make the bench fight for the chip with a warm
    # cache).
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.abspath(os.path.join("bench_cache",
                                                "xla_cache")))
    try:
        with _device_busy(active=cfg["platform"] != "cpu"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--candidate", fmt],
                capture_output=True, text=True, timeout=timeout_s,
                env=env)
        if proc.returncode != 0 or not proc.stdout.strip():
            _progress(f"fmt={fmt} FAILED rc={proc.returncode}")
            return {"error": f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-400:]}"}
        run = parse_last_json_line(proc.stdout)
        if run is None:
            return {"error": f"unusable child output: "
                             f"{proc.stdout.strip()[-200:]}"}
        if "k128_ms" in run and "ms" not in run:
            _progress(f"fmt={fmt}: k=128 {run['k128_ms']} ms/iter")
        else:
            _progress(f"fmt={fmt}: {run.get('ms')} ms/iter "
                      f"err={run.get('err')}")
        return run
    except subprocess.TimeoutExpired:
        err = {"error": f"timed out after {timeout_s:.0f}s",
               "timed_out": True}
        # The killed child's flight recorder is the only record of how
        # far it got (SIGKILL leaves no stderr tail): point at it.
        fp = _flight_path(f"candidate_{fmt}_k128"
                          if cfg.get("k128_run") else f"candidate_{fmt}")
        if os.path.exists(fp):
            err["flight"] = fp
            _progress(f"fmt={fmt} timed out; black box at {fp} "
                      f"(graft_trace blackbox)")
        return err
    # No blanket except: it would swallow the one-shot deadline
    # TimeoutError raised by the SIGALRM handler while the parent
    # waits in subprocess.run — the race would then keep running past
    # the deadline and the driver would kill the bench with no JSON
    # emitted.  Child-output parse failures are the None branch above.


def _bytes_per_iter_model(block_bytes: int, total_rows: int, k: int,
                          n_lvl: int) -> int:
    """Bandwidth-floor bytes of one iteration: every resident block
    array streamed once, the feature array read+written once per level
    plus ~2 more feature passes per level beyond the first (the
    routing gathers).  ONE definition for every feature width — the
    k=16 and k=128 headlines must share the model."""
    feat_bytes = total_rows * k * 4
    return block_bytes + feat_bytes * (2 * n_lvl + 2 * (n_lvl - 1))


def _check_wedged(result: dict, cfg: dict, label: str) -> bool:
    """After a candidate/rerun timeout on an accelerator platform,
    re-probe the chip (real data round-trip); record and report a
    wedge.  One policy for every timeout site."""
    if cfg["platform"] == "cpu":
        return False
    platform, _, perr = probe_backend(timeout_s=60.0, retries=1)
    if platform != "cpu":
        return False
    result["accelerator_wedged"] = (
        f"chip probe failed after {label} timeout: {perr}")
    _progress(f"accelerator wedged after {label}")
    return True


def race_candidates(result: dict, cfg: dict, finalize,
                    timeout_s: float = 900.0) -> dict:
    """Run each format candidate in its own subprocess, folding every
    completed result into `result` via ``finalize`` AS THE RACE RUNS —
    a deadline alarm (or any crash) mid-race must not discard a
    headline number a finished candidate already earned.  After a
    timeout the chip is re-probed and the race stops if it wedged
    (every later candidate would burn its timeout against a dead
    tunnel)."""
    if cfg["fmt"] == "auto":
        candidates = ["fold", "fold_tight", "pallas_sell", "hyb", "auto"]
    else:
        # Comma list supported (the mid-window upgrade races the two
        # fold packings without paying for the known-slower formats);
        # items are stripped, and an empty spec falls back to the
        # degraded default rather than racing ZERO candidates (which
        # would exit without the diagnosable-JSON contract).
        candidates = [f.strip() for f in cfg["fmt"].split(",")
                      if f.strip()] or ["fold"]
    runs = {}
    for f in candidates:
        _progress(f"candidate fmt={f}")
        runs[f] = _spawn_candidate(f, cfg, timeout_s)
        timed_out = runs[f].pop("timed_out", False)
        finalize(runs)
        if timed_out and _check_wedged(result, cfg, f"fmt={f}"):
            break   # later candidates would burn out against a dead link
    return runs


def run_bench(result: dict, platform: str, device_kind: str,
              fmt_override: str | None = None) -> None:
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.utils import logging as wb
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import random_dense

    cfg = _bench_config(platform, fmt_override)
    n, k, iters = cfg["n"], cfg["k"], cfg["iters"]
    result["config"] = {"n": n, "width": cfg["width"], "features": k,
                        "iterations": iters, "ba_neighbors": cfg["m"]}
    result["platform"] = platform
    result["device_kind"] = device_kind
    if cfg["degraded"]:
        result["degraded"] = True
    if cfg["overlap_slabs"] > 1:
        result["overlap_slabs"] = cfg["overlap_slabs"]
    if cfg["repl"] > 1:
        result["repl"] = cfg["repl"]
    # Measurement hygiene (VERDICT item 6): the committed line records
    # the host contention at race start — a loaded host explains an
    # anomalous CPU baseline or build time without re-running anything.
    try:
        from arrow_matrix_tpu.utils.platform import host_load

        result["host_load"] = host_load()
    except Exception:
        pass   # hygiene field, never the gate

    _progress(f"platform={platform} kind={device_kind} n={n} "
              f"fmt={cfg['fmt']}")
    seg = wb.init("bench", f"ba_n{n}", config=dict(result["config"]))
    with seg.segment("decompose_s"):
        levels = _cached_levels(n, cfg["m"], cfg["width"], seed=7,
                                max_levels=cfg["max_levels"])
    result["config"]["decompose_s"] = round(
        seg.entries[-1]["decompose_s"], 2)
    result["config"]["levels"] = len(levels)
    nnz = sum(int(l.matrix.nnz) for l in levels)
    result["config"]["edges_nnz"] = nnz

    # --- Host CPU baseline: scipy CSR through the decomposition (the
    # reference's CPU path: per-level CSRMM + permutations).  Runs in
    # the parent BEFORE the race so candidate subprocesses (which own
    # the accelerator) never contend with it for host cores.
    x_host = random_dense(n, k, seed=3)
    base_iters = 3 if n > (1 << 18) else iters
    _progress(f"decomposed in {result['config']['decompose_s']}s; "
              f"scipy baseline")
    xb = x_host.copy()
    with seg.segment("scipy_baseline_s"):
        for _ in range(base_iters):
            xb = decomposition_spmm(levels, xb)
    scipy_ms = seg.entries[-1]["scipy_baseline_s"] / base_iters * 1e3
    tol = numerics.relative_tolerance(nnz / max(n, 1), iters=1)
    _progress(f"scipy baseline {scipy_ms:.0f} ms/iter; racing candidates")

    def finalize(runs: dict) -> None:
        """Fold the current race state into `result` (called after
        every candidate): sanitized per-candidate numbers plus the
        best-so-far headline metrics.  Idempotent — later calls with
        more candidates overwrite with at-least-as-good winners."""
        result["device_runs"] = {
            name: {kk: vv for kk, vv in r.items()
                   if kk not in ("block_bytes", "total_rows",
                                 "dense_budget_gb")}
            for name, r in runs.items()}
        best = None
        for name, r in runs.items():
            if ("ms" in r and np.isfinite(r["err"]) and r["err"] <= tol
                    and (best is None or r["ms"] < runs[best]["ms"])):
                best = name
        if best is None:
            return
        win = runs[best]
        dev_ms = win["ms"]
        result["config"]["fmts"] = win["fmts"]
        result["config"]["build_s"] = win["build_s"]
        result["config"]["dense_budget_gb"] = win["dense_budget_gb"]
        result["fmt_used"] = best

        flops = 2.0 * nnz * k
        # Bandwidth roofline: the memory floor (_bytes_per_iter_model);
        # achieved/floor bandwidth against the chip's peak is the MFU
        # analog for a bandwidth-bound kernel.
        bytes_per_iter = _bytes_per_iter_model(
            win["block_bytes"], win["total_rows"], k, len(levels))
        achieved_gbps = bytes_per_iter / (dev_ms * 1e-3) / 1e9
        peak = _peak_bw(device_kind)
        result.update({
            "value": dev_ms,
            "vs_baseline": round(scipy_ms / dev_ms, 3),
            "scipy_cpu_ms": round(scipy_ms, 3),
            "gflops": round(flops / (dev_ms * 1e-3) / 1e9, 2),
            "frobenius_err_vs_cpu": win["err"],
            "frobenius_gate": tol,
            "bytes_per_iter_gb": round(bytes_per_iter / 2**30, 3),
            "achieved_gbps": round(achieved_gbps, 1),
        })
        # Roofline: gather-slots model when the winner reports one
        # (padded slots ARE the cost of the SELL/fold kernels —
        # PERFORMANCE.md; the achieved rate lands within ~7% of the
        # pure-gather probe on chip), HBM-stream model otherwise.
        if win.get("gather_slots") and win.get("peak_gather_rows_s"):
            rate = win["gather_slots"] / (dev_ms * 1e-3)
            result.update({
                "roofline_model": "gather-slots vs uniform-random "
                                  "materializing take (same chip, same "
                                  "run; >1 = index-locality win)",
                "gather_rows_per_s": round(rate),
                "peak_gather_rows_s": round(win["peak_gather_rows_s"]),
                "roofline_frac": round(
                    rate / win["peak_gather_rows_s"], 3),
            })
        else:
            result.update({
                "roofline_model": "hbm-stream",
                "roofline_frac": (round(achieved_gbps / peak, 3)
                                  if peak else None),
            })

    # --- Device path: race the candidate single-chip execution configs
    # at full scale (each in its own subprocess, see race_candidates)
    # and report the best.  Each candidate is gated for correctness
    # individually AND isolated against failure: a compile OOM, kernel
    # error, or wedged transfer in one format costs only that
    # candidate, not the race.
    runs = race_candidates(result, cfg, finalize)
    if result.get("value") is None:
        outcomes = [(name, r.get("err", r.get("error")))
                    for name, r in runs.items()]
        raise RuntimeError(
            f"every config failed or missed the correctness gate: "
            f"{outcomes} vs {tol:.1e}")

    # Secondary feature width on the WINNER only (north-star names 16
    # and 128 features): one extra subprocess re-builds the winning
    # format and measures k=128 — never inside the race, where it
    # would triple the device work and could time out a candidate
    # whose k=16 number was valid.
    if cfg["k128"] and not result.get("accelerator_wedged"):
        _progress(f"k=128 rerun on winner fmt={result['fmt_used']}")
        # 1500s: the rerun carries a 0.5 GB upload + two measures +
        # the sliced host golden; a timeout here SIGKILLs a process
        # mid-TPU-transfer, which wedges the tunnel — size the bound
        # so only a genuine wedge can hit it.
        rerun = _spawn_candidate(result["fmt_used"],
                                 dict(cfg, k128_run=True),
                                 timeout_s=1500.0)
        if "k128_ms" in rerun:
            # Gated like the k=16 headline (VERDICT r2 item 2: two
            # gated numbers per round): the measurement is reported
            # only when its one-step golden error passes.  Same gate
            # value as the race (`tol`, already recorded as
            # frobenius_gate) — one formula, one tuning point.
            tol128 = tol
            err128 = rerun.get("k128_err", float("inf"))
            result["k128_err"] = err128
            result["k128_gate"] = tol128
            if np.isfinite(err128) and err128 <= tol128:
                result["k128_ms"] = rerun["k128_ms"]
                # Co-equal headline (VERDICT r3 item 2i: BASELINE.md's
                # metric is 16 AND 128 features): publish the same
                # derived quantities as the k=16 headline.  The +~2%
                # time for 8x the bytes is the amortization story —
                # per-slot cost dominates, so k=128 bandwidth is ~8x.
                nnz128 = result["config"].get("edges_nnz", 0)
                n_lvl128 = result["config"].get("levels", 1)
                ms128 = rerun["k128_ms"]
                result["k128_gflops"] = round(
                    2.0 * nnz128 * 128 / (ms128 * 1e-3) / 1e9, 2)
                if rerun.get("total_rows"):
                    by = _bytes_per_iter_model(
                        rerun.get("block_bytes", 0),
                        rerun["total_rows"], 128, n_lvl128)
                    result["k128_achieved_gbps"] = round(
                        by / (ms128 * 1e-3) / 1e9, 1)
                if "k128_bf16_ms" in rerun:
                    # published only under the same gate — a timing
                    # from a kernel that missed its golden is not a
                    # result (the bf16 carriage shares the build the
                    # gate just validated).
                    result["k128_bf16_ms"] = rerun["k128_bf16_ms"]
            else:
                result["k128_error"] = (
                    f"missed correctness gate: {err128} > {tol128}")
        elif rerun.get("k128_error") or rerun.get("error"):
            result["k128_error"] = (rerun.get("k128_error")
                                    or rerun.get("error"))
        # Same wedge contract as the race: a timed-out rerun (e.g. the
        # larger k=128 upload wedging a half-healthy tunnel) must stop
        # the bench from then running kernel_compare against the dead
        # chip.
        if rerun.pop("timed_out", False):
            _check_wedged(result, cfg, "k=128 rerun")

    # --- --overlap_slabs sweep (graft-stream): re-measure the winning
    # format at each requested sub-slab count S, so the committed
    # artifact carries the overlap-vs-serial curve and the next
    # on-chip heal-window captures the verdict automatically (VERDICT
    # item 5).  Each point is its own subprocess with its own timeout
    # and correctness gate; one bad point costs only that point.
    sweep_spec = os.environ.get("AMT_BENCH_OVERLAP_SWEEP", "")
    if sweep_spec and not result.get("accelerator_wedged"):
        fmt_sweep = result.get("fmt_used") or "fold"
        sweep = result["overlap_sweep"] = {"fmt": fmt_sweep}
        for tok in sweep_spec.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if not tok.isdigit() or int(tok) < 1:
                sweep[tok] = {"error": "not a positive integer"}
                continue
            s = int(tok)
            if k % s != 0:
                sweep[str(s)] = {"error": f"S={s} does not divide k={k}"}
                continue
            _progress(f"overlap sweep: fmt={fmt_sweep} S={s}")
            run = _spawn_candidate(
                fmt_sweep, dict(cfg, overlap_slabs=s, k128=False),
                timeout_s=900.0)
            timed_out = run.pop("timed_out", False)
            point = {kk: run[kk]
                     for kk in ("ms", "err", "error", "host_load")
                     if run.get(kk) is not None}
            if ("err" in point and np.isfinite(point["err"])
                    and point["err"] > tol):
                point["gate_missed"] = tol
            sweep[str(s)] = point
            if timed_out and _check_wedged(result, cfg,
                                           f"overlap S={s}"):
                break   # later points would burn out against a dead link

    # --- --repl sweep (graft-repl): re-measure the winning fold-family
    # format at each requested replication factor c.  On one chip the
    # c-group column schedule is bit-identical by construction, so the
    # sweep is the wall-clock cost curve of the 2.5D carve-up — the
    # compute-side half of the T(c) model (the wire-side 1/c cut needs
    # a mesh; dryrun_multichip's repl rung measures that one).  Same
    # per-point subprocess/timeout/gate contract as the overlap sweep.
    repl_spec = os.environ.get("AMT_BENCH_REPL_SWEEP", "")
    if repl_spec and not result.get("accelerator_wedged"):
        fmt_sweep = result.get("fmt_used") or "fold"
        if not str(fmt_sweep).startswith("fold"):
            fmt_sweep = "fold"   # repl composes with the fold schedule
        sweep = result["repl_sweep"] = {"fmt": fmt_sweep}
        for tok in repl_spec.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if not tok.isdigit() or int(tok) < 1:
                sweep[tok] = {"error": "not a positive integer"}
                continue
            rc = int(tok)
            if k % rc != 0:
                sweep[str(rc)] = {"error": f"c={rc} does not divide "
                                           f"k={k}"}
                continue
            _progress(f"repl sweep: fmt={fmt_sweep} c={rc}")
            run = _spawn_candidate(
                fmt_sweep, dict(cfg, repl=rc, k128=False),
                timeout_s=900.0)
            timed_out = run.pop("timed_out", False)
            point = {kk: run[kk]
                     for kk in ("ms", "err", "error", "host_load")
                     if run.get(kk) is not None}
            if ("err" in point and np.isfinite(point["err"])
                    and point["err"] > tol):
                point["gate_missed"] = tol
            sweep[str(rc)] = point
            if timed_out and _check_wedged(result, cfg,
                                           f"repl c={rc}"):
                break   # later points would burn out against a dead link


# Ordered most-informative-first: the total budget may cut the tail,
# and the gather-family variants are cheap (small uploads, fast
# compiles) while the dense/pallas ones ship GBs of blocks — run every
# cheap one before the first expensive one.
COMPARE_VARIANTS = {
    "fold": dict(fmt="fold"),             # composed single-operator SELL
    # Tight packing — SAME config as the headline-race candidate (one
    # definition; the two sweeps must measure the same thing).
    "fold_tight": None,   # filled from CANDIDATE_KWARGS below
    # bf16-carried features (f32 accumulation): half the bytes per
    # gathered row — the amortization lever where the gather turns
    # bandwidth-bound (k=128); outside the f32 gate, diagnostics only.
    "fold_featbf16": dict(fmt="fold", feature_dtype="bf16"),
    "hyb": dict(fmt="hyb"),
    "ell": dict(fmt="ell"),               # platform-aware auto head
    # Head-stack kernel isolation: flat-COO head = scatter-add (TPU
    # scatters serialize), ELL/gell heads = gather + reduce.  The
    # spread between these is the head-kernel cost.
    "ell_headgell": dict(fmt="ell", head_fmt="gell"),
    "ell_headflat": dict(fmt="ell", head_fmt="flat"),
    "ell_headell": dict(fmt="ell", head_fmt="ell"),
    "dense": dict(fmt="dense"),
    "dense_bf16": dict(fmt="dense", dtype="bf16"),
    "pallas": dict(fmt="dense", kernel="pallas"),
    "pallas_bf16": dict(fmt="dense", kernel="pallas", dtype="bf16"),
}
COMPARE_VARIANTS["fold_tight"] = CANDIDATE_KWARGS["fold_tight"]
COMPARE_CONFIG = dict(n=65536, m=8, width=2048, k=16, iters=10)


def run_one_variant(name: str) -> None:
    """Build + measure ONE kernel variant; prints its ms as JSON.

    Runs in a subprocess spawned by ``kernel_compare`` so that a
    pathological kernel (e.g. a Mosaic compile that never returns — a
    hang SIGALRM cannot interrupt inside native code) costs its own
    timeout, not the whole bench.  ``AMT_BENCH_CPU=1`` pins the child
    to the host CPU (JAX_PLATFORMS alone cannot stop a site-registered
    TPU plugin from initializing) — for testing the variants without an
    accelerator."""
    _maybe_force_cpu()
    _install_flight(f"variant_{name}")
    _progress(f"variant={name} start")
    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils.graphs import random_dense

    c = COMPARE_CONFIG
    levels = _cached_levels(c["n"], c["m"], c["width"], seed=7,
                            max_levels=2)
    x_host = random_dense(c["n"], c["k"], seed=3)
    multi = MultiLevelArrow(levels, c["width"], mesh=None,
                            **COMPARE_VARIANTS[name])
    x = multi.set_features(x_host)
    print(json.dumps({"ms": round(_measure(multi, x, c["iters"]), 3)}),
          flush=True)


def kernel_compare(timeout_s: float = 300.0,
                   total_budget_s: float = 900.0,
                   cpu: bool = False, out: dict | None = None) -> dict:
    """ms/iter of the ELL / dense / Pallas / bf16 block kernels on one
    mid-size config (dense must fit): the data for VERDICT r1 item 6
    (integrate Pallas or retire it with numbers).  One subprocess per
    variant, each with a hard timeout; a total budget stops the sweep
    early if the device starts wedging (comparison is diagnostics — it
    must never eat the bench's own time).  ``cpu=True`` pins the
    children to the host CPU — needed whenever the probe reported a
    dead accelerator, or each variant child would hang in the dead
    plugin and burn its timeout.  The sweep itself defaults OFF on CPU
    platforms (AMT_BENCH_COMPARE="auto"); a CPU control run that wants
    these numbers must set AMT_BENCH_COMPARE=1 explicitly.

    ``out`` may be passed in (e.g. a dict already hanging off the
    bench's result): it is filled variant-by-variant AS THE SWEEP
    RUNS, so a deadline alarm mid-sweep keeps every number already
    measured instead of replacing them all with one error."""
    from arrow_matrix_tpu.utils.artifacts import parse_last_json_line

    if out is None:
        out = {}
    out["config"] = dict(COMPARE_CONFIG)
    env = dict(os.environ, AMT_BENCH_CPU="1") if cpu else None
    t_start = time.perf_counter()
    for name in COMPARE_VARIANTS:
        left = total_budget_s - (time.perf_counter() - t_start)
        if left <= 0:
            out[name + "_ms"] = None
            out[name + "_error"] = "compare budget exhausted"
            continue
        _progress(f"kernel variant {name}")
        try:
            with _device_busy(active=not cpu):
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--variant", name],
                    capture_output=True, text=True,
                    timeout=min(timeout_s, left),
                    env=env)
            rec = (parse_last_json_line(proc.stdout)
                   if proc.returncode == 0 else None)
            if rec is not None:
                out[name + "_ms"] = rec.get("ms")
            else:
                out[name + "_ms"] = None
                out[name + "_error"] = (f"rc={proc.returncode}: "
                                        f"{proc.stderr.strip()[-300:]}")
        except subprocess.TimeoutExpired:
            out[name + "_ms"] = None
            out[name + "_error"] = (f"timed out after "
                                    f"{min(timeout_s, left):.0f}s")
    return out


def _last_onchip_evidence() -> dict | None:
    """Compact summary of the newest committed on-chip artifact
    (bench_results/onchip_*.json, written by mid-round healthy-tunnel
    runs), embedded in the bench JSON line as ``last_onchip``.

    VERDICT r3 item 1: when the driver-time capture degrades to CPU
    because the tunnel wedged, the round artifact should still carry
    the evidence trail of the most recent real-chip measurement —
    clearly labeled as a prior capture, never substituted for the
    live ``value``."""
    import glob

    from arrow_matrix_tpu.utils.artifacts import (
        is_stray_verification_artifact,
        load_last_json_line,
        record_is_onchip,
    )

    # Stray verification exhaust (onchip_*_VERIFYDRIVE.json etc.) must
    # never pass as round evidence no matter what its record says.
    paths = [p for p in
             (glob.glob(os.path.join("bench_results", "onchip_*.json"))
              + glob.glob(os.path.join("bench_cache", "onchip_*.json")))
             if not is_stray_verification_artifact(p)]
    by_mtime = []
    for p in paths:
        try:
            by_mtime.append((os.path.getmtime(p), p))
        except OSError:
            continue
    # Newest artifact whose metric matches the headline — the watcher
    # also drops ladder/planar artifacts into the same namespace, and
    # a ladder-race ms must not masquerade as the SpMM evidence trail.
    def _cfg_key(d):
        c = d.get("config") or {}
        return (c.get("n"), c.get("width"), c.get("features"))

    newest = data = None
    newest_mtime = -1.0
    k128_extra = None
    scanned = 0
    for mt, p in sorted(by_mtime, reverse=True):
        d = load_last_json_line(p)
        if d is None:
            continue
        scanned += 1
        if d.get("metric") != "spmm_iter_ms" or not d.get("value"):
            continue
        # On-chip evidence only: the watcher's stage runner writes its
        # artifact on rc=0 even when the bench inside degraded to a
        # CPU fallback (tunnel flapped mid-window) — a CPU number in
        # the onchip_* namespace must never become the "most recent
        # real-chip measurement".  The shared predicate keeps this
        # bench and the watcher agreeing on the edge cases (unlabeled
        # artifacts qualify; only an explicit label disqualifies).
        if not record_is_onchip(d):
            continue
        if newest is None:
            newest, newest_mtime, data = p, mt, d
        # The co-equal k=128 headline may live in an older artifact
        # (e.g. a fold-only rerun postdates the full race): carry the
        # newest k128 numbers alongside, labeled with their source —
        # but ONLY from a capture of the SAME problem config (a k=128
        # ms from a different n/width must not masquerade under this
        # config's evidence).
        if (d.get("k128_ms") is not None and k128_extra is None
                and newest is not None
                and _cfg_key(d) == _cfg_key(data)):
            k128_extra = {"k128_ms": d["k128_ms"],
                          "k128_err": d.get("k128_err"),
                          "from": p}
        if (newest is not None
                and (k128_extra is not None or scanned >= 10)):
            break   # bounded: stop chasing k128 through old artifacts
    if newest is None:
        return None
    if k128_extra and data.get("k128_ms") is None:
        merge = {"k128_ms": k128_extra["k128_ms"],
                 "k128_from": k128_extra["from"]}
        if k128_extra["k128_err"] is not None:
            merge["k128_err"] = k128_extra["k128_err"]
        data = dict(data, **merge)
    keep = ("metric", "value", "unit", "vs_baseline", "platform",
            "device_kind", "fmt_used", "k128_ms", "k128_err",
            "k128_from", "k128_bf16_ms",
            "frobenius_err_vs_cpu", "frobenius_gate", "achieved_gbps",
            "roofline_frac", "gather_rows_per_s", "config", "degraded")
    summary = {k: data[k] for k in keep if k in data}
    if "config" in summary and isinstance(summary["config"], dict):
        summary["config"] = {k: summary["config"][k]
                             for k in ("n", "width", "features",
                                       "iterations", "levels")
                             if k in summary["config"]}
    return {
        "note": ("most recent committed on-chip capture (prior run, "
                 "NOT this invocation's measurement)"),
        "path": newest,
        "captured_unix": int(newest_mtime),
        "summary": summary,
    }


def main() -> None:
    if len(sys.argv) == 3 and sys.argv[1] == "--variant":
        run_one_variant(sys.argv[2])
        return
    if len(sys.argv) == 3 and sys.argv[1] == "--candidate":
        run_one_candidate(sys.argv[2])
        return
    # --overlap_slabs 1,2,4: sweep the winning format over the listed
    # sub-slab counts after the race (graft-stream).  Threaded through
    # the environment so candidate subprocesses and tests share one
    # spelling (AMT_BENCH_OVERLAP_SWEEP works without the flag).
    if "--overlap_slabs" in sys.argv:
        i = sys.argv.index("--overlap_slabs")
        if i + 1 >= len(sys.argv):
            print("--overlap_slabs needs a comma list, e.g. 1,2,4",
                  file=sys.stderr)
            raise SystemExit(2)
        os.environ["AMT_BENCH_OVERLAP_SWEEP"] = sys.argv[i + 1]
    # --repl 1,2,4: sweep the winning fold format over the listed 2.5D
    # replication factors after the race (graft-repl) — same env
    # threading as the overlap sweep.
    if "--repl" in sys.argv:
        i = sys.argv.index("--repl")
        if i + 1 >= len(sys.argv):
            print("--repl needs a comma list, e.g. 1,2,4",
                  file=sys.stderr)
            raise SystemExit(2)
        os.environ["AMT_BENCH_REPL_SWEEP"] = sys.argv[i + 1]
    # Deadline alarm: the parent spends its time in subprocess waits
    # (interruptible), so SIGALRM fires reliably here even when a
    # child is wedged inside native code.  AMT_BENCH_DEADLINE=0
    # disables.
    import signal

    deadline = int(os.environ.get("AMT_BENCH_DEADLINE", 3300))
    if deadline > 0 and hasattr(signal, "SIGALRM"):
        def _on_alarm(signum, frame):
            raise TimeoutError(
                f"bench deadline ({deadline}s) exceeded — accelerator "
                f"wedged mid-run?")

        signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(deadline)
    result = {"metric": "spmm_iter_ms", "value": None, "unit": "ms",
              "vs_baseline": None}
    # EVERY phase runs under the one JSON-emitting guard: the deadline
    # alarm (or any failure) during the probe or the comparison must
    # still produce the diagnosable line.
    try:
        # AMT_BENCH_PLATFORM short-circuits the (up to 2x60s) probe
        # when the caller already knows the backend — tests and known
        # environments.  Accepts "platform" or "platform:device kind"
        # ("tpu:TPU v5 lite") — without the kind a non-CPU forced run
        # keeps the platform string as its kind, so the roofline lookup
        # still works for values like "tpu:v5e" but degrades to None
        # rather than silently misattributing a generation.
        forced = os.environ.get("AMT_BENCH_PLATFORM")
        if forced:
            platform, _, kind = forced.partition(":")
            device_kind, probe_err = kind or platform, None
        else:
            platform, device_kind, probe_err = probe_backend_laddered()
        if probe_err:
            from arrow_matrix_tpu.utils.platform import (
                classify_probe_error,
            )

            result["backend_probe_error"] = probe_err
            result["backend_probe_class"] = classify_probe_error(
                probe_err)
        # The headline race runs FIRST — a tunneled accelerator is
        # healthiest early, and a later wedge must not cost the
        # round's number.  The kernel comparison follows as
        # diagnostics inside whatever deadline remains — INCLUDING
        # after a total race failure (the per-kernel numbers are
        # exactly what diagnoses an all-candidates-failed round).
        try:
            run_bench(result, platform, device_kind)
        except Exception as e:
            result["error"] = f"{type(e).__name__}: {e}"
        # Mid-window re-probe (round-2 postmortem): a degraded start
        # must not cost the round's accelerator number if the tunnel
        # recovers while the CPU fallback ran.  The CPU result is kept
        # as a diagnostic under "degraded_cpu_run"; the upgraded race
        # runs the two fold packings only (the known-best family;
        # each is gated individually, and racing hyb/auto would not
        # fit the remaining window) — finalize() folds numbers in
        # incrementally, so even a deadline alarm mid-upgrade keeps
        # whatever was earned.
        remaining = (deadline - (time.perf_counter() - _T0)
                     if deadline else 1e9)
        if (result.get("degraded") and not forced and remaining > 600
                and os.environ.get("AMT_BENCH_REPROBE", "1") == "1"):
            platform2, kind2, _ = probe_backend(timeout_s=120.0, retries=1)
            if platform2 != "cpu":
                _progress("accelerator recovered mid-window; upgrading")
                cpu_run = {k: result.get(k)
                           for k in ("value", "vs_baseline",
                                     "scipy_cpu_ms", "fmt_used",
                                     "frobenius_err_vs_cpu")}
                upgraded = {"metric": "spmm_iter_ms", "value": None,
                            "unit": "ms", "vs_baseline": None,
                            "degraded_cpu_run": cpu_run}
                try:
                    # Candidate list threaded through the cfg, NOT the
                    # environment (ADVICE r3: a setdefault here leaked
                    # into every later _bench_config in this run).  An
                    # explicit AMT_BENCH_FMT from the caller still wins.
                    run_bench(upgraded, platform2, kind2,
                              fmt_override=os.environ.get(
                                  "AMT_BENCH_FMT", "fold,fold_tight"))
                except Exception as e:
                    upgraded.setdefault(
                        "error", f"{type(e).__name__}: {e}")
                if upgraded.get("value") is not None:
                    result.clear()
                    result.update(upgraded)
                    platform, device_kind = platform2, kind2
        _, small = _degraded_small(platform)
        remaining = deadline - (time.perf_counter() - _T0) if deadline else 1e9
        # "auto": compare only on a real accelerator — CPU variant
        # times are not chip diagnostics and cost ~15 min; "1"/"0"
        # force.
        compare = os.environ.get("AMT_BENCH_COMPARE", "auto")
        if (not small and not result.get("accelerator_wedged")
                and (compare == "1"
                     or (compare == "auto" and platform != "cpu"))
                and remaining > 360):
            try:
                kernel_compare(
                    total_budget_s=min(900.0, remaining - 60),
                    cpu=(platform == "cpu"),
                    out=result.setdefault("kernel_compare", {}))
            except Exception as e:  # diagnostics, not the gate:
                # partial numbers already collected stay in place
                result["kernel_compare"]["error"] = (
                    f"{type(e).__name__}: {e}")
    except BaseException as e:
        # A late failure (e.g. the deadline alarm during diagnostics)
        # must not discard a headline number the race already earned —
        # finalize() folds winners into `result` incrementally, so
        # whatever is there is valid and measured.
        result.setdefault("error", f"{type(e).__name__}: {e}")
    if deadline > 0 and hasattr(signal, "SIGALRM"):
        signal.alarm(0)   # the final print must not be interruptible
    # Evidence trail: always embed the newest committed on-chip
    # artifact (labeled as a PRIOR capture) — a degraded CPU round
    # still points the reader at the real-chip numbers.
    try:
        evidence = _last_onchip_evidence()
        if evidence is not None:
            result["last_onchip"] = evidence
    except Exception:
        pass   # evidence is auxiliary; never block the JSON line
    # graft-ledger: the round's headline number ALSO lands in the
    # append-only store (the single sink every measured number flows
    # through; BENCH_r*.json rounds are regenerated FROM it by
    # `graft_ledger export`).  Emission must never block the JSON line.
    try:
        from arrow_matrix_tpu.ledger import (
            bench_metric as _bench_metric,
            record as _ledger_record,
        )

        _ledger_record(
            "bench",
            _bench_metric(result.get("metric", "spmm_iter_ms"),
                          result.get("config")),
            result.get("value"), unit=result.get("unit"),
            platform=result.get("platform"),
            device_kind=result.get("device_kind"),
            knobs={"config": result.get("config", {}),
                   "fmt_used": result.get("fmt_used")},
            payload={"parsed": result})
    except Exception as e:
        print(f"[ledger] bench record not persisted: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
    print(json.dumps(result), flush=True)
    if result.get("value") is None:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
