"""End-of-round benchmark: multi-level arrow SpMM iteration time.

Measures the reference's headline quantity — wall-clock `spmm_time` per
iteration of ``X := A @ X`` through a full arrow decomposition
(reference arrow/arrow_bench.py:111-134, protocol in BASELINE.md) — on
the available accelerator at protocol scale (>=1M rows, BASELINE.md
configs), and compares against the same iterated SpMM via scipy CSR on
the host CPU (the reference's CPU kernel, SURVEY.md §2 "Device kernel
bridge").

Robustness contract (round-1 postmortem): the accelerator backend is
probed in a *subprocess with a timeout* — a hung PJRT plugin (e.g. an
unreachable TPU tunnel) must degrade to a diagnosable CPU run, not hang
or crash the bench — and exactly ONE JSON line is always printed, with
an "error" field when anything failed:

  {"metric": "spmm_iter_ms", "value": N, "unit": "ms",
   "vs_baseline": scipy_ms / device_ms, ...diagnostics}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Peak HBM bandwidth (GB/s) by TPU generation, for the bandwidth
# roofline (public figures; the iterated SpMM is bandwidth-bound: each
# iteration streams the resident blocks once).
PEAK_HBM_GBPS = {
    "v6": 1640.0,
    "v5p": 2765.0,
    "v5e": 819.0,
    "v5lite": 819.0,   # v5e reports device_kind "TPU v5 lite"
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}


def _peak_bw(device_kind: str) -> float | None:
    kind = device_kind.lower().replace(" ", "")
    for key, bw in PEAK_HBM_GBPS.items():
        if key in kind:
            return bw
    return None


def probe_backend(timeout_s: float = 60.0, retries: int = 2
                  ) -> tuple[str, str | None]:
    """Initialize-check the default JAX backend in a subprocess.

    Returns (platform, error).  On repeated failure (nonzero rc *or
    hang* — the round-1 failure mode was `jax.devices()` hanging inside
    the site-registered TPU tunnel plugin) pins ``JAX_PLATFORMS=cpu``
    in this process and reports the last error so the bench still
    produces a measurement, flagged as degraded.
    """
    code = "import jax; print(jax.devices()[0].platform)"
    err = None
    for attempt in range(retries):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=timeout_s)
            if proc.returncode == 0 and proc.stdout.strip():
                return proc.stdout.split()[-1], None
            err = (f"backend probe rc={proc.returncode}: "
                   f"{proc.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            err = (f"backend probe timed out after {timeout_s:.0f}s "
                   f"(PJRT plugin init hang)")
        if attempt < retries - 1:
            time.sleep(min(5.0 * 2 ** attempt, 30.0))
    # JAX_PLATFORMS=cpu alone does NOT stop a site-registered plugin
    # from initializing (and hanging) at the first backend access —
    # force_cpu_devices also drops the plugin's backend factory.
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices()
    return "cpu", err


def _measure(multi, x, iters: int) -> float:
    """ms/iter via chained on-device iteration (`lax.scan`) ending in a
    scalar host fetch, with the dispatch+fetch round-trip subtracted —
    block_until_ready alone can return early over remote/tunneled
    devices, a host fetch cannot."""
    def chain(n: int) -> float:
        t0 = time.perf_counter()
        xd = multi.run(x, n) if n else x
        float(np.asarray(xd[0, 0]))
        return time.perf_counter() - t0

    chain(iters)  # compile + warmup at the benchmark length
    rtt = min(chain(0) for _ in range(3))
    return max((chain(iters) - rtt) / iters, 1e-9) * 1e3


def _degraded_small(platform: str) -> tuple[bool, bool]:
    """One derivation of the degraded/small mode from a platform string
    (used by main() with the probe's answer and by run_bench with the
    live backend's — they must agree on the rule)."""
    degraded = (platform == "cpu"
                and os.environ.get("AMT_BENCH_FULL") != "1")
    small = degraded or os.environ.get("AMT_BENCH_SMALL") == "1"
    return degraded, small


def _cached_levels(n: int, m: int, width: int, seed: int,
                   max_levels: int = 4):
    """Generate+decompose once per (n, m, width, seed), then reload the
    on-disk artifact — the reference's offline/online split
    (decomposition artifacts ARE the resume point, SURVEY.md §5): a
    34s setup at n=1M becomes a sub-second reload on repeat runs."""
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
        save_decomposition,
    )
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    base = os.path.join("bench_cache",
                        f"ba_{n}_{m}_w{width}_s{seed}_L{max_levels}")
    # Completion sentinel: save_decomposition writes many files; a run
    # killed mid-write (subprocess timeouts are SIGKILL) must not leave
    # a loadable-but-truncated artifact that later runs silently
    # benchmark as a smaller problem.
    sentinel = base + ".complete"
    if os.path.exists(sentinel):
        try:
            loaded = load_decomposition(base, width, block_diagonal=True)
            widths = load_level_widths(base, width, block_diagonal=True)
            _progress(f"loaded cached decomposition {base}")
            return as_levels(loaded, widths if widths is not None else width)
        except FileNotFoundError:
            pass
    a = barabasi_albert(n, m, seed=seed)
    levels = arrow_decomposition(a, arrow_width=width,
                                 max_levels=max_levels,
                                 block_diagonal=True, seed=seed,
                                 backend="auto")
    try:
        save_decomposition(levels, base, block_diagonal=True)
        with open(sentinel, "w") as f:
            f.write(f"{len(levels)} levels\n")
    except OSError as e:  # caching is best-effort (read-only dirs etc.)
        _progress(f"decomposition cache write failed: {e}")
    return levels


def _progress(msg: str) -> None:
    """Stage markers on stderr (stdout carries only the JSON line): a
    killed/timed-out run must be diagnosable from its partial output."""
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def run_bench(result: dict) -> None:
    import jax

    # Full-f32 matmul passes: the correctness gate is parity with the
    # host CPU result (BASELINE.md north star + the accumulation-order
    # policy in utils/numerics.py); the default TPU bf16-pass matmul
    # costs ~1e-3 relative error for ~10% speed.
    jax.config.update("jax_default_matmul_precision", "highest")

    from arrow_matrix_tpu.decomposition.decompose import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense
    from arrow_matrix_tpu.utils.platform import device_memory_budget

    dev = jax.devices()[0]
    # On a CPU fallback (accelerator unreachable or absent) the point is
    # a diagnosable measurement, not protocol numbers: drop to smoke
    # scale with the cheap-to-pack ELL format so the bench finishes in
    # seconds on one host core.  AMT_BENCH_FULL=1 overrides.
    degraded, small = _degraded_small(dev.platform)
    # Protocol scale (BASELINE.md: >=1M rows, features 16, 10 iters).
    if small:
        # Degraded/diagnostic scale: large enough that the folded SELL
        # operator beats the host scipy baseline even on CPU (measured
        # 1.24x at 2^17; at the old 32k smoke scale scipy won), small
        # enough to finish in seconds.
        n, m, width, k, iters = 1 << 17, 8, 2048, 16, 5
        fmt = "fold"
    else:
        n, m, width, k, iters = 1 << 20, 8, 2048, 16, 10
        fmt = "auto"
    n = int(os.environ.get("AMT_BENCH_N", n))
    fmt = os.environ.get("AMT_BENCH_FMT", fmt)

    budget = device_memory_budget(dev)
    result["config"] = {"n": n, "width": width, "features": k,
                        "iterations": iters, "ba_neighbors": m,
                        "dense_budget_gb": round(budget / 2**30, 2)}
    result["platform"] = dev.platform
    result["device_kind"] = dev.device_kind
    if degraded:
        result["degraded"] = True

    _progress(f"platform={dev.platform} kind={dev.device_kind} n={n} fmt={fmt}")
    # max_levels high enough to converge: a capped decomposition leaves
    # a grown last level holding half the nonzeros at near-full-matrix
    # width (measured 657k-wide at n=1M with the old cap of 4), which
    # no kernel can tile well.  At 1M/BA-8 the recursion exhausts after
    # 10 levels, all at the base width.
    t0 = time.perf_counter()
    levels = _cached_levels(n, m, width, seed=7,
                            max_levels=int(os.environ.get(
                                "AMT_BENCH_LEVELS", 12)))
    result["config"]["decompose_s"] = round(time.perf_counter() - t0, 2)

    result["config"]["levels"] = len(levels)
    nnz = sum(int(l.matrix.nnz) for l in levels)
    result["config"]["edges_nnz"] = nnz

    x_host = random_dense(n, k, seed=3)

    # --- Host CPU baseline: scipy CSR through the decomposition (the
    # reference's CPU path: per-level CSRMM + permutations).
    base_iters = 3 if n > (1 << 18) else iters
    _progress(f"decomposed in {result['config']['decompose_s']}s; "
              f"scipy baseline")
    xb = x_host.copy()
    t0 = time.perf_counter()
    for _ in range(base_iters):
        xb = decomposition_spmm(levels, xb)
    scipy_ms = (time.perf_counter() - t0) / base_iters * 1e3
    want = decomposition_spmm(levels, x_host)
    tol = numerics.relative_tolerance(nnz / max(n, 1), iters=1)

    # --- Device path: race the candidate single-chip execution configs
    # at full scale and report the best.  Each candidate is gated for
    # correctness individually AND isolated against failure: a compile
    # OOM or kernel error in one format must cost only that candidate,
    # not the race (round-2 postmortem: the all-ELL layout OOM'd at
    # compile and the hyb candidate never ran).
    candidates = ([("fold", "fold"), ("hyb", "hyb"), ("auto", fmt)]
                  if fmt == "auto" else [(fmt, fmt)])
    runs = {}
    best = None
    best_multi = multi = None
    for name, f in candidates:
        _progress(f"building fmt={f}")
        try:
            t0 = time.perf_counter()
            multi = MultiLevelArrow(levels, width, mesh=None, fmt=f,
                                    dense_budget=budget)
            build_s = time.perf_counter() - t0
            x = multi.set_features(x_host)
            _progress(f"fmt={f} built in {build_s:.0f}s; compile+measure")
            dev_ms = _measure(multi, x, iters)
            err = numerics.relative_error(
                multi.gather_result(multi.step(x)), want)
            block_bytes = sum(b.device_nbytes() for b in multi.blocks)
            runs[name] = {"ms": round(dev_ms, 3), "err": err,
                          "build_s": round(build_s, 2),
                          "fmts": list(multi.fmts),
                          "block_bytes": block_bytes,
                          "total_rows": multi.total_rows}
            _progress(f"fmt={f}: {dev_ms:.2f} ms/iter err={err:.2e}")
            if (np.isfinite(err) and err <= tol
                    and (best is None or dev_ms < runs[best]["ms"])):
                best = name
                best_multi = multi   # kept for the k=128 measurement
        except Exception as e:
            runs[name] = {"error": f"{type(e).__name__}: {str(e)[:400]}"}
            _progress(f"fmt={f} FAILED: {type(e).__name__}")
        finally:
            if multi is not best_multi:
                multi = None       # free the loser before the next builds
            x = None

    result["device_runs"] = {k: {kk: vv for kk, vv in v.items()
                                 if kk != "block_bytes" and kk != "total_rows"}
                             for k, v in runs.items()}
    if best is None:
        raise RuntimeError(
            f"every config failed or missed the correctness gate: "
            f"{[(k, v.get('err', v.get('error'))) for k, v in runs.items()]}"
            f" vs {tol:.1e}")
    win = runs[best]
    dev_ms = win["ms"]
    result["config"]["fmts"] = win["fmts"]
    result["config"]["build_s"] = win["build_s"]
    result["fmt_used"] = best

    flops = 2.0 * nnz * k
    # Bandwidth roofline: one iteration streams every resident block
    # array once and reads+writes the feature array once per level
    # (+ the routing gathers, ~2 more feature passes per level beyond
    # the first).  This is the memory floor; achieved/floor bandwidth
    # against the chip's peak is the MFU analog for a bandwidth-bound
    # kernel.
    feat_bytes = win["total_rows"] * k * 4
    n_lvl = len(levels)
    bytes_per_iter = win["block_bytes"] + feat_bytes * (2 * n_lvl
                                                        + 2 * (n_lvl - 1))
    achieved_gbps = bytes_per_iter / (dev_ms * 1e-3) / 1e9
    peak = _peak_bw(dev.device_kind)

    result.update({
        "value": dev_ms,
        "vs_baseline": round(scipy_ms / dev_ms, 3),
        "scipy_cpu_ms": round(scipy_ms, 3),
        "gflops": round(flops / (dev_ms * 1e-3) / 1e9, 2),
        "frobenius_err_vs_cpu": win["err"],
        "frobenius_gate": tol,
        "bytes_per_iter_gb": round(bytes_per_iter / 2**30, 3),
        "achieved_gbps": round(achieved_gbps, 1),
        "roofline_frac": (round(achieved_gbps / peak, 3)
                          if peak else None),
    })

    # Secondary feature width (the north-star metric names 16 AND 128
    # features): re-measure the winning executor at k=128 — a gathered
    # row moves 8x the bytes for the same slot cost, so this is the
    # amortized regime.
    if k != 128 and os.environ.get("AMT_BENCH_K128", "1") == "1":
        try:
            _progress("k=128 measurement on the winner")
            x128 = best_multi.set_features(random_dense(n, 128, seed=4))
            ms128 = _measure(best_multi, x128, iters)
            result["k128_ms"] = round(ms128, 3)
            _progress(f"k=128: {ms128:.2f} ms/iter")
        except Exception as e:   # secondary metric, never the gate
            result["k128_error"] = f"{type(e).__name__}: {str(e)[:200]}"


# Ordered most-informative-first: the total budget may cut the tail,
# and the gather-family variants are cheap (small uploads, fast
# compiles) while the dense/pallas ones ship GBs of blocks — run every
# cheap one before the first expensive one.
COMPARE_VARIANTS = {
    "fold": dict(fmt="fold"),             # composed single-operator SELL
    "hyb": dict(fmt="hyb"),
    "ell": dict(fmt="ell"),               # platform-aware auto head
    # Head-stack kernel isolation: flat-COO head = scatter-add (TPU
    # scatters serialize), ELL/gell heads = gather + reduce.  The
    # spread between these is the head-kernel cost.
    "ell_headgell": dict(fmt="ell", head_fmt="gell"),
    "ell_headflat": dict(fmt="ell", head_fmt="flat"),
    "ell_headell": dict(fmt="ell", head_fmt="ell"),
    "dense": dict(fmt="dense"),
    "dense_bf16": dict(fmt="dense", dtype="bf16"),
    "pallas": dict(fmt="dense", kernel="pallas"),
    "pallas_bf16": dict(fmt="dense", kernel="pallas", dtype="bf16"),
}
COMPARE_CONFIG = dict(n=65536, m=8, width=2048, k=16, iters=10)


def run_one_variant(name: str) -> None:
    """Build + measure ONE kernel variant; prints its ms as JSON.

    Runs in a subprocess spawned by ``kernel_compare`` so that a
    pathological kernel (e.g. a Mosaic compile that never returns — a
    hang SIGALRM cannot interrupt inside native code) costs its own
    timeout, not the whole bench.  ``AMT_BENCH_CPU=1`` pins the child
    to the host CPU (JAX_PLATFORMS alone cannot stop a site-registered
    TPU plugin from initializing) — for testing the variants without an
    accelerator."""
    if os.environ.get("AMT_BENCH_CPU") == "1":
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()
    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils.graphs import random_dense

    c = COMPARE_CONFIG
    levels = _cached_levels(c["n"], c["m"], c["width"], seed=7,
                            max_levels=2)
    x_host = random_dense(c["n"], c["k"], seed=3)
    multi = MultiLevelArrow(levels, c["width"], mesh=None,
                            **COMPARE_VARIANTS[name])
    x = multi.set_features(x_host)
    print(json.dumps({"ms": round(_measure(multi, x, c["iters"]), 3)}),
          flush=True)


def kernel_compare(timeout_s: float = 300.0,
                   total_budget_s: float = 900.0) -> dict:
    """ms/iter of the ELL / dense / Pallas / bf16 block kernels on one
    mid-size config (dense must fit): the data for VERDICT r1 item 6
    (integrate Pallas or retire it with numbers).  One subprocess per
    variant, each with a hard timeout; a total budget stops the sweep
    early if the device starts wedging (comparison is diagnostics — it
    must never eat the bench's own time)."""
    out = {"config": dict(COMPARE_CONFIG)}
    t_start = time.perf_counter()
    for name in COMPARE_VARIANTS:
        if time.perf_counter() - t_start > total_budget_s:
            out[name + "_ms"] = None
            out[name + "_error"] = "compare budget exhausted"
            continue
        _progress(f"kernel variant {name}")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--variant", name],
                capture_output=True, text=True, timeout=timeout_s)
            if proc.returncode == 0 and proc.stdout.strip():
                out[name + "_ms"] = json.loads(
                    proc.stdout.strip().splitlines()[-1])["ms"]
            else:
                out[name + "_ms"] = None
                out[name + "_error"] = (f"rc={proc.returncode}: "
                                        f"{proc.stderr.strip()[-300:]}")
        except subprocess.TimeoutExpired:
            out[name + "_ms"] = None
            out[name + "_error"] = f"timed out after {timeout_s:.0f}s"
    return out


def main() -> None:
    if len(sys.argv) == 3 and sys.argv[1] == "--variant":
        run_one_variant(sys.argv[2])
        return
    # Deadline alarm: a HALF-healthy tunnel (probe passes, a later
    # compile/dispatch wedges) would otherwise hang the parent past the
    # driver's timeout with no JSON emitted.  SIGALRM raises at the
    # next Python bytecode boundary — enough for RPC-polling hangs —
    # and the BaseException handler below still prints the diagnosable
    # line.  AMT_BENCH_DEADLINE=0 disables.
    import signal

    deadline = int(os.environ.get("AMT_BENCH_DEADLINE", 3300))
    if deadline > 0 and hasattr(signal, "SIGALRM"):
        def _on_alarm(signum, frame):
            raise TimeoutError(
                f"bench deadline ({deadline}s) exceeded — accelerator "
                f"wedged mid-run?")

        signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(deadline)
    result = {"metric": "spmm_iter_ms", "value": None, "unit": "ms",
              "vs_baseline": None}
    # EVERY phase runs under the one JSON-emitting guard: the deadline
    # alarm (or any failure) during the probe or the comparison must
    # still produce the diagnosable line.
    try:
        platform, probe_err = probe_backend()
        if probe_err:
            result["backend_probe_error"] = probe_err
        # Kernel comparison runs FIRST, before this process initializes
        # the accelerator backend: each variant subprocess needs the
        # chip to itself (TPU ownership is exclusive per process), so
        # the parent must not be holding it yet.
        _, small = _degraded_small(platform)
        if not small and os.environ.get("AMT_BENCH_COMPARE", "1") == "1":
            try:
                result["kernel_compare"] = kernel_compare()
            except Exception as e:  # diagnostics, not the gate
                result["kernel_compare"] = {
                    "error": f"{type(e).__name__}: {e}"}
        run_bench(result)
    except BaseException as e:
        result["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
        raise SystemExit(1)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
