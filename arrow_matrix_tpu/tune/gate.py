"""Plan-cache gate checks (graft-tune; wrapped by tools/tune_gate.py
and the ``graft_tune check`` subcommand).

A cached plan is a *promise* — "this configuration was bit-identical
to the golden and at least as fast as the default on this structure".
The gate replays the promise and exits nonzero when it no longer
holds:

* **hash integrity** — re-fingerprinting the plan's recorded source
  must reproduce the file's structure hash (catches fingerprint
  drift, artifact edits, and version skew);
* **cache purity** — a ``search()`` on the unchanged structure must
  be a pure cache hit with ZERO bench children spawned (the
  acceptance property of ISSUE 10);
* **bit-identity replay** — the tuned executor's f32 output must
  still equal the golden ``ops/sell.py`` fold path bit-for-bit;
* **no regression** — the tuned configuration must not be more than
  ``rel_tol`` (default 5%) slower than the default on a
  min-of-``repeats`` replay, with a small absolute slack so
  sub-millisecond CPU timing noise cannot fail a healthy plan.

``--refresh`` re-searches (``search(refresh=True)``) before checking.
"""

from __future__ import annotations

import glob
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from arrow_matrix_tpu.tune.fingerprint import (
    fingerprint_hash,
    structure_fingerprint,
)
from arrow_matrix_tpu.tune.plan import (
    PLAN_VERSION,
    TunePlan,
    load_plan_file,
    plan_dir,
)
from arrow_matrix_tpu.tune.search import (
    GOLDEN_SEED,
    _build_executor,
    load_levels_from_source,
    search,
)
from arrow_matrix_tpu.tune.space import Candidate


def _measure_min(multi, x, iters: int, repeats: int) -> float:
    from arrow_matrix_tpu.obs import chained_iteration_ms

    return min(chained_iteration_ms(multi.run, x, iters)
               for _ in range(max(repeats, 1)))


def check_structure(source: dict, *, directory: Optional[str] = None,
                    iters: int = 3, repeats: int = 3,
                    rel_tol: float = 0.05, abs_tol_ms: float = 0.25,
                    refresh: bool = False, timing: bool = True,
                    quiet: bool = False) -> dict:
    """Run every gate check for one structure's plan file.

    Returns ``{"ok", "structure_hash", "failures": [...],
    "checks": [...]}`` — ``failures`` is empty iff the plan's promise
    still holds for every cached k.
    """
    from arrow_matrix_tpu.utils.graphs import random_dense

    failures: List[str] = []
    checks: List[str] = []

    def say(msg: str) -> None:
        if not quiet:
            print(f"[tune-gate] {msg}", file=sys.stderr, flush=True)

    levels, width = load_levels_from_source(source)
    fp = structure_fingerprint(levels, width)
    h = fingerprint_hash(fp)
    say(f"structure {h}")

    record = load_plan_file(h, directory)
    if record is None:
        return {"ok": False, "structure_hash": h,
                "failures": [f"no plan file for {h} in "
                             f"{plan_dir(directory)!r}"],
                "checks": checks}
    if record.get("structure_hash") != h:
        failures.append(
            f"hash drift: file says {record.get('structure_hash')}, "
            f"re-fingerprint says {h}")
    if int(record.get("version", -1)) != PLAN_VERSION:
        failures.append(f"version skew: file v{record.get('version')} "
                        f"vs runtime v{PLAN_VERSION}")
    if failures:
        return {"ok": False, "structure_hash": h,
                "failures": failures, "checks": checks}
    checks.append("hash+version")

    ks = sorted(int(s) for s in (record.get("plans") or {}))
    if not ks:
        return {"ok": False, "structure_hash": h,
                "failures": ["plan file has no entries"],
                "checks": checks}

    if refresh:
        for k in ks:
            say(f"refresh: re-searching k={k}")
            p, rep = search(source, k, iters=iters, plan_dir=directory,
                            refresh=True, quiet=quiet)
            if p is None:
                failures.append(f"refresh search failed for k={k}: "
                                f"{rep.get('error')}")
        if failures:
            return {"ok": False, "structure_hash": h,
                    "failures": failures, "checks": checks}
        record = load_plan_file(h, directory)
        checks.append("refresh")

    default_multi = _build_executor(levels, width, Candidate("default"))
    for k in ks:
        plan = TunePlan.from_dict(record["plans"][str(k)])

        # Cache purity: an unchanged structure must hit, spawning
        # nothing.
        _, rep = search(source, k, plan_dir=directory, quiet=True)
        if not rep.get("cache_hit") or rep.get("children_spawned"):
            failures.append(
                f"k={k}: second search was not a pure cache hit "
                f"(cache_hit={rep.get('cache_hit')}, "
                f"children={rep.get('children_spawned')})")
        else:
            checks.append(f"k={k}:cache-purity")

        # Bit-identity replay vs the golden ops/sell.py path.
        x_host = random_dense(fp["n"], k, seed=GOLDEN_SEED)
        xd = default_multi.set_features(x_host)
        golden = np.asarray(
            default_multi.gather_result(default_multi.step(xd)),
            dtype=np.float32)
        tuned = _build_executor(
            levels, width,
            Candidate(plan.candidate, build=plan.build_kwargs(),
                      kernel_opts=plan.kernel_opts()))
        xt = tuned.set_features(x_host)
        mine = np.asarray(tuned.gather_result(tuned.step(xt)),
                          dtype=np.float32)
        if plan.bit_identical and not np.array_equal(mine, golden):
            failures.append(f"k={k}: plan {plan.candidate!r} lost "
                            f"bit-identity vs the golden fold path")
        else:
            checks.append(f"k={k}:bit-identity")

        # Regression replay: min-of-N, relative + absolute slack.
        if timing:
            d_ms = _measure_min(default_multi, xd, iters, repeats)
            t_ms = _measure_min(tuned, xt, iters, repeats)
            limit = d_ms * (1.0 + rel_tol) + abs_tol_ms
            say(f"k={k}: tuned {t_ms:.3f} ms vs default {d_ms:.3f} ms "
                f"(limit {limit:.3f})")
            if t_ms > limit:
                failures.append(
                    f"k={k}: tuned plan regressed: {t_ms:.3f} ms vs "
                    f"default {d_ms:.3f} ms (>{rel_tol:.0%} + "
                    f"{abs_tol_ms} ms slack)")
            else:
                checks.append(f"k={k}:no-regression")

    return {"ok": not failures, "structure_hash": h,
            "failures": failures, "checks": checks}


def gate_sources(directory: Optional[str] = None) -> Dict[str, dict]:
    """Every checkable plan file in the cache: hash -> recorded
    source (plans whose file carries no ``context.source`` cannot be
    replayed and are reported as failures by ``run_gate``)."""
    out: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(plan_dir(directory),
                                              "*.json"))):
        h = os.path.splitext(os.path.basename(path))[0]
        record = load_plan_file(h, directory)
        src = ((record or {}).get("context") or {}).get("source")
        out[h] = src
    return out


def run_gate(*, directory: Optional[str] = None,
             hashes: Optional[List[str]] = None,
             iters: int = 3, repeats: int = 3, rel_tol: float = 0.05,
             abs_tol_ms: float = 0.25, refresh: bool = False,
             timing: bool = True, quiet: bool = False) -> int:
    """Gate every (or the selected) cached plan; returns the process
    exit code (0 = every promise holds)."""
    sources = gate_sources(directory)
    if hashes:
        sources = {h: sources.get(h) for h in hashes}
    if not sources:
        print(f"tune-gate: no plan files in {plan_dir(directory)!r}",
              file=sys.stderr)
        return 1
    rc = 0
    for h, src in sources.items():
        if src is None:
            print(f"tune-gate FAIL {h}: plan file missing or has no "
                  f"replayable context.source", file=sys.stderr)
            rc = 1
            continue
        try:
            res = check_structure(src, directory=directory,
                                  iters=iters, repeats=repeats,
                                  rel_tol=rel_tol,
                                  abs_tol_ms=abs_tol_ms,
                                  refresh=refresh, timing=timing,
                                  quiet=quiet)
        except Exception as e:  # noqa: BLE001 — one structure's
            # missing/corrupt artifacts must not mask the others.
            print(f"tune-gate FAIL {h}: source not replayable: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            rc = 1
            continue
        if res["ok"]:
            print(f"tune-gate OK {h}: {', '.join(res['checks'])}")
        else:
            rc = 1
            for f in res["failures"]:
                print(f"tune-gate FAIL {h}: {f}", file=sys.stderr)
    return rc
