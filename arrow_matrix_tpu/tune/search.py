"""The autotune search loop (graft-tune).

``search()`` closes the loop the ISSUE-10 tentpole names: fingerprint
the structure (``tune/fingerprint.py``), short-circuit on a cached
plan (a second search of an unchanged graph spawns ZERO bench
children — the property ``tools/tune_gate.py`` verifies), otherwise
enumerate + prune the candidate space (``tune/space.py``), race the
survivors in subprocess-isolated children exactly the way ``bench.py``
races formats — each candidate in its own timeout-guarded process
with the flight recorder installed — and persist the winner as a
versioned :class:`~arrow_matrix_tpu.tune.plan.TunePlan`.

Eligibility is per traffic class (graft-classes).  For the default
``exact`` class a candidate may only WIN if its full-precision output
is bit-identical (``np.array_equal``, f32) to the golden
``ops/sell.py`` fold path — computed once in the parent as the default
executor's ``gather_result(step(x))`` on a seeded input, in original
row order.  The default configuration is itself always raced (and is
trivially bit-identical), so a winner always exists; candidates that
lose bit-identity (or are dtype experiments) are still timed and
recorded as diagnostics in the report.  For ``traffic_class="approx"``
a reduced-precision candidate may also win when its measured
single-step rel-Frobenius error is within the class tolerance
(``arrow_matrix_tpu/classes.py``) — and before such a winner is
persisted, its full error-vs-iteration curve is probed
(``ledger/probe.py``) and must certify (every point within tolerance);
the resulting certificate rides in the TunePlan.

Children are real subprocesses on purpose: a wedged compile or a
device grab costs ONE candidate its timeout, never the search; a
killed child leaves its flight-recorder ring behind
(``bench_cache/flight/tune_<candidate>.json``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from arrow_matrix_tpu.tune.fingerprint import (
    fingerprint_hash,
    structure_fingerprint,
)
from arrow_matrix_tpu.tune.plan import (
    PLAN_VERSION,
    TunePlan,
    load_plan,
    save_plans,
)
from arrow_matrix_tpu.tune.space import Candidate, enumerate_candidates

#: Seed of the deterministic bit-identity input (shared parent/child).
GOLDEN_SEED = 3


def load_levels_from_source(source: dict):
    """Rebuild the decomposition a child (or the parent) searches
    over.  Two source kinds:

    * ``{"kind": "ba", "n", "m", "width", "seed", "max_levels"}`` —
      regenerate a Barabasi-Albert graph and decompose it (both fully
      seeded, so every process sees the identical structure);
    * ``{"kind": "dir", "base", "width"}`` — load a committed
      ``io/graphio.py`` artifact directory (the two bench_cache
      graphs ship with checked-in plans).

    Returns ``(levels, width)``.
    """
    kind = source.get("kind")
    if kind == "ba":
        from arrow_matrix_tpu.decomposition import arrow_decomposition
        from arrow_matrix_tpu.utils import barabasi_albert

        a = barabasi_albert(int(source["n"]), int(source.get("m", 3)),
                            seed=int(source["seed"]))
        width = int(source["width"])
        levels = arrow_decomposition(
            a, width, max_levels=int(source.get("max_levels", 10)),
            block_diagonal=True, seed=int(source["seed"]))
        return levels, width
    if kind == "dir":
        from arrow_matrix_tpu.io.graphio import (
            as_levels,
            load_decomposition,
            load_level_widths,
        )

        base = source["base"]
        width = source.get("width")
        loaded = load_decomposition(base, width, block_diagonal=True)
        widths = load_level_widths(base, width, len(loaded))
        levels = as_levels(loaded, widths)
        return levels, int(np.max(np.asarray(widths)))
    raise ValueError(f"unknown levels source kind {kind!r}")


def _build_executor(levels, width: int, cand: Candidate):
    """One candidate's executor over already-loaded levels (single
    chip — the tuned path is the fold/serve path, mesh=None)."""
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    kwargs: Dict[str, Any] = {"fmt": "fold"}
    kwargs.update(cand.build)
    return MultiLevelArrow(levels, width, mesh=None,
                           kernel_opts=dict(cand.kernel_opts) or None,
                           **kwargs)


def _golden_output(levels, width: int, x_host: np.ndarray) -> np.ndarray:
    """The golden: the DEFAULT fold executor — the ``ops/sell.py``
    ``sell_spmm_t`` path — stepped once, gathered back to original row
    order, f32."""
    multi = _build_executor(levels, width, Candidate("default"))
    x = multi.set_features(x_host)
    return np.asarray(multi.gather_result(multi.step(x)),
                      dtype=np.float32)


def _flight_install(name: str) -> None:
    """Best-effort black-box recorder in a tune child (bench.py's
    ``_install_flight`` contract: a SIGKILLed child still leaves its
    last-known state on disk)."""
    try:
        from arrow_matrix_tpu.obs import flight

        path = os.path.join(
            os.environ.get("AMT_FLIGHT_DIR",
                           os.path.join("bench_cache", "flight")),
            f"{name}.json")
        flight.install(path)
    except Exception as e:  # noqa: BLE001 — never cost the measurement
        print(f"[tune] flight recorder unavailable: "
              f"{type(e).__name__}: {e}", file=sys.stderr)


def candidate_child_main(cfg: dict) -> dict:
    """Body of one candidate subprocess (``python -m
    arrow_matrix_tpu.tune --candidate <name>``): build, verify
    bit-identity vs the parent's golden artifact, measure ms/iter.
    Prints nothing itself — the caller emits the returned dict as the
    final JSON line (``utils/artifacts.parse_last_json_line`` contract).
    """
    if (os.environ.get("AMT_BENCH_FORCECPU") == "1"
            or os.environ.get("AMT_BENCH_CPU") == "1"):
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()
    name = cfg["candidate"]["name"]
    _flight_install(f"tune_{name}")
    from arrow_matrix_tpu.obs import chained_iteration_ms
    from arrow_matrix_tpu.utils.graphs import random_dense

    cand = Candidate(name, build=cfg["candidate"].get("build") or {},
                     kernel_opts=cfg["candidate"].get("kernel_opts")
                     or {})
    levels, width = load_levels_from_source(cfg["source"])
    multi = _build_executor(levels, width, cand)
    k = int(cfg["k"])
    x_host = random_dense(multi.n, k, seed=GOLDEN_SEED)
    x = multi.set_features(x_host)

    bit_identical = None
    rel_frobenius = None
    golden_path = cfg.get("golden_path")
    if golden_path:
        golden = np.load(golden_path)
        mine = np.asarray(multi.gather_result(multi.step(x)),
                          dtype=np.float32)
        bit_identical = bool(np.array_equal(mine, golden))
        # Single-step rel-Frobenius vs the golden: the approx-class
        # eligibility screen (the full curve certifies the winner).
        gn = float(np.linalg.norm(golden.astype(np.float64)))
        diff = float(np.linalg.norm(mine.astype(np.float64)
                                    - golden.astype(np.float64)))
        rel_frobenius = diff / gn if gn > 0 else diff

    ms = chained_iteration_ms(multi.run, x, int(cfg.get("iters", 3)))
    return {"name": name, "ms": round(float(ms), 4),
            "bit_identical": bit_identical,
            "rel_frobenius": rel_frobenius}


def _spawn_tune_candidate(cand: Candidate, cfg: dict,
                          timeout_s: float, platform: str) -> dict:
    """One candidate subprocess -> its parsed JSON (or an error dict);
    every failure shape is contained to the returned dict, the
    ``bench.py _spawn_candidate`` contract."""
    from arrow_matrix_tpu.utils.artifacts import parse_last_json_line

    child_cfg = dict(cfg)
    child_cfg["candidate"] = {"name": cand.name, "build": cand.build,
                              "kernel_opts": cand.kernel_opts}
    env = dict(os.environ, AMT_TUNE_CFG=json.dumps(child_cfg))
    if platform == "cpu":
        env["AMT_BENCH_FORCECPU"] = "1"
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.abspath(os.path.join("bench_cache",
                                                "xla_cache")))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "arrow_matrix_tpu.tune",
             "--candidate", cand.name],
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        err: Dict[str, Any] = {"name": cand.name,
                               "error": f"timed out after "
                                        f"{timeout_s:.0f}s",
                               "timed_out": True}
        fp = os.path.join(
            os.environ.get("AMT_FLIGHT_DIR",
                           os.path.join("bench_cache", "flight")),
            f"tune_{cand.name}.json")
        if os.path.exists(fp):
            err["flight"] = fp
        return err
    if proc.returncode != 0 or not proc.stdout.strip():
        return {"name": cand.name,
                "error": f"rc={proc.returncode}: "
                         f"{proc.stderr.strip()[-400:]}"}
    rec = parse_last_json_line(proc.stdout)
    if rec is None:
        return {"name": cand.name,
                "error": f"unusable child output: "
                         f"{proc.stdout.strip()[-200:]}"}
    return rec


def _certify_candidate(source: dict, dtype: str, k: int,
                       ledger_dir: Optional[str], say) :
    """Probe the full error-vs-iteration curve for one carriage dtype
    and derive its :class:`~arrow_matrix_tpu.classes.Certificate`
    (recorded in the ledger when one is configured); None when the
    probe fails."""
    from arrow_matrix_tpu.classes import certificate_from_record
    from arrow_matrix_tpu.ledger.probe import error_curves_for_source

    try:
        ledger = None
        if ledger_dir is not None:
            from arrow_matrix_tpu.ledger.store import Ledger

            ledger = Ledger(ledger_dir)
        recs = error_curves_for_source(source, k=int(k),
                                       dtypes=(dtype,), ledger=ledger)
        return certificate_from_record(recs[0])
    except Exception as e:  # noqa: BLE001 — a failed probe fails the
        say(f"certificate probe failed: {type(e).__name__}: {e}")
        return None         # candidate, never the search


def _plan_from_candidate(cand: Candidate, h: str, k: int) -> TunePlan:
    """Fold a candidate's overrides over the default knob set."""
    base = TunePlan(structure_hash=h, k=int(k)).to_dict()
    base.update({kk: v for kk, v in cand.build.items()})
    base.update({kk: v for kk, v in cand.kernel_opts.items()})
    base["candidate"] = cand.name
    return TunePlan.from_dict(base)


def search(source: dict, k: int, *, iters: int = 3,
           timeout_s: float = 240.0, dtype=np.float32,
           plan_dir: Optional[str] = None, refresh: bool = False,
           allow_int8: bool = False,
           restrict: Optional[List[str]] = None,
           run_dir: Optional[str] = None,
           ledger_dir: Optional[str] = None,
           traffic_class: str = "exact",
           extra: Optional[List[Candidate]] = None,
           lens_model=None,
           synth: bool = False,
           quiet: bool = False) -> Tuple[Optional[TunePlan], dict]:
    """Search (or cache-hit) the tuned plan for one (structure, k).

    Returns ``(plan, report)``.  ``report["cache_hit"]`` /
    ``report["children_spawned"]`` are the gate's purity evidence: an
    unchanged graph's second search is a pure cache hit with zero
    children.  ``refresh=True`` forces a re-search.  ``ledger_dir``
    redirects the winner's graft-ledger record (smoke runs pass a
    run-dir-local store).

    ``traffic_class="approx"`` admits tolerance-gated reduced-precision
    winners (module docstring); the cached plan records the class, so
    an exact consumer never silently inherits an approx plan
    (``load_plan`` keys on k within one structure file — approx
    searches should use a distinct ``plan_dir`` or consume the plan
    object directly, as ``serve/scheduler.ArrowServer`` does).

    ``extra`` forwards caller-supplied candidates (generated
    programs) to ``enumerate_candidates``; pallas extras must pass
    graft-kcert certification there or they are pruned with zero
    children spawned.

    ``lens_model`` (an ``obs.costmodel.CostModel``, or a path to its
    JSON artifact) arms the graft-lens compute screen in
    ``enumerate_candidates``: compute-hopeless candidates are pruned
    with ``"lens: …"`` reasons before their child spawns.

    ``synth=True`` arms graft-synth: per-level schedules derived from
    the degree-ladder fingerprint (``tune/synth.synth_candidates``)
    join the race through ``extra`` — same kcert/lens screens, same
    f32 bit-identity win rule — and the surviving generated program is
    persisted in the synth store so graft-kcert certifies it in every
    later process.  A cache hit still short-circuits BEFORE synthesis:
    purity (zero children) covers generated programs too.
    """
    from arrow_matrix_tpu.classes import tolerance_for
    from arrow_matrix_tpu.utils.platform import host_load

    def _say(msg: str) -> None:
        if not quiet:
            print(f"[graft-tune] {msg}", file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    levels, width = load_levels_from_source(source)
    fp = structure_fingerprint(levels, width, dtype=dtype)
    h = fingerprint_hash(fp)
    _say(f"structure {h} (n={fp['n']}, total_rows={fp['total_rows']}, "
         f"{len(fp['ladder']['rows'])} tiers)")

    if not refresh:
        cached = load_plan(h, k, plan_dir, quiet=True)
        if cached is not None and cached.traffic_class != traffic_class:
            _say(f"cached plan is {cached.traffic_class!r}, search "
                 f"wants {traffic_class!r}: re-searching")
            cached = None
        if cached is not None:
            _say(f"cache HIT for k={k}: candidate "
                 f"{cached.candidate!r} ({cached.measured_ms} ms, "
                 f"margin {cached.margin})")
            return cached, {
                "structure_hash": h, "k": int(k), "cache_hit": True,
                "children_spawned": 0,
                "lookup_ms": round((time.perf_counter() - t0) * 1e3, 3),
                "plan": cached.to_dict(),
            }

    platform = "cpu"
    try:
        import jax

        platform = jax.devices()[0].platform
    except (ImportError, RuntimeError):  # searchable without a device
        pass
    evaluator = "cpu-interpret" if platform == "cpu" else platform

    if isinstance(lens_model, (str, os.PathLike)):
        import json as _json

        from arrow_matrix_tpu.obs.costmodel import CostModel
        with open(lens_model, "r", encoding="utf-8") as fh:
            lens_model = CostModel.from_dict(_json.load(fh))
    if synth:
        from arrow_matrix_tpu.tune import synth as _synth

        generated = _synth.synth_candidates(fp,
                                            traffic_class=traffic_class)
        if generated:
            _say(f"synth: {len(generated)} generated candidate(s): "
                 + "; ".join(f"{c.name} [{_synth.schedule_summary(c.kernel_opts['schedule'])}]"
                             for c in generated))
            extra = list(extra or []) + generated
    cands, pruned = enumerate_candidates(
        fp, k, platform=platform, allow_int8=allow_int8,
        restrict=restrict, traffic_class=traffic_class, extra=extra,
        lens_model=lens_model)
    for name, why in pruned.items():
        _say(f"pruned {name}: {why}")

    synth_program = None
    if synth:
        # Persist + register the generated exact program ONLY when it
        # survived the kcert/lens screens — the committed store must
        # hold nothing `analysis kernels --check` would flag.
        for c in cands:
            if c.name == "synth_ladder":
                synth_program = _synth.persist_program(
                    fp, h, k, c.kernel_opts["schedule"])
                _say(f"synth: persisted generated program "
                     f"{synth_program}")
                break

    run_dir = run_dir or os.path.join("bench_cache", "tune_runs", h)
    os.makedirs(run_dir, exist_ok=True)
    golden_path = os.path.join(run_dir, f"golden_k{int(k)}.npy")
    from arrow_matrix_tpu.utils.graphs import random_dense

    x_host = random_dense(fp["n"], int(k), seed=GOLDEN_SEED)
    np.save(golden_path, _golden_output(levels, width, x_host))

    cfg = {"source": source, "k": int(k), "iters": int(iters),
           "golden_path": os.path.abspath(golden_path)}
    results: Dict[str, dict] = {}
    for cand in cands:
        _say(f"racing {cand.name}")
        results[cand.name] = _spawn_tune_candidate(
            cand, cfg, timeout_s, platform)
        r = results[cand.name]
        _say(f"  {cand.name}: ms={r.get('ms')} "
             f"bit_identical={r.get('bit_identical')} "
             f"err={r.get('error')}")

    default_ms = results.get("default", {}).get("ms")

    def _effective_dtype(c: Candidate) -> Optional[str]:
        """The accuracy-class key of a candidate's carriage: build or
        kernel_opts ``feature_dtype``, or — for a graft-synth per-level
        schedule — the NARROWEST per-tier carriage (the whole output
        is only as exact as its least exact tier)."""
        fd = (c.build.get("feature_dtype")
              or c.kernel_opts.get("feature_dtype"))
        if fd is None and c.kernel_opts.get("schedule"):
            carrs = {e.get("carriage", "f32")
                     for e in c.kernel_opts["schedule"]}
            for narrow in ("int8", "bf16"):
                if narrow in carrs:
                    return narrow
        return fd

    def _class_ok(c: Candidate) -> bool:
        r = results[c.name]
        if (r.get("error") is not None or r.get("ms") is None):
            return False
        if r.get("bit_identical") is True:
            return True
        if traffic_class != "approx":
            return False
        # Approx class: a reduced-precision candidate passes the
        # screen when its single-step error is within the class
        # tolerance; the full curve still has to certify below.
        fd = _effective_dtype(c)
        rel = r.get("rel_frobenius")
        return (fd is not None and rel is not None
                and rel <= tolerance_for(fd))

    eligible = [c for c in cands if c.eligible and _class_ok(c)]
    certificate = None
    winner = None
    while eligible:
        pick = min(eligible, key=lambda c: results[c.name]["ms"])
        fd = _effective_dtype(pick)
        if (traffic_class != "approx" or fd is None
                or results[pick.name].get("bit_identical") is True):
            winner = pick
            break
        # Reduced-precision approx winner: probe the full
        # error-vs-iteration curve before persisting — the curve IS
        # the certificate a serve-time admission decision trusts.
        cert = _certify_candidate(source, fd, k, ledger_dir, _say)
        if cert is not None and cert.covers(cert.iterations):
            winner, certificate = pick, cert
            break
        _say(f"{pick.name}: curve failed to certify "
             f"(tolerance {tolerance_for(fd)}) — dropping candidate")
        eligible.remove(pick)
    if winner is None:
        _say("no eligible candidate (default failed?) — no plan saved")
        return None, {
            "structure_hash": h, "k": int(k), "cache_hit": False,
            "children_spawned": len(cands), "results": results,
            "pruned": pruned, "error": "no eligible candidate",
            "synth_program": synth_program,
        }
    w_ms = float(results[winner.name]["ms"])
    margin = (None if not default_ms
              else round((float(default_ms) - w_ms) / float(default_ms),
                         4))
    plan = _plan_from_candidate(winner, h, k)
    plan = TunePlan.from_dict({
        **plan.to_dict(),
        "measured_ms": w_ms,
        "default_ms": default_ms,
        "margin": margin,
        "bit_identical":
            results[winner.name].get("bit_identical") is True,
        "host_load": host_load(),
        "platform": platform,
        "evaluator": evaluator,
        "created_unix": round(time.time(), 3),
        "traffic_class": traffic_class,
        "certificate": certificate.to_dict() if certificate else None,
    })
    path = save_plans(h, {int(k): plan}, fingerprint=fp,
                      directory=plan_dir,
                      context={"source": source, "iters": int(iters)})
    _say(f"winner {winner.name!r}: {w_ms} ms vs default {default_ms} "
         f"(margin {margin}); saved {path}")
    # graft-ledger: the winner + margin also land in the append-only
    # store, keyed by the same structure hash as the plan cache.
    try:
        from arrow_matrix_tpu.ledger import record as _ledger_record

        _ledger_record(
            "tune", f"tuned_spmm_ms_k{int(k)}", w_ms, unit="ms",
            directory=ledger_dir,
            structure_hash=h, platform=platform,
            device_kind="host" if platform == "cpu" else platform,
            host_load=plan.host_load.get("loadavg_1m")
            if isinstance(plan.host_load, dict) else None,
            knobs={"k": int(k), "candidate": winner.name,
                   "kernel": plan.kernel, "fmt": plan.fmt,
                   "chunk": plan.chunk,
                   "overlap_slabs": plan.overlap_slabs,
                   "feature_dtype": plan.feature_dtype,
                   "traffic_class": traffic_class},
            payload={"default_ms": default_ms, "margin": margin,
                     "bit_identical": plan.bit_identical,
                     "evaluator": evaluator,
                     "source": source, "plan_path": path})
    except Exception as e:
        _say(f"ledger record not persisted: {type(e).__name__}: {e}")
    return plan, {
        "structure_hash": h, "k": int(k), "cache_hit": False,
        "children_spawned": len(cands), "results": results,
        "pruned": pruned, "winner": winner.name,
        "plan": plan.to_dict(), "plan_path": path,
        "synth_program": synth_program,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def smoke_tune(run_dir: str, *, n: int = 96, width: int = 16,
               seed: int = 3, k: int = 8, iters: int = 2,
               timeout_s: float = 180.0,
               plan_dir: Optional[str] = None,
               restrict: Optional[List[str]] = None,
               quiet: bool = True) -> dict:
    """One tiny end-to-end search on a seeded BA graph — the
    amt_doctor TUNE probe and the tier-1 tests ride this (3 children,
    host CPU).  Returns the search report with the plan embedded."""
    if plan_dir is None:
        plan_dir = os.path.join(run_dir, "tune_plans")
    if restrict is None:
        restrict = ["default", "fold_tight", "chunk_4096"]
    source = {"kind": "ba", "n": int(n), "m": 3, "width": int(width),
              "seed": int(seed), "max_levels": 4}
    plan, report = search(source, k, iters=iters, timeout_s=timeout_s,
                          plan_dir=plan_dir, restrict=restrict,
                          run_dir=os.path.join(run_dir, "tune_runs"),
                          ledger_dir=os.path.join(run_dir, "ledger"),
                          quiet=quiet)
    report["plan_version"] = PLAN_VERSION
    report["ok"] = plan is not None
    return report
