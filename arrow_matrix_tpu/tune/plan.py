"""Versioned, persisted tuning plans (graft-tune).

A :class:`TunePlan` is ONE planned configuration: every knob the
executors previously took as five independent arguments — format /
tier split, kernel choice, chunking, carriage dtype, overlap ``S``,
replication ``c``, and the fused kernel's ``row_block`` / ``wave`` /
``smem_cols_budget`` / ``ring`` — plus the provenance that justifies
it (measured ms vs the default, margin, bit-identity verdict,
host-load context, evaluator platform).

Plans persist as one JSON file per structure hash under
``bench_cache/tune_plans/`` (override: ``AMT_TUNE_PLAN_DIR``), with
per-feature-width entries::

    {"version": 1, "structure_hash": "...",
     "fingerprint": {...}, "plans": {"16": {...}, "128": {...}}}

Consumption contract (wired through ``MultiLevelArrow`` /
``SellSlim`` / ``SellMultiLevel`` ``plan="auto"`` and
``serve/scheduler.ArrowServer``): a cache hit applies the knobs with
ZERO search cost; a miss or a version skew falls back to the built-in
defaults LOUDLY — a :class:`TunePlanMiss` warning, never silence —
so an operator can tell a tuned run from an untuned one.
"""

from __future__ import annotations

import dataclasses
import json
import os
import warnings
from dataclasses import dataclass
from typing import Any, Dict, Optional

from arrow_matrix_tpu.utils.artifacts import (
    atomic_write_json,
    locked_file,
)

#: Bump when the TunePlan schema or knob semantics change; a cached
#: plan from another version is a loud miss, never a silent apply.
PLAN_VERSION = 1

DEFAULT_PLAN_DIR = os.path.join("bench_cache", "tune_plans")


class TunePlanMiss(UserWarning):
    """Raised-as-warning when ``plan="auto"`` finds no usable cached
    plan (no file, no entry for the requested k, or version skew) —
    the executor proceeds on defaults, loudly."""


def plan_dir(override: Optional[str] = None) -> str:
    """The plan-cache directory: explicit override, else
    ``AMT_TUNE_PLAN_DIR``, else ``bench_cache/tune_plans``."""
    if override:
        return override
    return os.environ.get("AMT_TUNE_PLAN_DIR", DEFAULT_PLAN_DIR)


def plan_path(structure_hash: str,
              directory: Optional[str] = None) -> str:
    return os.path.join(plan_dir(directory), f"{structure_hash}.json")


@dataclass(frozen=True)
class TunePlan:
    """One planned configuration for one (structure, k)."""

    structure_hash: str
    k: int
    version: int = PLAN_VERSION

    # --- knobs (executor build arguments) ---
    fmt: str = "fold"
    kernel: str = "xla"
    chunk: Any = "auto"
    fold_growth: float = 1.2
    fold_align: Optional[int] = None       # None -> ops/ell.SLOT_ALIGN
    feature_dtype: Optional[str] = None    # None -> f32 carriage
    overlap_slabs: int = 1
    repl: int = 1

    # --- knobs (fused pallas_sell kernel call) ---
    row_block: int = 256
    wave: int = 16
    smem_cols_budget: Optional[int] = None
    ring: int = 2
    #: graft-synth per-level schedule (list of per-tier override
    #: dicts, ``tune/synth.synthesize_schedule`` shape).  None = the
    #: uniform knobs above apply to every tier; when set, the uniform
    #: knobs are the fallback for tiers the schedule doesn't name.
    schedule: Optional[list] = None

    # --- provenance ---
    candidate: str = "default"
    measured_ms: Optional[float] = None
    default_ms: Optional[float] = None
    margin: Optional[float] = None          # (default - measured)/default
    bit_identical: Optional[bool] = None
    host_load: Optional[float] = None
    platform: Optional[str] = None
    evaluator: Optional[str] = None         # e.g. "cpu-interpret"
    created_unix: Optional[float] = None

    # --- accuracy class (graft-classes) ---
    # "exact" plans win on bit-identity (today's contract, and the
    # default every pre-class cached plan file deserializes to);
    # "approx" plans win on the class tolerance and carry their
    # accuracy certificate (arrow_matrix_tpu/classes.py
    # Certificate.to_dict) as provenance.
    traffic_class: str = "exact"
    certificate: Optional[dict] = None

    def build_kwargs(self) -> Dict[str, Any]:
        """Executor construction overrides (``MultiLevelArrow``
        argument names)."""
        return {
            "fmt": self.fmt,
            "kernel": self.kernel,
            "chunk": self.chunk,
            "fold_growth": self.fold_growth,
            "fold_align": self.fold_align,
            "feature_dtype": self.feature_dtype,
            "overlap_slabs": self.overlap_slabs,
            "repl": self.repl,
        }

    def kernel_opts(self) -> Dict[str, Any]:
        """Per-call knobs of ``ops/pallas_sell.sell_spmm_t_pallas``."""
        opts = {
            "row_block": self.row_block,
            "wave": self.wave,
            "smem_cols_budget": self.smem_cols_budget,
            "ring": self.ring,
        }
        if self.schedule is not None:
            opts["schedule"] = [dict(e) for e in self.schedule]
        return opts

    def exec_config(self):
        """The serving rung this plan corresponds to — the degradation
        ladder (``serve/scheduler.degradation_ladder``) steps any of
        these knobs back down under pressure."""
        from arrow_matrix_tpu.serve.scheduler import ExecConfig

        return ExecConfig(kernel=self.kernel, repl=self.repl,
                          overlap_slabs=self.overlap_slabs,
                          feature_dtype=self.feature_dtype)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TunePlan":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def load_plan_file(structure_hash: str,
                   directory: Optional[str] = None) -> Optional[dict]:
    """The raw plan file for one structure hash, or None when absent
    or unreadable (the caller warns)."""
    path = plan_path(structure_hash, directory)
    try:
        with open(path, encoding="utf-8") as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    return d if isinstance(d, dict) else None


def load_plan(structure_hash: str, k: Optional[int] = None,
              directory: Optional[str] = None,
              quiet: bool = False) -> Optional[TunePlan]:
    """The cached :class:`TunePlan` for ``(structure_hash, k)``.

    ``k=None`` selects the largest-k entry (the amortized regime — the
    consumer that doesn't know its feature width yet, e.g. a server
    building its resident executor before the first request).  Any
    miss — no file, version skew, no entry for k — warns
    :class:`TunePlanMiss` (unless ``quiet``) and returns None.
    """
    def _miss(why: str) -> None:
        if not quiet:
            warnings.warn(
                f"tune plan miss for {structure_hash}: {why}; "
                f"falling back to built-in defaults "
                f"(run `graft_tune search` to populate the cache)",
                TunePlanMiss, stacklevel=3)

    d = load_plan_file(structure_hash, directory)
    if d is None:
        _miss(f"no plan file in {plan_dir(directory)!r}")
        return None
    if int(d.get("version", -1)) != PLAN_VERSION:
        _miss(f"version skew (file v{d.get('version')}, "
              f"runtime v{PLAN_VERSION})")
        return None
    plans = d.get("plans") or {}
    if not plans:
        _miss("plan file has no entries")
        return None
    if k is None:
        key = max(plans, key=lambda s: int(s))
    else:
        key = str(int(k))
        if key not in plans:
            _miss(f"no entry for k={k} "
                  f"(cached k: {sorted(int(s) for s in plans)})")
            return None
    entry = dict(plans[key])
    if int(entry.get("version", -1)) != PLAN_VERSION:
        _miss(f"entry version skew for k={key}")
        return None
    return TunePlan.from_dict(entry)


def save_plans(structure_hash: str, plans: Dict[int, TunePlan],
               fingerprint: Optional[dict] = None,
               directory: Optional[str] = None,
               context: Optional[dict] = None) -> str:
    """Merge ``plans`` (one per k) into the structure's plan file,
    atomically; returns the path.  Existing entries for other k values
    are preserved — one file is the whole per-structure cache."""
    d = plan_dir(directory)
    os.makedirs(d, exist_ok=True)
    path = plan_path(structure_hash, directory)
    # The read-merge-write is one critical section under the advisory
    # cross-process lock: atomic_write_json alone keeps readers safe,
    # but two fleet workers merging different k entries concurrently
    # would each rewrite the file from their own stale read and drop
    # the other's entry.
    with locked_file(path):
        existing = load_plan_file(structure_hash, directory)
        merged: Dict[str, dict] = {}
        if existing and int(existing.get("version", -1)) == \
                PLAN_VERSION:
            merged.update(existing.get("plans") or {})
        for k, p in plans.items():
            merged[str(int(k))] = p.to_dict()
        record = {
            "version": PLAN_VERSION,
            "structure_hash": structure_hash,
            "fingerprint": fingerprint,
            "context": context,
            "plans": merged,
        }
        atomic_write_json(path, record, indent=2, sort_keys=True)
    return path


def resolve_plan(plan, *, levels=None, width: Optional[int] = None,
                 dtype=None, growth: float = 1.2,
                 slot_align: Optional[int] = None, binary="auto",
                 plan_k: Optional[int] = None,
                 directory: Optional[str] = None
                 ) -> Optional[TunePlan]:
    """Normalize an executor's ``plan=`` argument to a
    :class:`TunePlan` (or None = defaults, after a loud miss).

    Accepted forms: a TunePlan (version-checked), a plan dict
    (``TunePlan.to_dict`` shape), or the string ``"auto"`` — hash the
    given levels and look the plan up in the cache.
    """
    if plan is None:
        return None
    if isinstance(plan, TunePlan):
        if int(plan.version) != PLAN_VERSION:
            warnings.warn(
                f"tune plan version skew (plan v{plan.version}, "
                f"runtime v{PLAN_VERSION}); ignoring the plan",
                TunePlanMiss, stacklevel=2)
            return None
        return plan
    if isinstance(plan, dict):
        return resolve_plan(TunePlan.from_dict(plan), plan_k=plan_k,
                            directory=directory)
    if plan == "auto":
        if levels is None or width is None:
            raise ValueError(
                "plan='auto' needs the levels and width to hash")
        from arrow_matrix_tpu.tune.fingerprint import structure_hash

        import numpy as np

        h = structure_hash(levels, width,
                           dtype=np.float32 if dtype is None else dtype,
                           growth=growth, slot_align=slot_align,
                           binary=binary)
        return load_plan(h, plan_k, directory)
    raise ValueError(f"unknown plan {plan!r} (expected 'auto', a "
                     f"TunePlan, a plan dict, or None)")
