"""The discrete plan space + feasibility pruning (graft-tune).

Candidates are the small set of configurations worth racing for one
(structure, k): tier-split variants of the SELL fold (the
``fold_tight`` / single-tier-ELL / HYB axes), chunking, the fused
``pallas_sell`` kernel with its slab/SMEM/ring knobs, overlap ``S``,
2.5D replication ``c``, and the carriage-dtype experiments (bf16,
plus opt-in int8).

Pruning happens BEFORE any child is spawned, with the models the repo
already trusts:

* the HBM certificate (``obs/memview.largest_fitting_repl`` over the
  fingerprint's slot-count byte model) rejects replication factors
  whose ×c footprint cannot fit the device budget
  (``obs/comm.hbm_budget_bytes``);
* divisibility (``c | k``, ``S | (k/c)``) rejects schedules the
  column-group split cannot express — the same predicate
  ``serve/scheduler.ExecConfig.accepts_k`` applies at admission;
* the ``repl_predict_ms`` / ``exposed_comm_ms`` cost models screen
  out candidates whose *modeled* step time is far beyond the default
  configuration's model (3x slack — the models rank, the bench race
  decides);
* evaluator capability: the streaming pallas path needs
  ``k % 16 == 0`` on a real chip — read from the ONE predicate the
  kernel itself validates with
  (``ops/pallas_sell.supported_feature_width`` ->
  ``KernelContract.supports_k``, graft-kcert) so the tuner and the
  kernel can never disagree; DMA-ring variants are stream-only so
  they are pruned on the interpret (CPU) evaluator;
* kernel certification (graft-kcert): every pallas candidate's
  concretized call meta is proven under KC1-KC5
  (``analysis/kernels.certify_candidate_opts``) BEFORE any child
  spawns — an uncertifiable grid/ring/budget combination is pruned
  with a ``"kcert: ..."`` reason and zero children.  Generated
  programs (ROADMAP item 3) ride the same screen through the
  ``extra`` candidate hook.

Carriage-dtype eligibility is per traffic class (graft-classes): for
``traffic_class="exact"`` (the default, today's contract) bf16/int8
are marked ``eligible=False`` — they cannot be bit-identical to the
f32 golden by construction, so they are timed as diagnostics but can
never be persisted as the winner.  For ``traffic_class="approx"`` the
same candidates become ``eligible=True``: the winner gate is the class
tolerance (measured rel-Frobenius vs the golden,
``arrow_matrix_tpu/classes.py``), not bit-identity, and the winning
plan records its accuracy certificate.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Candidate:
    """One raceable configuration: executor build overrides plus
    fused-kernel call knobs (see ``TunePlan``)."""

    name: str
    build: Dict[str, Any] = field(default_factory=dict)
    kernel_opts: Dict[str, Any] = field(default_factory=dict)
    eligible: bool = True
    note: str = ""


def predicted_operator_bytes(fp: dict, k: int,
                             feature_itemsize: int = 4) -> int:
    """Static footprint model from the fingerprint alone: packed SELL
    slots (int32 cols + data unless binary) plus one carried feature
    array — the number the HBM certificate multiplies by c."""
    slots = int(sum(fp["ladder"]["slots"]))
    rows = int(fp["total_rows"])
    cols_b = slots * 4
    data_b = 0 if fp["binary"] else slots * 4
    deg_b = rows * 4 if fp["binary"] else 0
    carriage = rows * int(k) * feature_itemsize
    return cols_b + data_b + deg_b + carriage


def enumerate_candidates(fp: dict, k: int, *,
                         platform: str = "cpu",
                         allow_int8: bool = False,
                         budget_bytes: Optional[int] = None,
                         restrict: Optional[List[str]] = None,
                         traffic_class: str = "exact",
                         extra: Optional[List[Candidate]] = None,
                         lens_model=None
                         ) -> Tuple[List[Candidate], Dict[str, str]]:
    """The candidate list for one (fingerprint, k), already pruned.

    Returns ``(candidates, pruned)`` where ``pruned`` maps each
    rejected candidate name to its reason — the search report records
    both, so a plan's provenance shows what was *not* tried and why.

    ``restrict`` (names) narrows the space — the smoke/doctor path
    races 3 candidates instead of ~12.

    ``traffic_class="approx"`` flips the carriage-dtype candidates to
    ``eligible=True`` (tolerance-gated winners, see module docstring);
    int8 still needs the explicit ``allow_int8`` opt-in even there.

    ``extra`` appends caller-supplied candidates (the generated-
    program hook): they ride the same screens, including graft-kcert
    certification for pallas kernels — an uncertifiable candidate is
    pruned here, before any child spawns.

    ``lens_model`` (a fitted ``obs.costmodel.CostModel`` for THIS
    structure) arms the compute-side screen — the comm-only T(c)
    screen's twin: a candidate whose lens-predicted iteration time
    exceeds 3x the default candidate's prediction is pruned before
    any child spawns, with a ``"lens: …"`` reason.  The margin is
    deliberately conservative (the model ranks, the bench decides)
    and the screen never touches eligibility — f32 bit-identity and
    winner rules are unchanged.
    """
    from arrow_matrix_tpu.classes import TRAFFIC_CLASSES

    if traffic_class not in TRAFFIC_CLASSES:
        raise ValueError(f"unknown traffic class {traffic_class!r} "
                         f"(expected one of {TRAFFIC_CLASSES})")
    approx = traffic_class == "approx"
    from arrow_matrix_tpu.obs.comm import hbm_budget_bytes, repl_predict_ms
    from arrow_matrix_tpu.obs.memview import largest_fitting_repl

    interpret = platform == "cpu"
    raw: List[Candidate] = [
        Candidate("default", note="the hand-tuned baseline; always "
                                  "raced, trivially bit-identical"),
        Candidate("fold_tight",
                  build={"fold_growth": 1.1, "fold_align": 1},
                  note="minimal padded slots (more tiers)"),
        Candidate("fold_coarse",
                  build={"fold_growth": 1.5},
                  note="fewer tiers, more padding"),
        Candidate("ell_one_tier",
                  build={"fold_growth": 1e9, "fold_align": 1},
                  note="degenerate tier split: one ELL tier "
                       "(plus the zero-degree prefix)"),
        Candidate("hyb",
                  build={"fmt": "hyb"},
                  note="split ELL+COO whole-level kernel"),
        Candidate("chunk_4096",
                  build={"chunk": 4096},
                  note="fixed gather chunk vs the auto budget"),
        Candidate("pallas_sell",
                  build={"kernel": "pallas_sell"},
                  note="fused gather->FMA kernel"),
        Candidate("pallas_sell_smem_small",
                  build={"kernel": "pallas_sell"},
                  kernel_opts={"smem_cols_budget": 1 << 14},
                  note="forced slab streaming (small SMEM budget)"),
        Candidate("pallas_sell_rb128",
                  build={"kernel": "pallas_sell"},
                  kernel_opts={"row_block": 128},
                  note="half-size VMEM row tile"),
        Candidate("pallas_sell_ring1",
                  build={"kernel": "pallas_sell"},
                  kernel_opts={"ring": 1},
                  note="serial DMA (no waves in flight)"),
        Candidate("pallas_sell_ring4",
                  build={"kernel": "pallas_sell"},
                  kernel_opts={"ring": 4},
                  note="deeper VMEM ring"),
        Candidate("pallas_sell_bf16",
                  build={"kernel": "pallas_sell",
                         "feature_dtype": "bf16"},
                  eligible=approx,
                  note=("fused kernel, bf16 carriage / f32 "
                        "accumulate (KC1-KC5 certified); "
                        "tolerance-gated winner" if approx else
                        "fused kernel, bf16 carriage diagnostic "
                        "(never f32 bit-identical; cannot win)")),
        Candidate("overlap2",
                  build={"overlap_slabs": 2},
                  note="S=2 chunked overlap schedule"),
        Candidate("repl2",
                  build={"repl": 2},
                  note="2.5D column groups, c=2"),
        Candidate("bf16",
                  build={"feature_dtype": "bf16"}, eligible=approx,
                  note=("bf16 carriage: approx-class candidate "
                        "(tolerance-gated winner)" if approx else
                        "bf16 carriage diagnostic (never f32 "
                        "bit-identical; cannot win)")),
    ]
    if allow_int8:
        raw.append(Candidate(
            "int8", build={"feature_dtype": "int8"}, eligible=approx,
            note=("opt-in int8 (q, scale) carriage: approx-class "
                  "candidate" if approx else
                  "opt-in int8-carriage experiment (diagnostic only)")))
    if approx or allow_int8:
        # The fused (q, scale) SELL variant (ROADMAP item 2's last
        # kernel): int8 carriage lines + f32 accumulate in-kernel, the
        # per-feature scale applied outside.  Raced for approx plans
        # alongside pallas_sell_bf16; allow_int8 also surfaces it as
        # an exact-class diagnostic.
        raw.append(Candidate(
            "pallas_sell_int8",
            build={"kernel": "pallas_sell", "feature_dtype": "int8"},
            eligible=approx,
            note=("fused kernel, int8 (q, scale) carriage / f32 "
                  "accumulate (KC1-KC5 certified); tolerance-gated "
                  "winner" if approx else
                  "fused kernel, int8 (q, scale) carriage diagnostic "
                  "(never f32 bit-identical; cannot win)")))
    if extra:
        raw.extend(extra)

    budget = hbm_budget_bytes(budget_bytes)
    base_bytes = predicted_operator_bytes(fp, k)
    # Modeled default step time: slots streamed once at the comm-model
    # link rate — only used as the 3x cost-model screen's yardstick.
    default_ms = repl_predict_ms(1, 0, compute_ms=0.0)

    lens_base = 0.0
    if lens_model is not None:
        from arrow_matrix_tpu.obs.costmodel import predict_candidate_ms
        lens_base = predict_candidate_ms(lens_model, fp, k, {}, {})

    out, pruned = [], {}
    for c in raw:
        if restrict is not None and c.name not in restrict:
            pruned[c.name] = "not in restricted candidate set"
            continue
        repl = int(c.build.get("repl", 1))
        slabs = int(c.build.get("overlap_slabs", 1))
        if repl > 1:
            if k % repl:
                pruned[c.name] = (f"repl={repl} needs repl | k "
                                  f"(k={k})")
                continue
            fit = largest_fitting_repl(base_bytes, budget,
                                       choices=(1, repl))
            if fit < repl:
                pruned[c.name] = (
                    f"HBM certificate: {base_bytes} B x{repl} exceeds "
                    f"budget {budget} B")
                continue
            predicted = repl_predict_ms(repl, 0, compute_ms=default_ms)
            if predicted > 3.0 * max(default_ms, 1e-9):
                pruned[c.name] = (f"cost model: predicted "
                                  f"{predicted:.3f} ms > 3x default")
                continue
        if slabs > 1 and (k // repl) % slabs:
            pruned[c.name] = (f"overlap S={slabs} needs S | (k/c) "
                              f"(k={k}, c={repl})")
            continue
        if c.build.get("kernel") == "pallas_sell":
            # The ONE streaming-gate predicate: the kernel's own
            # contract (supported_feature_width -> supports_k).
            from arrow_matrix_tpu.ops.pallas_sell import (
                supported_feature_width)
            if not interpret and not supported_feature_width(k):
                pruned[c.name] = ("streaming pallas_sell needs "
                                  f"k % 16 == 0 on chip (k={k})")
                continue
            if interpret and "ring" in c.kernel_opts:
                pruned[c.name] = ("DMA ring depth is a stream-only "
                                  "knob; interpret evaluator runs the "
                                  "vectorized body")
                continue
            from arrow_matrix_tpu.analysis.kernels import (
                certify_candidate_opts)
            reason = certify_candidate_opts(
                c.kernel_opts, k, interpret=interpret,
                feature_dtype=c.build.get("feature_dtype"))
            if reason is not None:
                pruned[c.name] = reason
                continue
        if lens_model is not None and lens_base > 0.0 \
                and c.name != "default":
            predicted = predict_candidate_ms(lens_model, fp, k,
                                             c.build, c.kernel_opts)
            if predicted > 3.0 * lens_base:
                pruned[c.name] = (
                    f"lens: predicted compute {predicted:.3f} ms > "
                    f"3x default {lens_base:.3f} ms")
                continue
        out.append(c)
    return out, pruned
