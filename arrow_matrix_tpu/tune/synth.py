"""graft-synth: structure-JIT kernel synthesis (ROADMAP item 3).

The tune layer raced a FIXED menu of hand-written configurations
(``tune/space.py``) while graft-lens proved the cost is per-level
heterogeneous — on the committed ba_256_3 point the entire bf16-vs-f32
gap lands on the L0 tail tier as decode/accumulate, not bytes.  This
module closes the loop the JITSPMM way (arxiv 2312.05639: row-block
specialization derived from the sparsity structure; arxiv 1705.10218:
schedule parameters priced per structure, not globally): it reads the
degree-ladder fingerprint and *derives* a per-level Pallas schedule —
head levels get dense-ish wide-row-block / shallow-ring tilings, tail
levels get scatter-ish narrow-row-block / deep-ring tilings — instead
of choosing among uniform knob settings.

A synthesized schedule is a parameterized program over the existing
meta-first builders (``ops/pallas_sell.slab_call_meta`` et al.), never
new kernel source: the per-tier overrides flow through
``sell_spmm_t_pallas(schedule=...)`` into the SAME certified
``sell_tier_spmm_packed`` slab calls.  The pipeline a generated
program rides, end to end:

* :func:`synth_candidates` emits candidates into the race through
  ``enumerate_candidates(extra=...)`` — screened by the graft-lens
  cost model (per-level predictions, 3x rule) and certified KC1-KC5
  (``analysis/kernels.certify_candidate_opts`` walks every schedule
  entry) BEFORE any child spawns;
* the subprocess-isolated harness races survivors under the unchanged
  f32 bit-identity win rule (an all-f32 per-level schedule changes the
  slab partitioning, never the per-row accumulation order, so it CAN
  be bitwise-exact against the golden fold path);
* the winner persists in the TunePlan cache keyed by structure hash —
  a second search on an unchanged structure is a pure hit with ZERO
  children (PR 10's promise, now covering generated programs);
* :func:`persist_program` writes the synthesized program into the
  committed store (``bench_cache/synth_programs.json``) and
  ``ops/kernel_contract.registered_kernels()`` lazily re-registers it
  via :func:`register_persisted_programs`, so graft-kcert certifies
  generated programs in every process, manifest-drift-gated like the
  hand-written builders.

This module is import-light on purpose (no jax at import time): the
kernel-contract registry must stay loadable host-only, and the metas /
witness callables import ``ops/pallas_sell`` lazily.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from arrow_matrix_tpu.ops.kernel_contract import (
    KernelContract,
    KernelEntry,
    register_kernel,
)

STORE_VERSION = 1

#: Degree-ladder family bands (slot width w = realized tier m_t), the
#: SAME bands obs/costmodel.tier_family prices with.
TAIL_WIDTH = 8
MID_WIDTH = 64

#: Per-family schedule policy: (row_block, wave, ring, slab_blocks).
#: Tail tiers are scatter-ish — short rows mean each wave moves few
#: bytes, so keep the VMEM tile narrow, the DMA ring deep (latency
#: hiding over bandwidth), and the slab short; head tiers are dense-ish
#: — wide rows amortize the launch, so widen the tile, keep the ring
#: shallow, and let the slab grow to the full scalar-prefetch budget
#: (slab_blocks=None).
FAMILY_POLICY: Dict[str, Tuple[int, int, int, Optional[int]]] = {
    "tail": (64, 8, 4, 4),
    "mid": (128, 8, 3, 8),
    "head": (256, 16, 2, None),
}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: The committed generated-program store.  ``AMT_SYNTH_STORE`` is the
#: test/override hook; the default is repo-anchored so certification
#: finds the same programs from any working directory.
DEFAULT_STORE_PATH = os.path.join(_REPO_ROOT, "bench_cache",
                                  "synth_programs.json")


def store_path(path: Optional[str] = None) -> str:
    if path is not None:
        return path
    return os.environ.get("AMT_SYNTH_STORE", DEFAULT_STORE_PATH)


def ladder_family(width: int) -> str:
    """The degree-ladder band of one tier's slot width — mirrors
    ``obs/costmodel.tier_family`` ("zero" handled by the caller: a
    zero-width tier launches no kernel)."""
    if width <= TAIL_WIDTH:
        return "tail"
    if width <= MID_WIDTH:
        return "mid"
    return "head"


def synthesize_schedule(fp: dict, *,
                        carriage_policy: str = "exact") -> List[dict]:
    """Derive the per-level schedule from a structure fingerprint's
    degree ladder.  Returns a list of per-tier entries (the
    ``sell_spmm_t_pallas(schedule=...)`` / TunePlan payload), each
    carrying the synthesis provenance (``m_t``, ``rows``, ``family``)
    alongside the runtime knobs.

    ``carriage_policy="exact"`` keeps every tier f32 (the schedule can
    win at f32 bit-identity); ``"mixed"`` narrows byte-dominated
    head/mid tiers to bf16 while keeping decode-dominated tail tiers
    f32 — exactly the graft-lens ba_256_3 attribution finding (the
    bf16 penalty lives on the tail tier).
    """
    if carriage_policy not in ("exact", "mixed"):
        raise ValueError(f"unknown carriage policy {carriage_policy!r}")
    ladder = fp["ladder"]
    widths = [int(w) for w in ladder["slot_width"]]
    rows = [int(r) for r in ladder["rows"]]
    schedule: List[dict] = []
    for t, (w, r) in enumerate(zip(widths, rows)):
        if w < 1 or r < 1:
            continue        # zero-degree prefix: no kernel launch
        fam = ladder_family(w)
        row_block, wave, ring, slab_blocks = FAMILY_POLICY[fam]
        if slab_blocks is None:
            budget = None   # full scalar-prefetch budget: long slabs
        else:
            # Bound the slab to ``slab_blocks`` row blocks of cols
            # (int32: m_t * 4 B per row) — slab_rows() floors at one
            # block, so a tiny budget still streams.
            budget = w * 4 * row_block * slab_blocks
        carriage = "f32"
        if carriage_policy == "mixed" and fam != "tail":
            carriage = "bf16"
        entry = {"tier": t, "m_t": w, "rows": r, "family": fam,
                 "row_block": row_block, "wave": wave, "ring": ring,
                 "carriage": carriage}
        if budget is not None:
            entry["smem_cols_budget"] = budget
        schedule.append(entry)
    return schedule


def schedule_summary(schedule: List[dict]) -> str:
    """One-line human summary: ``L1:head rb256/r2 ...``."""
    return " ".join(
        f"L{e['tier']}:{e['family']} rb{e['row_block']}/r{e['ring']}"
        + ("/" + e["carriage"] if e.get("carriage", "f32") != "f32"
           else "")
        for e in schedule)


def program_name(structure_hash: str) -> str:
    return f"pallas_synth_{structure_hash[:8]}"


def synth_candidates(fp: dict, *, traffic_class: str = "exact",
                     interpret: bool = False) -> List[Any]:
    """The generated candidates for one fingerprint, ready for
    ``enumerate_candidates(extra=...)``:

    * ``synth_ladder`` — the all-f32 per-level schedule; exact-class
      eligible (bit-identity is preserved: per-tier knobs repartition
      slabs, the per-row accumulation order is unchanged);
    * ``synth_ladder_mixed`` — bf16 on byte-dominated head/mid tiers,
      f32 on decode-dominated tail tiers; approx-class eligible only,
      raced alongside ``pallas_sell_bf16``.

    Uniform-knob structures (a one-tier ladder) still synthesize — the
    value is that NOTHING here is hand-enumerated; the menu shrinks to
    a fallback.
    """
    from arrow_matrix_tpu.tune.space import Candidate

    exact = synthesize_schedule(fp, carriage_policy="exact")
    if not exact:
        return []
    approx = traffic_class == "approx"
    out = [Candidate(
        "synth_ladder",
        build={"kernel": "pallas_sell"},
        kernel_opts={"schedule": exact},
        note=("generated per-level schedule from the degree ladder: "
              + schedule_summary(exact)))]
    mixed = synthesize_schedule(fp, carriage_policy="mixed")
    if any(e.get("carriage") == "bf16" for e in mixed):
        out.append(Candidate(
            "synth_ladder_mixed",
            build={"kernel": "pallas_sell"},
            kernel_opts={"schedule": mixed},
            eligible=approx,
            note=("generated mixed-carriage schedule (bf16 head/mid, "
                  "f32 tail): " + schedule_summary(mixed)
                  + ("; tolerance-gated winner" if approx else
                     "; diagnostic (never f32 bit-identical)"))))
    return out


# ---------------------------------------------------------------------------
# Persistence: the committed generated-program store
# ---------------------------------------------------------------------------


def load_store(path: Optional[str] = None) -> dict:
    p = store_path(path)
    try:
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {"version": STORE_VERSION, "programs": {}}
    if not isinstance(doc, dict) or "programs" not in doc:
        raise ValueError(f"synth store {p!r} is not a program store")
    if int(doc.get("version", -1)) != STORE_VERSION:
        raise ValueError(
            f"synth store version skew: {p!r} carries "
            f"{doc.get('version')!r}, this build reads {STORE_VERSION}")
    return doc


def synth_program_record(fp: dict, structure_hash: str, k: int,
                         schedule: List[dict]) -> dict:
    """The store record of one generated program.  Budgets and lane
    constants are captured at persist time so host-only loads rebuild
    the KernelContract without importing jax."""
    from arrow_matrix_tpu.ops import pallas_sell as ps

    return {
        "structure_hash": structure_hash,
        "k": int(k),
        "n": int(fp["n"]),
        "binary": bool(fp["binary"]),
        "schedule": [dict(e) for e in schedule],
        "granule": ps.GRANULE,
        "stream_k_multiple": ps.STREAM_K_MULTIPLE,
        "smem_cols_budget": ps.DEFAULT_SMEM_COLS_BUDGET,
        "vmem_budget": ps.KERNEL_CONTRACT.vmem_budget_bytes,
        "summary": schedule_summary(schedule),
    }


def persist_program(fp: dict, structure_hash: str, k: int,
                    schedule: List[dict],
                    path: Optional[str] = None) -> str:
    """Write (merge) one generated program into the store and register
    it in-process; returns the program name.  Read-merge-write with an
    atomic replace — the store is tiny and synth runs are rare, so a
    lost concurrent merge re-synthesizes identically next search."""
    p = store_path(path)
    name = program_name(structure_hash)
    doc = load_store(p)
    doc["version"] = STORE_VERSION
    doc["programs"][name] = synth_program_record(fp, structure_hash, k,
                                                 schedule)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p) or ".",
                               prefix=".synth_", suffix=".json")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, p)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    register_kernel(entry_from_program(name, doc["programs"][name]))
    return name


# ---------------------------------------------------------------------------
# Registration: generated programs as certifiable KernelEntry objects
# ---------------------------------------------------------------------------


def _normalized_points(prog: dict) -> List[dict]:
    """The runtime-normalized (m_t, rows, rb, wave, ring, carriage,
    budget) points of one program — EXACTLY the numbers
    ``sell_tier_spmm_packed`` would execute, so the certified metas and
    the executed calls cannot drift (the meta-first discipline)."""
    granule = int(prog["granule"])
    default_budget = int(prog["smem_cols_budget"])
    points = []
    for e in prog["schedule"]:
        m_t, rows = int(e["m_t"]), int(e["rows"])
        if m_t < 1 or rows < 1:
            continue
        rb = int(e.get("row_block", 256))
        aligned_rows = -(-max(rows, 1) // granule) * granule
        rb = min(rb, aligned_rows)
        rb = max(granule, rb - rb % granule)
        w = min(int(e.get("wave", 16)), rb)
        while w > 1 and rb % w:
            w -= 1
        points.append({
            "m_t": m_t, "rows": rows, "row_block": rb, "wave": w,
            "ring": int(e.get("ring", 2)),
            "carriage": e.get("carriage", "f32"),
            "budget": int(e.get("smem_cols_budget", default_budget)),
        })
    return points


def _program_metas(prog: dict) -> List[dict]:
    """Concretized slab-call metas for every per-tier point of one
    generated program (lazy jax import — certification time only)."""
    from arrow_matrix_tpu.ops import pallas_sell as ps

    granule = int(prog["granule"])
    k = int(prog["k"])
    n = int(prog["n"])
    n_lines = max(1, -(-n // granule))
    binary = bool(prog["binary"])
    metas = []
    for pt in _normalized_points(prog):
        rb = pt["row_block"]
        rows_pad = -(-pt["rows"] // rb) * rb
        slab = min(ps.slab_rows(pt["m_t"], rb, pt["budget"]), rows_pad)
        metas.append(ps.slab_call_meta(
            pt["m_t"], slab, k, rb, binary, True, pt["wave"],
            pt["ring"], n_lines=n_lines, carriage=pt["carriage"],
            smem_cols_budget=pt["budget"]))
    return metas


def _program_witness(prog: dict):
    """Boundary-column interpret witness over the program's distinct
    (row_block, wave, ring, carriage) configurations: every slot
    points at the last feature row, streamed and vectorized bodies
    must agree bitwise (the generated-program twin of
    ``pallas_sell.kcert_witness``, at witness scale k=16)."""
    import numpy as np

    import jax.numpy as jnp

    from arrow_matrix_tpu.ops import pallas_sell as ps

    k, n_table = 16, 64
    configs = sorted({(pt["row_block"], pt["wave"], pt["ring"],
                       pt["carriage"])
                      for pt in _normalized_points(prog)})
    if not configs:
        return False, "program has no certifiable schedule points"
    x_t = jnp.asarray(
        np.linspace(-1.0, 1.0, k * n_table, dtype=np.float32)
        .reshape(k, n_table))
    x_packed = ps.pack_features_t(x_t)
    try:
        for rb, wave, ring, carriage in configs:
            rows, m_t = min(rb, 32), 3
            cols = jnp.full((m_t, rows), n_table - 1, dtype=jnp.int32)
            deg = jnp.full((rows,), m_t, dtype=jnp.int32)
            vec = ps.sell_tier_spmm_packed(
                cols, x_packed, deg=deg, stream=False, interpret=True,
                row_block=rb, wave=wave, feature_dtype=carriage)
            st = ps.sell_tier_spmm_packed(
                cols, x_packed, deg=deg, stream=True, interpret=True,
                row_block=rb, wave=wave, ring=ring,
                feature_dtype=carriage)
            if not np.array_equal(np.asarray(vec), np.asarray(st)):
                return False, (f"stream/vectorized mismatch at rb={rb}"
                               f" wave={wave} ring={ring} "
                               f"({carriage})")
            if not np.isfinite(np.asarray(st)).all():
                return False, f"non-finite boundary output (rb={rb})"
    except Exception as exc:   # a raise IS the out-of-bounds evidence
        return False, f"boundary interpret run raised: {exc!r}"
    return True, (f"{len(configs)} schedule config(s): boundary-column "
                  f"interpret round trip ok (stream==vectorized)")


def entry_from_program(name: str, prog: dict) -> KernelEntry:
    """A generated program as a certifiable :class:`KernelEntry`.  The
    contract envelope is derived from the stored schedule; the source
    under KC3/KC4 AST review is the REAL ring-schedule builder
    (``ops/pallas_sell.py``) the program parameterizes."""
    points = _normalized_points(prog)
    contract = KernelContract(
        name=name,
        module="arrow_matrix_tpu.tune.synth",
        kind="sell_stream",
        granule=int(prog["granule"]),
        stream_k_multiple=int(prog["stream_k_multiple"]),
        row_blocks=tuple(sorted({pt["row_block"] for pt in points})),
        rings=tuple(sorted({pt["ring"] for pt in points})),
        waves=tuple(sorted({pt["wave"] for pt in points})),
        ks=(int(prog["k"]),),
        carriage_dtypes=tuple(sorted({pt["carriage"]
                                      for pt in points})),
        accum_dtype="f32",
        smem_cols_budget=int(prog["smem_cols_budget"]),
        vmem_budget_bytes=int(prog["vmem_budget"]),
    )

    def _source_path():
        from arrow_matrix_tpu.ops import pallas_sell as ps

        return ps.__file__

    return KernelEntry(
        contract=contract,
        metas=lambda: _program_metas(prog),
        source_path=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), os.pardir,
            "ops", "pallas_sell.py"),
        witness=lambda: _program_witness(prog),
    )


def register_persisted_programs(path: Optional[str] = None) -> List[str]:
    """Register every program in the store; returns the names (empty
    when the store is absent).  Called lazily by
    ``kernel_contract.registered_kernels()`` so generated programs ride
    certification in every process that looks at the registry."""
    doc = load_store(path)
    names = []
    for name in sorted(doc["programs"]):
        register_kernel(entry_from_program(name, doc["programs"][name]))
        names.append(name)
    return names
