"""Canonical structure fingerprint + hash (graft-tune).

A tuned plan is only reusable if the thing it was tuned FOR can be
named.  This module names it: a deterministic fingerprint of the
decomposition's *structure* — per-level rows/nnz/arrow widths, the
folded degree ladder at the requested tier split, the slot histogram,
and the tier imbalance scalars (``obs/imbalance.summarize_units``) —
hashed to a short hex key.  Everything is derived from the levels on
the host with numpy only; no executor is built and no device is
touched, so the hash is cheap enough to compute at every
``plan="auto"`` construction.

Invariances (pinned by tests/test_tune.py):

* re-decomposing the same graph with the same seed → same hash
  (the fingerprint reads structure, not object identity or memory
  layout);
* a save/load round trip through ``io/graphio.py`` artifacts → same
  hash (CSR vs CsrLike-triplet levels fingerprint identically);
* different width, tier split (growth/align), or dtype → different
  hash (those change the packed operator, so plans must not cross).

The hash deliberately does NOT include the feature width ``k``: the
operator is k-independent, so one plan file carries per-k entries
(see ``tune/plan.py``).
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional

import numpy as np

#: Bump when the fingerprint schema changes — a hash from another
#: version must never silently collide with the current one.
FINGERPRINT_VERSION = 1


def _per_level_degrees(matrix) -> np.ndarray:
    """Per-row nnz of one level matrix (CSR or CsrLike triplet)."""
    from scipy import sparse

    if isinstance(matrix, sparse.csr_matrix):
        indptr = matrix.indptr
    else:
        indptr = matrix[2]
    return np.diff(np.asarray(indptr, dtype=np.int64))


def folded_total_rows(levels, width: int) -> int:
    """The shared flat row count of the single-chip (mesh=None) build
    — the same derivation ``MultiLevelArrow.__init__`` performs, so
    the fingerprint's ladder is computed over exactly the rows the
    executor packs."""
    from arrow_matrix_tpu.io.graphio import number_of_blocks
    from arrow_matrix_tpu.parallel.mesh import pad_to_multiple

    widths = []
    for i, lvl in enumerate(levels):
        is_last = i == len(levels) - 1
        if lvl.arrow_width > width or is_last:
            widths.append(-(-lvl.arrow_width // width) * width)
        else:
            widths.append(width)
    unit = max(widths)
    max_rows = max(number_of_blocks(lvl.matrix, w) * w
                   for lvl, w in zip(levels, widths))
    return pad_to_multiple(max_rows, unit)


def folded_degrees(levels, total: int) -> np.ndarray:
    """Per-row nnz of the folded operator in level-0 order: every
    level's row degrees routed through the same
    ``inv_perm0[pad_permutation(perm)]`` coordinate map the fold uses
    (``MultiLevelArrow._init_folded``), summed.  Levels are
    edge-disjoint, so the sum IS the folded degree."""
    from arrow_matrix_tpu.parallel.multi_level import pad_permutation

    perms = [pad_permutation(np.asarray(lvl.permutation), total)
             for lvl in levels]
    inv_perm0 = np.argsort(perms[0])
    deg = np.zeros(total, dtype=np.int64)
    for lvl, p in zip(levels, perms):
        mp = inv_perm0[p]
        ld = np.zeros(total, dtype=np.int64)
        d = _per_level_degrees(lvl.matrix)
        ld[:d.size] = d
        deg[mp] += ld
    return deg


def structure_fingerprint(levels, width: int, dtype=np.float32,
                          growth: float = 1.2,
                          slot_align: Optional[int] = None,
                          binary="auto") -> dict:
    """The canonical structure record the hash is taken over.  All
    values are plain python ints/floats/strings (JSON-stable); floats
    that come from ratios are rounded so bit-level numpy noise cannot
    split a hash."""
    from arrow_matrix_tpu.io.graphio import num_rows
    from arrow_matrix_tpu.obs.imbalance import summarize_units
    from arrow_matrix_tpu.ops.ell import SLOT_ALIGN
    from arrow_matrix_tpu.ops.sell import align_up_vec, tier_boundaries
    from arrow_matrix_tpu.parallel.multi_level import (
        resolve_block_dtype,
        resolve_levels_binary,
    )

    if slot_align is None:
        slot_align = SLOT_ALIGN
    dtype = resolve_block_dtype(dtype)
    total = folded_total_rows(levels, width)
    deg = folded_degrees(levels, total)

    # The exact ladder the SELL packer would build: ascending aligned
    # degrees, tiers split at the growth ratio.
    sorted_deg = np.sort(deg, kind="stable")
    aligned = (align_up_vec(sorted_deg, slot_align) if slot_align > 1
               else sorted_deg)
    starts = tier_boundaries(aligned, growth) + [total]
    tier_rows, tier_nnz, tier_slots, tier_width = [], [], [], []
    for lo, hi in zip(starts[:-1], starts[1:]):
        m_t = int(aligned[hi - 1]) if hi > lo else 0
        tier_rows.append(int(hi - lo))
        tier_nnz.append(int(sorted_deg[lo:hi].sum()))
        tier_slots.append(m_t * (hi - lo))
        tier_width.append(m_t)

    # Slot histogram: distinct aligned degrees and their row counts —
    # the padded-gather cost surface the tier split carves up.
    vals, counts = np.unique(aligned, return_counts=True)

    imb = summarize_units(tier_rows, tier_nnz, tier_slots, units="tier")

    def _r(v):
        return None if v is None else round(float(v), 6)

    levels_fp = []
    for lvl in levels:
        d = _per_level_degrees(lvl.matrix)
        levels_fp.append({
            "rows": int(num_rows(lvl.matrix)),
            "nnz": int(d.sum()),
            "arrow_width": int(lvl.arrow_width),
        })

    return {
        "version": FINGERPRINT_VERSION,
        "n": int(num_rows(levels[0].matrix)),
        "total_rows": int(total),
        "width": int(width),
        "dtype": np.dtype(dtype).name,
        "binary": bool(resolve_levels_binary(levels, binary)),
        "growth": round(float(growth), 6),
        "slot_align": int(slot_align),
        "levels": levels_fp,
        "ladder": {
            "tier_starts": [int(s) for s in starts[:-1]],
            "rows": tier_rows,
            "nnz": tier_nnz,
            "slots": tier_slots,
            "slot_width": tier_width,
        },
        "slot_hist": {
            "deg": [int(v) for v in vals],
            "count": [int(c) for c in counts],
        },
        "imbalance": {
            "nnz_max_over_mean": _r(imb["nnz_max_over_mean"]),
            "rows_max_over_mean": _r(imb["rows_max_over_mean"]),
            "padded_slot_waste": _r(imb["padded_slot_waste"]),
        },
    }


def fingerprint_hash(fp: dict) -> str:
    """sha256 over the canonical JSON encoding, truncated to 16 hex
    chars — the plan-cache file name."""
    blob = json.dumps(fp, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def structure_hash(levels, width: int, dtype=np.float32,
                   growth: float = 1.2,
                   slot_align: Optional[int] = None,
                   binary="auto") -> str:
    """Fingerprint + hash in one call (the common consumer path)."""
    return fingerprint_hash(structure_fingerprint(
        levels, width, dtype=dtype, growth=growth,
        slot_align=slot_align, binary=binary))
