"""graft-tune: structure-specialized kernel autotuning with a
persistent plan cache.

The loop (README "graft-tune" section): **search** — fingerprint the
decomposition's structure and race the pruned candidate space in
subprocess-isolated bench children; **cache** — persist the winner as
a versioned :class:`TunePlan` keyed by the structure hash; **consume**
— executors built with ``plan="auto"`` (and the graft-serve scheduler)
resolve hash → cached plan → knobs at zero search cost, falling back
LOUDLY on a miss; **degrade** — the serving degradation ladder steps
any tuned knob back down under pressure.
"""

from arrow_matrix_tpu.tune.fingerprint import (
    FINGERPRINT_VERSION,
    fingerprint_hash,
    structure_fingerprint,
    structure_hash,
)
from arrow_matrix_tpu.tune.plan import (
    PLAN_VERSION,
    TunePlan,
    TunePlanMiss,
    load_plan,
    plan_dir,
    plan_path,
    resolve_plan,
    save_plans,
)
from arrow_matrix_tpu.tune.search import (
    search,
    smoke_tune,
)
from arrow_matrix_tpu.tune.space import (
    Candidate,
    enumerate_candidates,
)

__all__ = [
    "FINGERPRINT_VERSION",
    "PLAN_VERSION",
    "Candidate",
    "TunePlan",
    "TunePlanMiss",
    "enumerate_candidates",
    "fingerprint_hash",
    "load_plan",
    "plan_dir",
    "plan_path",
    "resolve_plan",
    "save_plans",
    "search",
    "smoke_tune",
    "structure_fingerprint",
    "structure_hash",
]
