"""``python -m arrow_matrix_tpu.tune`` — the candidate-child entry
point (``--candidate <name>``, config via the ``AMT_TUNE_CFG``
environment JSON, result as the final stdout JSON line — the
``bench.py`` child protocol) plus a passthrough to the ``graft_tune``
CLI for interactive use."""

from __future__ import annotations

import json
import os
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv[:1] == ["--candidate"]:
        from arrow_matrix_tpu.tune.search import candidate_child_main

        cfg = json.loads(os.environ["AMT_TUNE_CFG"])
        try:
            out = candidate_child_main(cfg)
        except Exception as e:  # noqa: BLE001 — one line, parent parses
            out = {"name": cfg.get("candidate", {}).get("name"),
                   "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(out), flush=True)
        return 0 if out.get("error") is None else 1
    from arrow_matrix_tpu.cli.graft_tune import main as cli_main

    return cli_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
