"""ErrorProbe: error-vs-iteration curves against the f32 golden.

The repo's accuracy story so far is single-shot: graft-tune proves
bit-identity for ONE step, bench.py reports one final Frobenius error.
Neither says how reduced-precision carriage (bf16 folded state, int8
quantized state) DRIFTS as iterations compound — the number a serving
deployment choosing a carriage dtype actually needs, and the curve the
paper's accuracy discussion is about.

The probe runs the golden trajectory — the DEFAULT f32 fold executor
(the exact ``tune/search.py`` golden path) stepped ``iterations``
times, gathered to host after every step — then replays the same seeded
input through each probed dtype and records the per-iteration
Frobenius, relative-Frobenius, and max-abs error against the golden at
the same iteration.  Everything is seeded (``GOLDEN_SEED`` by default),
so the curves are deterministic and ``tools/ledger_gate.py`` can treat
a committed curve as a regression baseline: the f32 curve is
identically zero BY CONSTRUCTION (same config ⇒ same trajectory), so
any nonzero f32 point in a later run is itself a bit-identity
regression.

Dtypes:

* ``f32`` / ``bf16`` / ``int8`` — ALL real executors
  (``feature_dtype`` carriage, ``parallel/multi_level.py``).  int8
  carriage became real in graft-classes — the fold step carries a
  symmetric per-feature-row ``(q, scale)`` pair and requantizes each
  iteration on device — so its records now say ``"emulated": false``
  and a certificate derived from them (``arrow_matrix_tpu/classes.py``)
  describes the carriage the executor actually serves.  The old
  host-side quantize-dequantize emulation survives behind
  ``emulate_int8=True`` for A/B-ing the device path against the
  state-precision model.

Each curve is one ledger record: ``kind="error_curve"``,
``metric=f"error_curve_{dtype}"`` (dtype in the metric keeps baseline
keys per-dtype), ``value`` = final relative-Frobenius error, curve
arrays in ``payload``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

#: Seed shared with the tune golden (tune/search.py GOLDEN_SEED).
DEFAULT_SEED = 3

#: Default probe depth: enough iterations for bf16 drift to show its
#: compounding shape, small enough to run on every doctor invocation.
DEFAULT_ITERATIONS = 8

PROBE_DTYPES = ("f32", "bf16", "int8")


def _platform_info():
    try:
        import jax

        dev = jax.devices()[0]
        return jax.default_backend(), getattr(dev, "device_kind",
                                              dev.platform)
    except Exception:  # pragma: no cover - no backend available
        return None, None


def _quantize_int8(x: np.ndarray) -> np.ndarray:
    """Symmetric per-tensor int8 round trip: the precision an int8
    carriage would keep between steps."""
    amax = float(np.max(np.abs(x)))
    if amax == 0.0:
        return x.copy()
    scale = amax / 127.0
    q = np.clip(np.round(x / scale), -127, 127)
    return (q * scale).astype(np.float32)


def _build(levels, width: int, feature_dtype: Optional[str]):
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    return MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                           feature_dtype=feature_dtype)


def _trajectory(multi, x_host: np.ndarray, iterations: int,
                quantize: bool = False) -> List[np.ndarray]:
    """Host-gathered state after every step.  ``quantize`` round-trips
    the state through int8 on the host between steps (the emulated
    int8 carriage); re-uploading via ``set_features`` keeps the device
    layout handling in one place."""
    out: List[np.ndarray] = []
    x = multi.set_features(x_host)
    for _ in range(iterations):
        x = multi.step(x)
        host = multi.gather_result(x)
        if quantize:
            host = _quantize_int8(host)
            x = multi.set_features(host)
        out.append(np.asarray(host, dtype=np.float32))
    return out


def error_curve(golden: Sequence[np.ndarray],
                probed: Sequence[np.ndarray]) -> Dict[str, List[float]]:
    """Per-iteration error of ``probed`` against ``golden`` (same
    length): Frobenius, relative Frobenius (vs the golden's norm), and
    max-abs.  Plain float lists — JSON-ready ledger payload."""
    fro: List[float] = []
    rel: List[float] = []
    mab: List[float] = []
    for g, p in zip(golden, probed):
        d = p.astype(np.float64) - g.astype(np.float64)
        f = float(np.linalg.norm(d))
        gn = float(np.linalg.norm(g.astype(np.float64)))
        fro.append(f)
        rel.append(f / gn if gn > 0 else f)
        mab.append(float(np.max(np.abs(d))) if d.size else 0.0)
    return {"frobenius": fro, "rel_frobenius": rel, "max_abs": mab}


def error_curves_for_source(source: Dict[str, Any], *, k: int = 4,
                            iterations: int = DEFAULT_ITERATIONS,
                            seed: int = DEFAULT_SEED,
                            dtypes: Sequence[str] = ("f32", "bf16"),
                            ledger=None,
                            emulate_int8: bool = False
                            ) -> List[Dict[str, Any]]:
    """Probe one structure (a ``tune/search.py`` levels source) at each
    dtype; returns the ledger records (appended to ``ledger`` when one
    is given, otherwise built with ``ts_unix=0``/pinned provenance so
    the result is deterministic for tests).

    The structure key is the graft-tune ``structure_hash`` — the same
    key the plan cache and every bench record uses, so a curve joins
    the rest of the ledger on it.
    """
    from arrow_matrix_tpu.ledger import store
    from arrow_matrix_tpu.tune.fingerprint import structure_hash
    from arrow_matrix_tpu.tune.search import load_levels_from_source

    levels, width = load_levels_from_source(source)
    shash = structure_hash(levels, width)
    platform, device_kind = _platform_info()

    rng = np.random.default_rng(seed)
    # The row count comes from the golden executor itself; build it
    # first, then draw the seeded input at its shape.
    golden_exec = _build(levels, width, None)
    n_rows = golden_exec.n
    x0 = rng.standard_normal((n_rows, k)).astype(np.float32)
    golden = _trajectory(golden_exec, x0, iterations)

    records: List[Dict[str, Any]] = []
    for dtype in dtypes:
        if dtype not in PROBE_DTYPES:
            raise ValueError(f"unknown probe dtype {dtype!r}; "
                             f"expected one of {PROBE_DTYPES}")
        emulated = dtype == "int8" and emulate_int8
        if emulated:
            probed = _trajectory(_build(levels, width, None), x0,
                                 iterations, quantize=True)
        else:
            feature_dtype = None if dtype == "f32" else dtype
            probed = _trajectory(_build(levels, width, feature_dtype),
                                 x0, iterations)
        curve = error_curve(golden, probed)
        knobs = {"dtype": dtype, "k": k, "iterations": iterations,
                 "seed": seed, "emulated": emulated, "fmt": "fold"}
        payload = dict(curve)
        payload["source"] = dict(source)
        value = curve["rel_frobenius"][-1] if curve["rel_frobenius"] \
            else None
        if ledger is not None:
            rec = ledger.record(
                "error_curve", f"error_curve_{dtype}", value,
                unit="rel_frobenius", structure_hash=shash,
                knobs=knobs, payload=payload, platform=platform,
                device_kind=device_kind)
        else:
            rec = {
                "schema": store.SCHEMA_VERSION,
                "kind": "error_curve",
                "record_id": "",
                "prev": None,
                "ts_unix": 0,
                "metric": f"error_curve_{dtype}",
                "value": value,
                "unit": "rel_frobenius",
                "structure_hash": shash,
                "platform": platform,
                "device_kind": device_kind,
                "host_load": None,
                "git_rev": None,
                "knobs": knobs,
                "payload": payload,
            }
            rec["record_id"] = store.canonical_record_id(rec)
        records.append(rec)
    return records
