"""graft-ledger: the unified performance & accuracy record store.

One append-only, hash-chained, schema-versioned JSONL stream
(``bench_results/ledger/ledger.jsonl``) that every measured number in
the repo flows through — bench race results, tune winners, serving SLO
reports, pulse window summaries, scale-ladder rungs, error-vs-iteration
curves — keyed by the graft-tune structure hash plus executor knobs,
platform, host load, and git revision.  See ``ledger/store.py`` for the
integrity model, ``ledger/gate.py`` for drift detection,
``ledger/probe.py`` for the accuracy probe, ``ledger/export.py`` for
the legacy ``BENCH_r*.json`` bridge, and ``cli/graft_ledger.py`` for
the operator surface.
"""

from arrow_matrix_tpu.ledger.store import (  # noqa: F401
    DEFAULT_LEDGER_DIR,
    KINDS,
    LEDGER_BASENAME,
    SCHEMA_VERSION,
    Ledger,
    bench_metric,
    canonical_record_id,
    default_ledger,
    ledger_dir,
    ledger_path,
    record,
    schema_problems,
)
