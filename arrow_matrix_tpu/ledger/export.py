"""Legacy-schema bridge: BENCH_r*.json ⇄ the ledger.

The repo's bench trajectory is a series of round files
(``BENCH_r01.json`` … ``BENCH_r05.json``) with a fixed shape —
``{n, cmd, rc, tail, parsed}`` where ``tail``'s LAST line is the JSON
measurement record (the ``utils/artifacts.parse_last_json_line``
contract every round has honored).  The ledger supersedes the format
but must not orphan the series: rounds 6–10 landed no BENCH file at
all (ROADMAP), and downstream tooling still reads the old shape.

Two directions:

* **ingest** (``ingest_legacy_bench`` / ``ingest_tune_plans``) — load
  the committed history INTO the ledger, so the very first baseline
  has real medians to band against.  Rounds whose ``parsed`` is null
  (r01 predates the parsed contract) are skipped with a note, never
  invented.
* **export** (``export_legacy_round``) — regenerate the legacy shape
  FROM ledger records, so ``BENCH_r06.json`` is produced by
  ``graft_ledger export``, not hand-written.  The exported ``parsed``
  starts from the newest bench record's parsed payload verbatim
  (``degraded``/``backend_probe_class`` and the rest of the r02–r05
  vocabulary survive untouched) and gains four ledger-era sections:
  ``tuned`` (winner-vs-default per structure), ``serving`` (the SLO
  report numbers), ``error_curves`` (final relative-Frobenius per
  dtype per structure), and ``ledger`` (store head + count — the
  provenance pointer).  Export reads only committed records and adds
  no fresh timestamps, so exporting twice from the same store is
  byte-identical (pinned by tests/test_ledger.py against the
  checked-in BENCH_r06.json).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from arrow_matrix_tpu.ledger import store
from arrow_matrix_tpu.utils.artifacts import atomic_write_json

#: parsed-section fields every legacy round since r02 has carried;
#: export refuses to emit a round missing any of them.
LEGACY_PARSED_REQUIRED = ("metric", "value", "unit", "vs_baseline",
                          "config", "platform", "device_kind")

LEGACY_TOP_REQUIRED = ("n", "cmd", "rc", "tail", "parsed")


def validate_legacy(doc: Any) -> List[str]:
    """Problems with one legacy round document (empty = valid)."""
    if not isinstance(doc, dict):
        return ["round document is not a JSON object"]
    problems = [f"missing top-level field {f!r}"
                for f in LEGACY_TOP_REQUIRED if f not in doc]
    parsed = doc.get("parsed")
    if parsed is not None:
        if not isinstance(parsed, dict):
            problems.append("parsed is neither null nor an object")
        else:
            problems += [f"parsed missing field {f!r}"
                         for f in LEGACY_PARSED_REQUIRED
                         if f not in parsed]
    return problems


def ingest_legacy_bench(ledger: store.Ledger,
                        paths: List[str]) -> Tuple[int, List[str]]:
    """Append one ``kind="bench"`` record per legacy round file whose
    ``parsed`` is non-null.  Returns ``(ingested, notes)``.  The whole
    parsed record rides in the payload — ingest preserves, never
    summarizes."""
    notes: List[str] = []
    count = 0
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        problems = validate_legacy(doc)
        if problems:
            notes.append(f"skip {path}: {'; '.join(problems)}")
            continue
        parsed = doc.get("parsed")
        if parsed is None:
            notes.append(f"skip {path}: parsed is null (pre-contract "
                         f"round)")
            continue
        ledger.record(
            "bench",
            store.bench_metric(parsed["metric"],
                               parsed.get("config")),
            parsed["value"],
            unit=parsed["unit"],
            structure_hash=None,  # legacy rounds predate fingerprints
            platform=parsed["platform"],
            device_kind=parsed["device_kind"],
            host_load=None,       # legacy rounds captured no loadavg
            knobs={"legacy_round": doc["n"],
                   "config": parsed.get("config", {})},
            payload={"parsed": parsed, "cmd": doc["cmd"],
                     "rc": doc["rc"], "source_file":
                         os.path.basename(path)})
        count += 1
    return count, notes


def ingest_tune_plans(ledger: store.Ledger,
                      plan_dir: str) -> Tuple[int, List[str]]:
    """Append one ``kind="tune"`` record per (structure, k) winner in
    the committed plan cache — the tuned-vs-default margins the r06
    export and the baseline both band on."""
    notes: List[str] = []
    count = 0
    try:
        names = sorted(os.listdir(plan_dir))
    except OSError as e:
        return 0, [f"skip {plan_dir}: {e}"]
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(plan_dir, name)
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        plans = doc.get("plans")
        shash = doc.get("structure_hash")
        if not isinstance(plans, dict) or not shash:
            notes.append(f"skip {path}: no plans/structure_hash")
            continue
        for k_str, plan in sorted(plans.items(),
                                  key=lambda kv: int(kv[0])):
            load = plan.get("host_load") or {}
            # k rides in the metric name: a k=16 and a k=128 timing of
            # the same structure must never share a drift band.
            ledger.record(
                "tune", f"tuned_spmm_ms_k{int(k_str)}",
                plan.get("measured_ms"),
                unit="ms", structure_hash=shash,
                platform=plan.get("platform"),
                device_kind="host" if plan.get("platform") == "cpu"
                else plan.get("platform"),
                host_load=load.get("loadavg_1m"),
                knobs={"k": int(k_str),
                       "candidate": plan.get("candidate"),
                       "kernel": plan.get("kernel"),
                       "fmt": plan.get("fmt"),
                       "chunk": plan.get("chunk"),
                       "overlap_slabs": plan.get("overlap_slabs"),
                       "feature_dtype": plan.get("feature_dtype")},
                payload={"default_ms": plan.get("default_ms"),
                         "margin": plan.get("margin"),
                         "bit_identical": plan.get("bit_identical"),
                         "evaluator": plan.get("evaluator"),
                         "source": doc.get("context", {}).get(
                             "source")})
            count += 1
    return count, notes


def _newest(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    return records[-1] if records else None


def compose_round(ledger: store.Ledger, round_n: int,
                  head: Optional[str] = None) -> Dict[str, Any]:
    """Build the legacy round document from the store (pure read — no
    timestamps, no environment).  Raises ``ValueError`` when the store
    has no bench record to anchor the parsed section on.

    ``head`` pins the export to the chain PREFIX ending at that record
    id — the provenance pointer every exported round records under
    ``parsed.ledger.head``.  Re-exporting a historical round through
    its own recorded head is byte-identical even after the store has
    grown past it (the chain is append-only, so the prefix below a
    record id never changes); without ``head`` the round snapshots the
    whole store."""
    records = ledger.read_all()
    if head is not None:
        ids = [r.get("record_id") for r in records]
        if head not in ids:
            raise ValueError(f"head record {head!r} is not in the "
                             f"store chain at {ledger.path}")
        records = records[:ids.index(head) + 1]

    def view(kind: str) -> List[Dict[str, Any]]:
        return [r for r in records if r.get("kind") == kind]

    # Anchor on the newest bench record that carries a parsed payload:
    # bench-kind records are also used for raw measurements (e.g. the
    # reshard peak-HBM probes), and those cannot seed a legacy round's
    # parsed section.
    bench = _newest([r for r in view("bench")
                     if r.get("payload", {}).get("parsed")])
    if bench is None:
        raise ValueError("export needs at least one bench record with "
                         "a parsed payload in the ledger (run "
                         "`graft_ledger ingest` or a bench round "
                         "first)")
    parsed = dict(bench.get("payload", {}).get("parsed") or {})

    tuned: List[Dict[str, Any]] = []
    for rec in view("tune"):
        payload = rec.get("payload", {})
        tuned.append({
            "structure_hash": rec.get("structure_hash"),
            "k": rec.get("knobs", {}).get("k"),
            "candidate": rec.get("knobs", {}).get("candidate"),
            "tuned_ms": rec.get("value"),
            "default_ms": payload.get("default_ms"),
            "margin": payload.get("margin"),
            "bit_identical": payload.get("bit_identical"),
        })

    serving = None
    serve = _newest(view("serve"))
    if serve is not None:
        sp = serve.get("payload", {})
        serving = {
            "requests": sp.get("requests"),
            "completed": sp.get("completed"),
            "failed": sp.get("failed"),
            "shed": sp.get("shed"),
            "rejected": sp.get("rejected"),
            "requests_per_s": serve.get("value"),
            "latency_ms": sp.get("latency_ms"),
            "structure_hash": serve.get("structure_hash"),
            "record_id": serve.get("record_id"),
        }

    error_curves: List[Dict[str, Any]] = []
    for rec in view("error_curve"):
        error_curves.append({
            "metric": rec.get("metric"),
            "dtype": rec.get("knobs", {}).get("dtype"),
            "emulated": rec.get("knobs", {}).get("emulated"),
            "structure_hash": rec.get("structure_hash"),
            "iterations": rec.get("knobs", {}).get("iterations"),
            "final_rel_frobenius": rec.get("value"),
            "rel_frobenius": rec.get("payload", {}).get(
                "rel_frobenius"),
            "record_id": rec.get("record_id"),
        })

    parsed["tuned"] = tuned
    parsed["serving"] = serving
    parsed["error_curves"] = error_curves
    parsed["ledger"] = {
        "records": len(records),
        "head": records[-1].get("record_id") if records else None,
        "store": ledger.path,
        "bench_record_id": bench.get("record_id"),
    }
    # tail contract: the measurement record is the LAST line (the
    # parse_last_json_line convention every legacy round honors).
    tail = json.dumps(parsed, sort_keys=True) + "\n"
    return {"n": round_n,
            "cmd": f"graft_ledger export --round {round_n}",
            "rc": 0, "tail": tail, "parsed": parsed}


def export_legacy_round(ledger: store.Ledger, round_n: int,
                        out_path: str,
                        head: Optional[str] = None) -> Dict[str, Any]:
    """Compose + validate + atomically write one legacy round file.
    When ``head`` is omitted and ``out_path`` already exists, the
    export pins itself to the existing file's recorded
    ``parsed.ledger.head`` — regenerating a round is byte-identical by
    construction, never silently rebased onto a grown store."""
    if head is None and os.path.exists(out_path):
        with open(out_path, encoding="utf-8") as fh:
            prior = json.load(fh)
        head = ((prior.get("parsed") or {}).get("ledger")
                or {}).get("head")
    doc = compose_round(ledger, round_n, head=head)
    problems = validate_legacy(doc)
    if problems:
        raise ValueError(f"composed round fails the legacy schema: "
                         f"{problems}")
    atomic_write_json(out_path, doc, indent=1, sort_keys=True)
    return doc
