"""The graft-ledger record store: one append-only, schema-validated,
hash-chained JSONL file that is the single sink for every measured
number in the repo.

Before the ledger, each subsystem persisted its own snapshot — bench
rounds as ``BENCH_r*.json``, tune winners inside plan files, serving
SLO reports as ``serve_summary.json``, pulse windows in a ring, ladder
rungs in ``scale_ladder.json`` — with no shared key, no history, and
no way to ask "did this number regress?".  Every emitter now ALSO
writes one :func:`Ledger.record` line keyed by the graft-tune
structure hash (``tune/fingerprint.py``) plus the executor knobs,
platform/device_kind, host load, and git revision, so the repo's whole
measured history is one queryable stream under
``bench_results/ledger/ledger.jsonl``.

Integrity model (pinned by tests/test_ledger.py):

* **append-only by construction** — records are only ever appended
  (``utils/artifacts.append_jsonl``: serialized first, one write,
  flushed + fsync'd; a crash can tear at most the trailing line);
* **tamper-evident by hash chain** — every record's ``record_id`` is
  the sha256 of its own canonical JSON (sans the id field), and every
  record carries ``prev`` = the preceding record's id.  Editing any
  historical line breaks its own id; deleting or reordering one breaks
  the successor's ``prev`` link.  :meth:`Ledger.validate` walks the
  chain and reports every break — schema drift that
  ``tools/ledger_gate.py`` turns into a nonzero exit;
* **versioned schema** — ``schema`` is checked per record; a record
  from another schema version is a validation problem, never a silent
  reinterpretation.

The default store location is ``bench_results/ledger/`` (override:
``AMT_LEDGER_DIR``; ``AMT_LEDGER=0`` disables the module-level
:func:`record` hook entirely — emitters stay measurement-only).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Iterable, List, Optional

from arrow_matrix_tpu.utils.artifacts import append_jsonl, locked_file

#: Bump when the record shape changes; old records then fail
#: validation LOUDLY instead of being silently reinterpreted.
SCHEMA_VERSION = 1

#: The emitter families.  A record's ``kind`` names which subsystem
#: measured it — the coarse query axis (`graft_ledger report --kind`).
KINDS = ("bench", "tune", "serve", "pulse", "ladder", "smoke",
         "error_curve", "probe", "fleet", "kcert", "xray", "lens")

DEFAULT_LEDGER_DIR = os.path.join("bench_results", "ledger")
LEDGER_BASENAME = "ledger.jsonl"

#: Fields every record must carry, with their accepted types.  ``None``
#: inside a tuple marks the field as nullable.
_FIELD_TYPES: Dict[str, tuple] = {
    "schema": (int,),
    "kind": (str,),
    "record_id": (str,),
    "prev": (str, None),
    "ts_unix": (int, float),
    "metric": (str,),
    "value": (int, float, None),
    "unit": (str, None),
    "structure_hash": (str, None),
    "platform": (str, None),
    "device_kind": (str, None),
    "host_load": (int, float, None),
    "git_rev": (str, None),
    "knobs": (dict,),
    "payload": (dict,),
}


def ledger_dir(override: Optional[str] = None) -> str:
    """The store directory: explicit override, else ``AMT_LEDGER_DIR``,
    else ``bench_results/ledger``."""
    if override:
        return override
    return os.environ.get("AMT_LEDGER_DIR", DEFAULT_LEDGER_DIR)


def ledger_path(directory: Optional[str] = None) -> str:
    return os.path.join(ledger_dir(directory), LEDGER_BASENAME)


def canonical_record_id(rec: Dict[str, Any]) -> str:
    """``"lr" + sha256(canonical JSON of the record minus record_id)``
    truncated to 16 hex chars.  ``prev`` IS part of the hashed content,
    so the ids form a chain: no historical line can change without
    breaking either its own id or its successor's ``prev``."""
    body = {k: v for k, v in rec.items() if k != "record_id"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return "lr" + hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def schema_problems(rec: Any, index: Optional[int] = None) -> List[str]:
    """Structural problems of ONE record (empty = valid).  Pure
    function over the parsed object — shared by :meth:`Ledger.validate`,
    the gate, and the doctor probe."""
    where = f"record {index}" if index is not None else "record"
    if not isinstance(rec, dict):
        return [f"{where}: not a JSON object"]
    problems = []
    for field, types in _FIELD_TYPES.items():
        if field not in rec:
            problems.append(f"{where}: missing field {field!r}")
            continue
        v = rec[field]
        if v is None:
            if None not in types:
                problems.append(f"{where}: field {field!r} is null")
            continue
        # bool is an int subclass; a True value is never a number here.
        if isinstance(v, bool) or not isinstance(
                v, tuple(t for t in types if t is not None)):
            problems.append(
                f"{where}: field {field!r} has type "
                f"{type(v).__name__}, expected "
                f"{'/'.join(t.__name__ for t in types if t)}")
    if not problems:
        if rec["schema"] != SCHEMA_VERSION:
            problems.append(
                f"{where}: schema version {rec['schema']} != runtime "
                f"{SCHEMA_VERSION}")
        if rec["kind"] not in KINDS:
            problems.append(f"{where}: unknown kind {rec['kind']!r}")
    return problems


def _git_rev() -> Optional[str]:
    """The working tree's short revision, cached for the process.
    ``AMT_GIT_REV`` overrides (hermetic tests, exported environments);
    any git failure degrades to None — provenance, not a requirement."""
    env = os.environ.get("AMT_GIT_REV")
    if env is not None:
        return env or None
    global _GIT_REV_CACHE
    if _GIT_REV_CACHE is _UNSET:
        try:
            proc = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10)
            _GIT_REV_CACHE = (proc.stdout.strip()
                              if proc.returncode == 0
                              and proc.stdout.strip() else None)
        except (OSError, subprocess.SubprocessError):
            _GIT_REV_CACHE = None
    return _GIT_REV_CACHE


_UNSET = object()
_GIT_REV_CACHE: Any = _UNSET


def _default_host_load() -> Optional[float]:
    try:
        from arrow_matrix_tpu.utils.platform import host_load

        return float(host_load()["loadavg_1m"])
    except (ImportError, KeyError, TypeError, ValueError, OSError):
        return None


class Ledger:
    """One JSONL store (see the module docstring for the contract)."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = ledger_dir(directory)
        self.path = ledger_path(directory)

    # -- writing -------------------------------------------------------

    def record(self, kind: str, metric: str,
               value: Optional[float] = None, *,
               unit: Optional[str] = None,
               structure_hash: Optional[str] = None,
               knobs: Optional[Dict[str, Any]] = None,
               payload: Optional[Dict[str, Any]] = None,
               platform: Optional[str] = None,
               device_kind: Optional[str] = None,
               host_load: Any = _UNSET,
               git_rev: Any = _UNSET,
               ts_unix: Optional[float] = None) -> Dict[str, Any]:
        """Append ONE record; returns it (with ``record_id`` set).

        ``host_load`` and ``git_rev`` default to live lookups (1-minute
        loadavg, ``git rev-parse``); pass an explicit value — including
        None — to pin them.  Raises ``ValueError`` on an invalid record
        (unknown kind, unserializable knobs/payload): a ledger line is
        a contract, not a log line.
        """
        # The prev-link read and the append are ONE critical section
        # under the cross-process advisory lock: two fleet workers
        # recording concurrently would otherwise both read the same
        # tail and fork the hash chain (one torn `prev` link).
        with locked_file(self.path):
            rec: Dict[str, Any] = {
                "schema": SCHEMA_VERSION,
                "kind": kind,
                "record_id": "",
                "prev": (self.last_record() or {}).get("record_id"),
                "ts_unix": round(time.time(), 3) if ts_unix is None
                else ts_unix,
                "metric": metric,
                "value": value,
                "unit": unit,
                "structure_hash": structure_hash,
                "platform": platform,
                "device_kind": device_kind,
                "host_load": (_default_host_load()
                              if host_load is _UNSET else host_load),
                "git_rev": _git_rev() if git_rev is _UNSET
                else git_rev,
                "knobs": dict(knobs or {}),
                "payload": dict(payload or {}),
            }
            rec["record_id"] = canonical_record_id(rec)
            problems = schema_problems(rec)
            if problems:
                raise ValueError(f"refusing to append an invalid "
                                 f"ledger record: {problems}")
            append_jsonl(self.path, rec, lock=False)
        return rec

    # -- reading -------------------------------------------------------

    def read_all(self) -> List[Dict[str, Any]]:
        """Every parseable record, in file order.  A torn TRAILING line
        (the one crash window the append contract allows) is skipped
        here and reported by :meth:`validate`."""
        records, _ = self._read_with_problems()
        return records

    def _read_with_problems(self):
        records: List[Dict[str, Any]] = []
        problems: List[str] = []
        if not os.path.exists(self.path):
            return records, problems
        with open(self.path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    problems.append(
                        f"line {i + 1}: torn trailing line (crash "
                        f"mid-append?) — truncate it to repair")
                else:
                    problems.append(f"line {i + 1}: unparseable (the "
                                    f"file was edited in place?)")
                continue
            records.append(rec)
        return records, problems

    def last_record(self) -> Optional[Dict[str, Any]]:
        records = self.read_all()
        return records[-1] if records else None

    def query(self, *, kind: Optional[str] = None,
              metric: Optional[str] = None,
              structure_hash: Optional[str] = None,
              platform: Optional[str] = None
              ) -> List[Dict[str, Any]]:
        out = []
        for rec in self.read_all():
            if not isinstance(rec, dict):
                continue
            if kind is not None and rec.get("kind") != kind:
                continue
            if metric is not None and rec.get("metric") != metric:
                continue
            if (structure_hash is not None
                    and rec.get("structure_hash") != structure_hash):
                continue
            if platform is not None and rec.get("platform") != platform:
                continue
            out.append(rec)
        return out

    # -- integrity -----------------------------------------------------

    def validate(self) -> List[str]:
        """Every schema and chain problem in the store (empty = clean).
        The append-only promise is verified, not assumed: a rewritten
        line fails its own id, a removed/reordered line breaks the
        successor's ``prev`` link."""
        records, problems = self._read_with_problems()
        prev_id: Optional[str] = None
        for i, rec in enumerate(records):
            problems += schema_problems(rec, index=i)
            if not isinstance(rec, dict):
                prev_id = None
                continue
            claimed = rec.get("record_id")
            if isinstance(claimed, str):
                expect = canonical_record_id(rec)
                if claimed != expect:
                    problems.append(
                        f"record {i}: record_id {claimed} does not "
                        f"match its content (expected {expect}) — the "
                        f"line was edited in place")
            if rec.get("prev") != prev_id:
                problems.append(
                    f"record {i}: prev={rec.get('prev')} breaks the "
                    f"chain (expected {prev_id}) — a record was "
                    f"removed, reordered, or appended out of band")
            prev_id = claimed if isinstance(claimed, str) else None
        return problems


def bench_metric(metric: str, config: Optional[Dict[str, Any]]) -> str:
    """The metric name for a bench record: the problem shape rides in
    the name (``spmm_iter_ms_n1048576_w2048``) because bench records
    carry no structure hash — without the shape in the key, rounds
    measured at different scales would share one drift band and the
    gate would flag growth as regression."""
    cfg = config or {}
    n, width = cfg.get("n"), cfg.get("width")
    if n and width:
        return f"{metric}_n{n}_w{width}"
    return metric


def default_ledger() -> Ledger:
    return Ledger()


def record(kind: str, metric: str, value: Optional[float] = None,
           directory: Optional[str] = None,
           **kwargs) -> Optional[Dict[str, Any]]:
    """Module-level emitter hook: append to the DEFAULT store
    (``AMT_LEDGER_DIR`` / ``bench_results/ledger``), or to an explicit
    ``directory`` (smoke runs pass a run-dir-local store so gates and
    tests never dirty the committed ledger).  ``AMT_LEDGER=0``
    disables it (returns None).  Emitters call this at the end of a
    measurement; a failure to persist is reported to stderr and
    returns None — telemetry must never take down the run that
    produced the number."""
    if os.environ.get("AMT_LEDGER", "1") == "0":
        return None
    try:
        return Ledger(directory).record(kind, metric, value, **kwargs)
    except (OSError, ValueError, TypeError) as e:
        print(f"[ledger] failed to append {kind}/{metric} record: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None


def records_from(paths_or_records: Iterable[Any]) -> List[Dict[str, Any]]:
    """Normalize a mixed list of record dicts / ledger paths into one
    record list (gate + CLI helper)."""
    out: List[Dict[str, Any]] = []
    for item in paths_or_records:
        if isinstance(item, dict):
            out.append(item)
        else:
            lg = Ledger(os.path.dirname(str(item))) \
                if str(item).endswith(".jsonl") else Ledger(str(item))
            if str(item).endswith(".jsonl"):
                lg.path = str(item)
            out.extend(lg.read_all())
    return out
