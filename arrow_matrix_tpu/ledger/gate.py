"""Drift detection over the ledger (the ``tools/ledger_gate.py`` CLI).

A baseline is a per-``(kind, metric, structure_hash, platform)``
summary of the committed ledger's history: the robust center (median)
and spread (MAD) of the host-load-normalized values, plus the pinned
reference curve for ``error_curve`` records.  ``check_records``
compares fresh records against it and reports three failure families,
each of which makes the CLI exit nonzero:

* **perf regression** — a lower-is-better metric (unit ``ms``/``s``)
  whose normalized value exceeds
  ``median + max(band_k·1.4826·MAD, rel_floor·median)``.  The MAD term
  absorbs real run-to-run noise; the relative floor (default 5%)
  guarantees a planted 10% regression trips even on a low-variance
  baseline where the MAD band alone would be microscopic.  Host-load
  normalization (``value / (1 + loadavg_1m)``) keeps a number measured
  on a loaded host from tripping (or masking) the band;
* **accuracy-curve regression** — any point of a fresh error curve
  exceeding ``curve_factor ×`` the baseline curve's point (with an
  absolute floor so a zero baseline — the f32 curve — still has a
  meaningful threshold: any f32 error above the floor is a
  bit-identity break);
* **schema drift** — records failing ``store.schema_problems`` or a
  store failing chain validation;
* **kcert regression** — a ``kind="kcert"`` rule-count record (the
  kernel certifier's passing KC-rule tally, graft-kcert) falling
  below the baseline median: certified rules may only be added,
  never silently lost;
* **lens miscalibration** — a ``kind="lens"`` ratio record (the
  compute cost model's measured/predicted ratio, graft-lens) outside
  the absolute calibration band ``[0.5, 2.0]``, or drifted more than
  ``LENS_DRIFT_FACTOR×`` from the baseline median ratio: a model that
  stops predicting within 2× of reality (or quietly walks away from
  its committed calibration) must not keep pruning tune candidates.
  Ratios are load-invariant (both sides of the division ran under the
  same load), so the comparison is on the raw value, never
  host-load-normalized.  Lens ``ms`` records band like any other
  timing metric.

Keys absent from the baseline are reported as NEW, never as failures —
a new structure/metric must not block the ledger that is trying to
record it for the first time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from arrow_matrix_tpu.ledger import store
from arrow_matrix_tpu.utils.artifacts import atomic_write_json

BASELINE_VERSION = 1
BASELINE_BASENAME = "baseline.json"

#: Band width in robust standard deviations (1.4826·MAD ≈ σ for
#: normal noise): generous — the gate hunts regressions, not noise.
BAND_K = 4.0

#: Relative floor on the band: a value more than 5% above the median
#: fails even when the MAD band is tighter than that.  Pinned by the
#: planted-10%-regression test.
REL_FLOOR = 0.05

#: A fresh error-curve point may be at most this factor above the
#: baseline point before it is an accuracy regression.
CURVE_FACTOR = 2.0

#: Absolute floor for curve comparison: baseline points below this
#: (including the all-zero f32 curve) use the floor as the reference,
#: so "anything above 2e-6" trips on a zero baseline.
CURVE_FLOOR = 1e-6

#: Units where larger means worse.  Everything else (errors included —
#: error curves have their own pointwise check) is compared the same
#: way on ``value``; unit-less counts are skipped for banding.
#: "B" (bytes) bands the graft-xray wire metrics: replacing the
#: base64 wire must show up as a gated byte DROP, and a frame-size
#: regression fails like a latency regression does.
_LOWER_IS_BETTER_UNITS = {"ms", "s", "B"}

#: Absolute calibration band for graft-lens measured/predicted ratio
#: records — mirrors ``obs/lens.py``'s LENS_RATIO_MIN/MAX (ISSUE 18
#: acceptance band; the two constants are pinned equal by
#: tests/test_lens.py).
LENS_RATIO_MIN = 0.5
LENS_RATIO_MAX = 2.0

#: A fresh lens ratio may drift at most this factor from the baseline
#: median ratio (in either direction) before the model is declared
#: miscalibrated relative to its committed calibration.
LENS_DRIFT_FACTOR = 1.5

#: graft-host satellite: a non-exact traffic class (graft-xray
#: ``iter_ms_<cls>`` records) must keep its latency within this
#: factor of the exact class measured on the same structure/platform.
#: Reduced-precision carriage that is byte-cheaper but TIME-slower is
#: a regression the per-key band cannot see (each class drifts inside
#: its own band); this cross-class check fails it loudly.
XRAY_CLASS_FACTOR = 1.5


def baseline_key(rec: Dict[str, Any]) -> str:
    return "|".join(str(rec.get(f)) for f in
                    ("kind", "metric", "structure_hash", "platform"))


def is_degraded(rec: Dict[str, Any]) -> bool:
    """True when the record's measurement self-reports a degraded
    environment (bench.py CPU fallback after an accelerator probe
    failure: ``parsed.degraded``).  Degraded numbers are kept in the
    ledger — they are the honest history — but excluded from banding
    in BOTH directions: they must not trip the gate, and they must not
    widen the band a clean number is compared against."""
    parsed = (rec.get("payload") or {}).get("parsed")
    return bool(isinstance(parsed, dict) and parsed.get("degraded"))


def normalized_value(rec: Dict[str, Any]) -> Optional[float]:
    """Host-load-normalized value: ``value / (1 + loadavg_1m)``.
    Records without a load snapshot (or with the -1 "unknown" marker)
    normalize by 1."""
    v = rec.get("value")
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return None
    load = rec.get("host_load")
    if isinstance(load, (int, float)) and not isinstance(load, bool) \
            and load >= 0:
        return float(v) / (1.0 + float(load))
    return float(v)


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    m = len(s) // 2
    return s[m] if len(s) % 2 else 0.5 * (s[m - 1] + s[m])


def _mad(vals: Sequence[float], med: float) -> float:
    return _median([abs(v - med) for v in vals])


def build_baseline(records: List[Dict[str, Any]],
                   band_k: float = BAND_K,
                   rel_floor: float = REL_FLOOR) -> Dict[str, Any]:
    """Summarize a record list into a baseline document.  Banded
    metrics keep median/MAD/count over normalized values; error-curve
    keys pin the NEWEST curve (the committed reference) instead of
    averaging — curves are deterministic at fixed seed, so the newest
    one IS the contract."""
    banded: Dict[str, List[float]] = {}
    curves: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if store.schema_problems(rec):
            continue
        key = baseline_key(rec)
        if rec["kind"] == "error_curve":
            payload = rec.get("payload", {})
            curve = payload.get("rel_frobenius")
            if isinstance(curve, list):
                curves[key] = {
                    "rel_frobenius": [float(p) for p in curve],
                    "record_id": rec.get("record_id"),
                    "knobs": dict(rec.get("knobs", {})),
                }
            continue
        if is_degraded(rec):
            continue
        nv = normalized_value(rec)
        if nv is None:
            continue
        banded.setdefault(key, []).append(nv)
    metrics: Dict[str, Any] = {}
    for key, vals in banded.items():
        med = _median(vals)
        mad = _mad(vals, med)
        unit = None
        for rec in records:
            if baseline_key(rec) == key and rec.get("unit"):
                unit = rec["unit"]
        metrics[key] = {"median": med, "mad": mad, "count": len(vals),
                        "unit": unit}
    return {"version": BASELINE_VERSION, "band_k": band_k,
            "rel_floor": rel_floor, "metrics": metrics,
            "curves": curves}


def band_upper(entry: Dict[str, Any], band_k: float,
               rel_floor: float) -> float:
    med = float(entry["median"])
    mad = float(entry["mad"])
    return med + max(band_k * 1.4826 * mad, rel_floor * abs(med))


def check_records(records: List[Dict[str, Any]],
                  baseline: Dict[str, Any], *,
                  band_k: Optional[float] = None,
                  rel_floor: Optional[float] = None,
                  curve_factor: float = CURVE_FACTOR,
                  curve_floor: float = CURVE_FLOOR
                  ) -> Tuple[List[str], List[str]]:
    """``(failures, notes)``: failures are regressions/schema drift
    (nonzero exit), notes are informational (new keys, skipped
    records)."""
    band_k = baseline.get("band_k", BAND_K) if band_k is None \
        else band_k
    rel_floor = baseline.get("rel_floor", REL_FLOOR) \
        if rel_floor is None else rel_floor
    metrics = baseline.get("metrics", {})
    curves = baseline.get("curves", {})
    failures: List[str] = []
    notes: List[str] = []
    for i, rec in enumerate(records):
        problems = store.schema_problems(rec, index=i)
        if problems:
            failures += [f"schema drift: {p}" for p in problems]
            continue
        key = baseline_key(rec)
        if rec["kind"] == "error_curve":
            base = curves.get(key)
            if base is None:
                notes.append(f"new curve key (no baseline): {key}")
                continue
            fresh = rec.get("payload", {}).get("rel_frobenius")
            if not isinstance(fresh, list):
                failures.append(f"schema drift: {key} error_curve "
                                f"record has no rel_frobenius curve")
                continue
            ref = base["rel_frobenius"]
            for j, (f, b) in enumerate(zip(fresh, ref)):
                limit = curve_factor * max(float(b), curve_floor)
                if float(f) > limit:
                    failures.append(
                        f"accuracy regression: {key} iteration {j}: "
                        f"{f:.3e} > {limit:.3e} "
                        f"(baseline {b:.3e} × {curve_factor})")
            if len(fresh) < len(ref):
                failures.append(
                    f"accuracy regression: {key} curve shortened "
                    f"({len(fresh)} < baseline {len(ref)} points)")
            continue
        if rec["kind"] == "kcert":
            # Kernel-certifier verdict counts (graft-kcert): the
            # number of passing KC rules must never shrink — fewer
            # rules passing than the baseline median means a kernel
            # or the certifier itself regressed.  Counts have no
            # host-load band; the comparison is direct.
            entry = metrics.get(key)
            if entry is None:
                notes.append(f"new metric key (no baseline): {key}")
                continue
            value = rec.get("value")
            if value is None:
                notes.append(f"no numeric value: {key}")
                continue
            if float(value) < float(entry["median"]):
                failures.append(
                    f"kcert regression: {key}: {float(value):.0f} "
                    f"passing rules < baseline median "
                    f"{entry['median']:.0f}")
            continue
        if rec["kind"] == "lens" and rec.get("unit") == "ratio":
            # Compute-model calibration (graft-lens): the
            # measured/predicted ratio must sit inside the absolute
            # band regardless of any baseline, and — once a baseline
            # exists — must not drift far from its committed median.
            # Raw value on purpose: a ratio is load-invariant.
            value = rec.get("value")
            if value is None:
                notes.append(f"no numeric value: {key}")
                continue
            v = float(value)
            if not (LENS_RATIO_MIN <= v <= LENS_RATIO_MAX):
                failures.append(
                    f"lens miscalibration: {key}: measured/predicted "
                    f"ratio {v:.3f} outside "
                    f"[{LENS_RATIO_MIN}, {LENS_RATIO_MAX}]")
                continue
            entry = metrics.get(key)
            if entry is None:
                notes.append(f"new metric key (no baseline): {key}")
                continue
            med = float(entry["median"])
            if med > 0 and not (med / LENS_DRIFT_FACTOR <= v
                                <= med * LENS_DRIFT_FACTOR):
                failures.append(
                    f"lens miscalibration: {key}: ratio {v:.3f} "
                    f"drifted > {LENS_DRIFT_FACTOR}x from baseline "
                    f"median {med:.3f}")
            continue
        if is_degraded(rec):
            notes.append(f"degraded measurement (unbanded): {key}")
            continue
        entry = metrics.get(key)
        if entry is None:
            notes.append(f"new metric key (no baseline): {key}")
            continue
        unit = rec.get("unit") or entry.get("unit")
        if unit not in _LOWER_IS_BETTER_UNITS:
            notes.append(f"unbanded unit {unit!r}: {key}")
            continue
        nv = normalized_value(rec)
        if nv is None:
            notes.append(f"no numeric value: {key}")
            continue
        upper = band_upper(entry, band_k, rel_floor)
        if nv > upper:
            failures.append(
                f"perf regression: {key}: normalized {nv:.4g} {unit} "
                f"> band {upper:.4g} (median {entry['median']:.4g}, "
                f"MAD {entry['mad']:.4g}, n={entry['count']})")
    f3, n3 = xray_class_problems(records, baseline)
    failures += f3
    notes += n3
    return failures, notes


def xray_class_problems(records: List[Dict[str, Any]],
                        baseline: Dict[str, Any],
                        factor: float = XRAY_CLASS_FACTOR
                        ) -> Tuple[List[str], List[str]]:
    """Cross-class latency check over graft-xray ``iter_ms_<cls>``
    records (see :data:`XRAY_CLASS_FACTOR`).  Classes are compared on
    the same ``(structure_hash, platform)`` cell; the exact reference
    is the fresh exact measurement when this batch carries one, else
    the committed baseline median for the exact key.  Same-batch
    comparison on purpose: both numbers then share the host load, so
    no load band is needed."""
    failures: List[str] = []
    notes: List[str] = []
    fresh: Dict[Tuple[str, str], Dict[str, float]] = {}
    for rec in records:
        metric = str(rec.get("metric") or "")
        if rec.get("kind") != "xray" \
                or not metric.startswith("iter_ms_") \
                or is_degraded(rec):
            continue
        value = rec.get("value")
        if value is None:
            continue
        cell = (str(rec.get("structure_hash")),
                str(rec.get("platform")))
        # Last write wins inside one batch — matches read_all order.
        fresh.setdefault(cell, {})[metric[len("iter_ms_"):]] = \
            float(value)
    metrics = baseline.get("metrics", {})
    for (shash, platform), by_cls in sorted(fresh.items()):
        exact = by_cls.get("exact")
        if exact is None:
            key = "|".join(("xray", "iter_ms_exact", shash, platform))
            entry = metrics.get(key)
            if entry is not None:
                exact = float(entry["median"])
        for cls in sorted(by_cls):
            if cls == "exact":
                continue
            if exact is None or exact <= 0:
                notes.append(
                    f"xray class {cls!r} has no exact reference "
                    f"(structure {shash}, {platform}) — class band "
                    f"skipped")
                continue
            v = by_cls[cls]
            if v > factor * exact:
                failures.append(
                    f"class regression: iter_ms_{cls} = {v:.4g} ms > "
                    f"{factor} x exact {exact:.4g} ms (structure "
                    f"{shash}, {platform}) — byte-cheaper but "
                    f"time-slower")
    return failures, notes


def baseline_path(directory: Optional[str] = None) -> str:
    return os.path.join(store.ledger_dir(directory), BASELINE_BASENAME)


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"baseline version {doc.get('version')} != "
                         f"runtime {BASELINE_VERSION}")
    return doc


def save_baseline(path: str, baseline: Dict[str, Any]) -> str:
    return atomic_write_json(path, baseline, indent=2, sort_keys=True)


def run_gate(ledger_dir: Optional[str] = None,
             baseline_file: Optional[str] = None,
             records: Optional[List[Dict[str, Any]]] = None
             ) -> Tuple[int, List[str]]:
    """The whole gate as a library call: validate the store (chain +
    schema), load the baseline, band every record.  Returns
    ``(exit_code, report_lines)``."""
    lg = store.Ledger(ledger_dir)
    lines: List[str] = []
    failures: List[str] = []
    chain = lg.validate()
    failures += [f"schema drift: {p}" for p in chain]
    recs = lg.read_all() if records is None else records
    bpath = baseline_file or baseline_path(ledger_dir)
    if not os.path.exists(bpath):
        lines.append(f"ledger_gate: no baseline at {bpath} — "
                     f"run `graft_ledger rebaseline` to create one")
        lines += [f"  FAIL {f}" for f in failures]
        return (1 if failures else 0), lines
    baseline = load_baseline(bpath)
    f2, notes = check_records(recs, baseline)
    failures += f2
    lines.append(f"ledger_gate: {len(recs)} records vs "
                 f"{len(baseline.get('metrics', {}))} banded keys + "
                 f"{len(baseline.get('curves', {}))} curves "
                 f"({bpath})")
    lines += [f"  FAIL {f}" for f in failures]
    lines += [f"  note {n}" for n in notes]
    lines.append("ledger_gate: FAIL" if failures else "ledger_gate: ok")
    return (1 if failures else 0), lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ledger_gate",
        description="drift gate over the graft-ledger record store")
    ap.add_argument("--ledger-dir", default=None,
                    help="store directory (default: AMT_LEDGER_DIR or "
                         "bench_results/ledger)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <ledger-dir>/"
                         f"{BASELINE_BASENAME})")
    ap.add_argument("--check", action="store_true",
                    help="gate the full store against the baseline "
                         "(the default action)")
    ap.add_argument("--rebaseline", action="store_true",
                    help="rebuild the baseline from the store and "
                         "write it")
    args = ap.parse_args(argv)
    if args.rebaseline:
        lg = store.Ledger(args.ledger_dir)
        problems = lg.validate()
        if problems:
            for p in problems:
                print(f"  FAIL schema drift: {p}")
            return 1
        bpath = args.baseline or baseline_path(args.ledger_dir)
        save_baseline(bpath, build_baseline(lg.read_all()))
        print(f"ledger_gate: baseline written to {bpath}")
        return 0
    rc, lines = run_gate(args.ledger_dir, args.baseline)
    for line in lines:
        print(line)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
