"""The shared retry/backoff/watchdog policy (graft-serve satellite).

:class:`~arrow_matrix_tpu.faults.supervisor.Supervisor` originally
carried its retry knobs as loose constructor arguments, which was fine
while exactly one caller (the batch CLIs via ``cli/common
.make_supervisor``) built supervisors.  graft-serve builds one
supervisor *per request*, and a serving runtime that hand-copies four
floats per request is how the batch and serving retry behaviors drift
apart.  :class:`RetryPolicy` is the one value-object both share: the
batch CLIs build it from their flags, the server holds a single
instance and stamps every per-request supervisor with it.

Jitter is deterministic and seedable: the classic thundering-herd fix
(±``jitter`` fraction on each backoff delay) is drawn from a
``random.Random`` seeded by ``(seed, salt, attempt)`` — string
seeding, which CPython derives from the bytes themselves, so two
processes (or a rerun of a chaos scenario) with the same seed sleep
the same schedule.  No wall-clock randomness anywhere, which is what
lets tools/serve_gate.py assert recovered runs bit-identical AND
replay-identical in shed/retry counts.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry / exponential-backoff / watchdog parameters.

    ``delay_s(attempt)`` is the sleep before retry ``attempt`` (1-based
    — the first retry sleeps ``backoff_s``, the next
    ``backoff_s * backoff_factor``, ...), with a deterministic
    ±``jitter`` fraction drawn from ``seed``/``salt``.  ``watchdog_s``
    of 0 disables the per-iteration watchdog.
    """

    max_retries: int = 2
    backoff_s: float = 0.05
    backoff_factor: float = 2.0
    jitter: float = 0.0          # fraction of the delay, in [0, 1]
    seed: int = 0
    watchdog_s: float = 0.0
    watchdog_grace_s: float = 30.0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got "
                             f"{self.max_retries}")
        if self.backoff_s < 0 or self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_s >= 0 and backoff_factor >= 1 required, got "
                f"{self.backoff_s}/{self.backoff_factor}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter is a fraction in [0, 1], got "
                             f"{self.jitter}")

    def delay_s(self, attempt: int, salt: str = "") -> float:
        """Backoff before retry ``attempt`` (1-based), jittered
        deterministically: same (seed, salt, attempt) -> same delay,
        across processes and reruns."""
        a = max(int(attempt), 1)
        base = self.backoff_s * self.backoff_factor ** (a - 1)
        if not self.jitter or not base:
            return base
        u = random.Random(
            f"{self.seed}:{salt}:{a}").uniform(-1.0, 1.0)
        return max(base * (1.0 + self.jitter * u), 0.0)

    def schedule(self, salt: str = "") -> tuple:
        """All ``max_retries`` delays, for logging/tests."""
        return tuple(self.delay_s(a, salt=salt)
                     for a in range(1, self.max_retries + 1))

    def for_worker(self, worker_id: str) -> "RetryPolicy":
        """The same policy re-seeded for one fleet worker: the seed is
        derived from ``(seed, worker_id)`` through sha256 (stable
        across processes, unlike ``hash()``), so N workers retrying
        the same dead dependency draw DIFFERENT jittered schedules —
        no thundering herd — while any one worker's schedule stays
        bit-reproducible at a fixed base seed."""
        digest = hashlib.sha256(
            f"{self.seed}:{worker_id}".encode("utf-8")).digest()
        derived = int.from_bytes(digest[:8], "big")
        return dataclasses.replace(self, seed=derived)

    @classmethod
    def from_args(cls, args, **overrides) -> "RetryPolicy":
        """Build from a CLI namespace carrying the ``add_heal_args``
        flags (absent attributes fall back to the defaults)."""
        kw = dict(
            max_retries=int(getattr(args, "max_retries", 2)),
            watchdog_s=float(getattr(args, "watchdog", 0.0) or 0.0),
            jitter=float(getattr(args, "retry_jitter", 0.0) or 0.0),
            seed=int(getattr(args, "seed", 0) or 0),
        )
        kw.update(overrides)
        return cls(**kw)
