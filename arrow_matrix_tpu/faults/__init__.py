"""graft-heal: deterministic fault injection + self-healing supervision.

Five consecutive bench rounds showed the dominant failure mode of the
long iterated ``X := A @ X`` runs is *runtime* faults — tunnel wedges
mid-transfer, SIGKILLed candidates, rounds silently degrading — and
until now recovery was folklore exercised only by real outages.  This
package turns it into a tested code path:

  * :mod:`~arrow_matrix_tpu.faults.plan` — a deterministic fault plan
    (``AMT_FAULT_PLAN`` env: JSON or a path to JSON) driving thin
    injection hooks at the existing seams (executor ``step()``, mesh
    collectives, routing-table builds, artifact loads).  With no plan
    set every hook is one ``None`` check — a literal no-op adding no
    trace-time collectives and no measurable latency.
  * :mod:`~arrow_matrix_tpu.faults.supervisor` — the self-healing
    iteration-loop supervisor shared by all three SpMM CLIs:
    per-iteration watchdog, exponential backoff + bounded retry,
    checkpoint resume/rollback, and a cheap jitted finite-check on X
    with rollback-to-checkpoint on NaN/Inf.  Every fault seen and
    every recovery taken is a flight-recorder + metrics event.

Gate: ``tools/chaos_gate.py`` runs the scenario matrix (hang, kill,
corrupt artifact, NaN burst) on small BA graphs and asserts each fault
is detected, recovered, and the recovered run's final X is
bit-identical to the fault-free run.
"""

from arrow_matrix_tpu.faults.plan import (
    FaultInjected,
    FaultPlan,
    active_plan,
    clear_plan,
    inject,
    on_step,
    reload_plan,
    set_plan,
)
from arrow_matrix_tpu.faults.policy import RetryPolicy
from arrow_matrix_tpu.faults.supervisor import (
    Abort,
    NonFiniteState,
    Supervisor,
    WatchdogStalled,
    WatchdogTimeout,
    state_is_finite,
)

__all__ = [
    "Abort",
    "FaultInjected",
    "FaultPlan",
    "NonFiniteState",
    "RetryPolicy",
    "Supervisor",
    "WatchdogStalled",
    "WatchdogTimeout",
    "active_plan",
    "clear_plan",
    "inject",
    "on_step",
    "reload_plan",
    "set_plan",
    "state_is_finite",
]
