"""Deterministic, seeded fault plans and the injection hooks.

A plan is one JSON object (or a path to a file holding one) in the
``AMT_FAULT_PLAN`` environment variable::

    AMT_FAULT_PLAN='{"scenario": "hang", "site": "*.step",
                     "after": 2, "hang_s": 1.0}'

Fields:

``scenario``
    ``hang``  — sleep ``hang_s`` seconds at the hook (past a
    supervisor watchdog this is indistinguishable from a wedged PJRT
    transfer, which is the point);
    ``kill``  — ``SIGKILL`` this process mid-iteration (the bench
    candidate timeout path; nothing in-process runs afterwards, so
    recovery is checkpoint resume in the NEXT process);
    ``error`` — raise :class:`FaultInjected` (a generic transient);
    ``nan``   — poison ``burst`` seeded positions of the carried X
    with NaN (the silent-corruption scenario);
    ``corrupt`` — raise the artifact-integrity error at an I/O hook
    (the in-process simulation of a truncated npy; ``tools/
    chaos_gate.py`` also corrupts real bytes on disk).

``site``
    fnmatch pattern against hook sites: ``multi_level.step``,
    ``sell_slim.step``, ``mesh.fetch_replicated``, ``mesh.put_global``,
    ``routing.build_route``, ``io.load_decomposition``.  ``*.step``
    matches every executor step hook.

``after`` / ``count``
    Fire on the ``after``-th matching hit (0-based, counted per
    process; an executor's untimed warmup step is hit 0) and keep
    firing for ``count`` hits (default 1 — one-shot).  Hit counting is
    the determinism story: no clocks, no randomness in *when*.

``seed`` / ``burst``
    The NaN scenario draws ``burst`` flat positions from
    ``default_rng(seed)`` — deterministic in *where*, too.

``target``
    Substring filter on the hook's target (I/O hooks pass the path);
    empty matches everything.

Hooks are literal no-ops when no plan is set: one module-global
``None`` check, no imports beyond stdlib at module import, and every
hook sits on the host side of the jit boundary — injection can never
add a trace-time collective to a compiled step.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import signal
import time
from typing import Any, Dict, Optional

ENV_VAR = "AMT_FAULT_PLAN"

SCENARIOS = ("hang", "kill", "error", "nan", "corrupt")


class FaultInjected(RuntimeError):
    """A fault deliberately raised by the active plan (scenario
    ``error`` / ``corrupt``) — the supervisor treats it like any other
    transient runtime failure."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One parsed fault plan (see module docstring for field
    semantics)."""

    scenario: str
    site: str = "*"
    after: int = 0
    count: int = 1
    hang_s: float = 1.0
    burst: int = 4
    seed: int = 0
    target: str = ""

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FaultPlan":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise ValueError(f"unknown fault-plan field(s) {unknown}; "
                             f"known: {sorted(known)}")
        plan = cls(**obj)
        if plan.scenario not in SCENARIOS:
            raise ValueError(f"unknown fault scenario "
                             f"{plan.scenario!r}; one of {SCENARIOS}")
        return plan


def parse_plan(spec: str) -> FaultPlan:
    """Parse a plan from a JSON string or a path to a JSON file."""
    text = spec.strip()
    if not text.startswith("{"):
        with open(text, encoding="utf-8") as fh:
            text = fh.read()
    return FaultPlan.from_json(json.loads(text))


# -- module state -----------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_HITS: Dict[str, int] = {}
_FIRED = 0


def _load_env() -> Optional[FaultPlan]:
    spec = os.environ.get(ENV_VAR)
    return parse_plan(spec) if spec else None


def set_plan(plan) -> None:
    """Install a plan (FaultPlan, plan dict, or None) and reset hit
    counters — the in-process test entry point."""
    global _PLAN, _FIRED
    if isinstance(plan, dict):
        plan = FaultPlan.from_json(plan)
    _PLAN = plan
    _HITS.clear()
    _FIRED = 0


def clear_plan() -> None:
    set_plan(None)


def reload_plan() -> Optional[FaultPlan]:
    """Re-read ``AMT_FAULT_PLAN`` (tests mutate the env mid-process;
    CLI subprocesses get the env read at import time)."""
    set_plan(_load_env())
    return _PLAN


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


# Env is read once at import: a CLI subprocess launched with
# AMT_FAULT_PLAN set is armed before any hook can run.
set_plan(_load_env())


# -- firing -----------------------------------------------------------------


def _matches(site: str, target: Optional[str]) -> bool:
    if _PLAN is None or not fnmatch.fnmatch(site, _PLAN.site):
        return False
    if _PLAN.target and (target is None or _PLAN.target not in target):
        return False
    return True


def _should_fire(site: str) -> bool:
    """Count this matching hit and decide whether the plan fires on it
    (hit counting is per site, so ``*.step`` plans are insensitive to
    how many OTHER hooks the run passes through)."""
    global _FIRED
    hit = _HITS.get(site, 0)
    _HITS[site] = hit + 1
    if _PLAN.after <= hit < _PLAN.after + _PLAN.count:
        _FIRED += 1
        return True
    return False


def _flight_event(site: str, **data) -> None:
    # obs.flight.record is a no-op until a recorder is installed; the
    # import is deferred so plan.py stays stdlib-only on the fast path.
    from arrow_matrix_tpu.obs import flight

    flight.record("fault", f"injected:{_PLAN.scenario}", site=site,
                  **data)


def inject(site: str, target: Optional[str] = None) -> None:
    """The generic injection hook: no-op without a matching armed plan;
    otherwise sleep (hang), die (kill), or raise (error / corrupt)."""
    if _PLAN is None:   # the always-taken production branch
        return
    if not _matches(site, target) or not _should_fire(site):
        return
    scenario = _PLAN.scenario
    _flight_event(site, target=target)
    if scenario == "hang":
        time.sleep(_PLAN.hang_s)
    elif scenario == "kill":
        # Flush anything buffered first: the whole point of the kill
        # scenario is proving the blackbox + checkpoint survive it.
        from arrow_matrix_tpu.obs import flight

        rec = flight.get_recorder()
        if rec is not None:
            rec.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    elif scenario == "corrupt":
        raise FaultInjected(
            f"injected corrupt-artifact fault at {site} "
            f"(target={target!r})")
    elif scenario == "error":
        raise FaultInjected(f"injected transient fault at {site}")
    # scenario "nan" is array-valued and only meaningful at on_step
    # hooks; at a generic site a matching nan plan burns its hit
    # harmlessly (the plan author picked the wrong site).


def on_step(site: str, x):
    """Executor-step hook: like :func:`inject`, but scenario ``nan``
    poisons and returns the carried feature array (hooks never mutate
    in place — jax arrays are functionally updated)."""
    if _PLAN is None:   # the always-taken production branch
        return x
    if not _matches(site, None) or not _should_fire(site):
        return x
    if _PLAN.scenario != "nan":
        # Re-credit the hit consumed above and let the scalar hook
        # re-consume it so hang/kill/error fire identically at step
        # sites.
        _HITS[site] -= 1
        inject(site)
        return x
    _flight_event(site, burst=_PLAN.burst)
    import numpy as np

    rng = np.random.default_rng(_PLAN.seed)
    size = 1
    for d in x.shape:
        size *= int(d)
    flat = rng.integers(0, max(size, 1),
                        size=min(_PLAN.burst, max(size, 1)))
    for i in sorted(set(int(v) for v in flat)):
        x = x.at[np.unravel_index(i, x.shape)].set(float("nan"))
    return x
