"""The self-healing iteration-loop supervisor.

All three SpMM CLIs run their iteration loop through one
:class:`Supervisor`: the loop body stays the CLI's own (timing spans,
validation, metrics), while the supervisor owns everything the paper's
50+-iteration production runs need when the machine misbehaves:

  * a per-iteration **watchdog** (``watchdog_s``): the body runs on a
    worker thread and a stalled iteration raises
    :class:`WatchdogTimeout` instead of wedging the run forever — the
    in-process analog of tools/tunnel_watcher.py's job-level timeout;
  * **bounded retry with exponential backoff**: transient failures
    (device errors, injected faults) re-run the same iteration from
    its entry state; ``max_retries`` consecutive failures end the run
    with a sealed flight recorder instead of a stack trace mid-loop;
  * a cheap **jitted finite-check** on the carried X each iteration:
    a NaN/Inf burst rolls back to the last checkpoint (or the
    iteration-entry state when none exists) rather than silently
    poisoning every subsequent iteration;
  * **checkpoint cadence + resume**: ``checkpoint_every`` saves ride
    utils/checkpoint.py (orbax or npz) and a fresh process resumes
    from the last one — the closed loop tools/chaos_gate.py proves
    bit-identical;
  * **flight-recorder + metrics events** for every fault seen and
    every recovery taken (kinds ``heal``/``fault`` in the blackbox;
    counters ``heal_faults`` / ``heal_recoveries`` in the registry).

Determinism contract: recovery re-runs the exact same compiled step
from the exact same state, so a recovered run's final X is
bit-identical to a fault-free run — asserted by tools/chaos_gate.py
for every scenario in the injection matrix.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Any, Callable, Optional

from arrow_matrix_tpu.faults.policy import RetryPolicy
from arrow_matrix_tpu.obs import flight


class Abort(Exception):
    """Unrecoverable, policy-level failure (validation gate, flag
    error): the supervisor never retries it."""


class WatchdogTimeout(RuntimeError):
    """An iteration exceeded the watchdog budget but the stalled
    attempt eventually drained — the iteration is retriable."""


class WatchdogStalled(RuntimeError):
    """An iteration exceeded the watchdog budget and never drained
    within the grace window: a genuine wedge.  In-process retry is
    impossible (the stalled thread cannot be killed); the supervisor
    seals the blackbox and re-raises so process-level recovery
    (checkpoint resume in a fresh process) takes over."""


class NonFiniteState(RuntimeError):
    """The carried X failed the finite-check after an iteration."""


@functools.lru_cache(maxsize=1)
def _finite_all():
    """One cached jitted reduction (the mesh.py ``_replicator`` idiom:
    a fresh jit per call would recompile every iteration)."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda a: jnp.all(jnp.isfinite(a)))


def state_is_finite(x) -> bool:
    """True when every element of ``x`` is finite.  One jitted
    all-reduce; the host reads back a single scalar — the iteration
    loop it guards already blocks on the step result, so this adds one
    tiny kernel, not a new sync point."""
    return bool(_finite_all()(x))


class Supervisor:
    """Run ``body(x, it) -> y`` for ``it`` in ``[start, stop)`` with
    watchdog / retry / rollback / checkpointing around it.

    ``carry=True`` threads ``y`` into the next iteration's ``x`` (the
    iterated ``X := A @ X`` run); ``carry=False`` keeps ``x`` fixed
    (the fresh-input benchmark loops).  ``layout`` tags checkpoints so
    a resume under a different execution mode fails loudly instead of
    silently permuting rows (utils/checkpoint.py).
    """

    def __init__(self, name: str, *, carry: bool = True,
                 watchdog_s: float = 0.0,
                 watchdog_grace_s: float = 30.0,
                 max_retries: int = 2,
                 backoff_s: float = 0.05,
                 backoff_factor: float = 2.0,
                 policy: Optional[RetryPolicy] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 finite_check: bool = True,
                 layout: Optional[str] = None,
                 registry=None,
                 tracer=None,
                 verbose: bool = True,
                 canonicalize: Optional[Callable[[Any], Any]] = None):
        self.name = name
        self.carry = carry
        # The retry/backoff/watchdog knobs live in one shared
        # RetryPolicy (faults/policy.py) so the batch CLIs and
        # graft-serve run the identical recovery behavior.  The loose
        # keyword form is kept for existing callers; an explicit
        # ``policy`` wins.
        if policy is None:
            policy = RetryPolicy(
                max_retries=int(max_retries),
                backoff_s=float(backoff_s),
                backoff_factor=float(backoff_factor),
                watchdog_s=float(watchdog_s or 0.0),
                watchdog_grace_s=float(watchdog_grace_s))
        self.policy = policy
        self.watchdog_s = float(policy.watchdog_s or 0.0)
        self.watchdog_grace_s = float(policy.watchdog_grace_s)
        self.max_retries = int(policy.max_retries)
        self.backoff_s = float(policy.backoff_s)
        self.backoff_factor = float(policy.backoff_factor)
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = int(checkpoint_every)
        self.finite_check = finite_check
        self.layout = layout
        self.registry = registry
        self.tracer = tracer
        self.verbose = verbose
        #: Optional device-level map applied to the carried state right
        #: before every save (graft-repl: the 2.5D executors carry
        #: per-replica-group PARTIAL slabs — ``fetch_replicated`` in the
        #: checkpoint layer would silently persist replica 0's partial
        #: view.  The executors' ``merge_carries`` produces the fully
        #: replicated canonical state, which is a bit-exact resume
        #: point because the step re-extracts each group's own slab).
        self.canonicalize = canonicalize
        self.faults_seen = 0
        self.recoveries = 0
        self.last_checkpoint_step: Optional[int] = None

    # -- events ------------------------------------------------------------

    def _span(self, name: str, **attrs):
        """A tracer span when graft-serve attached a tracer, else a
        no-op — attempt/resume/checkpoint phases then appear on the
        same request-correlated Perfetto track as the scheduler's
        admission and batch spans (the request context is ambient)."""
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, supervisor=self.name, **attrs)

    def _event(self, kind: str, name: str, **data) -> None:
        flight.record(kind, name, supervisor=self.name, **data)
        if self.registry is not None:
            self.registry.counter(f"heal_{name}",
                                  supervisor=self.name).inc()
        if self.verbose:
            extra = " ".join(f"{k}={v}" for k, v in data.items())
            print(f"[graft-heal {self.name}] {name} {extra}")

    def _fault(self, reason: str, it: int, err: Exception) -> None:
        self.faults_seen += 1
        self._event("fault", reason, iteration=it,
                    error=f"{type(err).__name__}: {err}")

    def _recovery(self, action: str, it: int, **data) -> None:
        self.recoveries += 1
        self._event("heal", action, iteration=it, **data)

    # -- checkpointing -----------------------------------------------------

    def resume(self, like) -> Optional[tuple]:
        """Load the last checkpoint (None when absent/not configured);
        returns ``(x, step)`` restored onto ``like``'s sharding.

        Every successful load emits a ``resumed`` flight event carrying
        this supervisor's name (the request/run id) — the checkpoint
        layer's own event has the path but not the identity of the run
        that adopted the state.  A checkpoint predating the version/
        layout tags (pre-canonicalize, "legacy") cannot be verified
        against the current layout: it still loads, but with a LOUD
        warning and ``legacy=True`` on the event, never a crash.
        """
        if not self.checkpoint_path:
            return None
        from arrow_matrix_tpu.utils.checkpoint import (
            checkpoint_meta,
            load_state,
        )

        with self._span("resume", path=self.checkpoint_path):
            meta = checkpoint_meta(self.checkpoint_path)
            state = load_state(self.checkpoint_path, like=like,
                               layout=self.layout)
        if state is not None:
            self.last_checkpoint_step = state[1]
            legacy = meta is None or int(meta.get("version") or 0) < 1
            if legacy:
                import sys

                print(f"[graft-heal {self.name}] WARNING: checkpoint "
                      f"at {self.checkpoint_path} predates the "
                      f"version/layout tags (legacy format) — the "
                      f"carried-X layout cannot be verified against "
                      f"{self.layout!r}; resuming anyway",
                      file=sys.stderr)
            self._event("heal", "resumed", step=state[1],
                        path=self.checkpoint_path, legacy=legacy)
        return state

    def _save(self, x, step: int) -> None:
        from arrow_matrix_tpu.utils.checkpoint import save_state

        with self._span("checkpoint", step=step):
            if self.canonicalize is not None:
                x = self.canonicalize(x)
            save_state(self.checkpoint_path, x, step,
                       layout=self.layout)
        self.last_checkpoint_step = step
        self._event("heal", "checkpointed", step=step)

    def _rollback(self, x_entry, it: int, like):
        """State to retry from after a fault at iteration ``it``: the
        last checkpoint when one exists (the NaN-burst contract —
        anything the burst may have touched is discarded), else the
        iteration-entry state."""
        if self.carry and self.checkpoint_path:
            state = self.resume(like)
            if state is not None:
                x_ck, step_ck = state
                if step_ck <= it:
                    self._recovery("rollback_to_checkpoint", it,
                                   resumed_step=step_ck)
                    return x_ck, step_ck
        self._recovery("retry_from_iteration_entry", it)
        return x_entry, it

    # -- the supervised attempt -------------------------------------------

    def _attempt(self, body: Callable, x, it: int):
        if self.watchdog_s <= 0:
            return body(x, it)
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["y"] = body(x, it)
            except BaseException as e:  # delivered to the caller below
                box["e"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"heal-{self.name}-it{it}")
        t.start()
        if not done.wait(self.watchdog_s):
            self._fault("watchdog_timeout", it,
                        WatchdogTimeout(f"iteration {it} exceeded "
                                        f"{self.watchdog_s:.3f}s"))
            # A python thread cannot be killed; give the stall a
            # bounded grace to drain (an injected hang does, a wedged
            # PJRT transfer does not) and retry only when it did.
            if not done.wait(self.watchdog_grace_s):
                raise WatchdogStalled(
                    f"iteration {it} still running after watchdog "
                    f"({self.watchdog_s:.3f}s) + grace "
                    f"({self.watchdog_grace_s:.1f}s); process-level "
                    f"recovery (checkpoint resume) required")
            raise WatchdogTimeout(
                f"iteration {it} exceeded the {self.watchdog_s:.3f}s "
                f"watchdog (drained during grace; retrying)")
        if "e" in box:
            raise box["e"]
        return box["y"]

    # -- the loop ----------------------------------------------------------

    def run(self, body: Callable[[Any, int], Any], x0, start_it: int,
            stop_it: int) -> tuple:
        """Supervised loop; returns ``(x_final, ok)``.

        ``body`` raising :class:`Abort` ends the run immediately with
        ``ok=False`` (policy failures are not retried);
        :class:`WatchdogStalled` is re-raised after sealing the
        blackbox; anything else is a fault: backoff, rollback, retry.
        """
        x = x0
        it = start_it
        consecutive = 0
        while it < stop_it:
            try:
                # The attempt span carries iteration + retry ordinal
                # (and, under graft-serve, the ambient request id), so
                # a retried iteration shows up as two attempt spans —
                # the first with an ``error`` arg — on one track.
                with self._span("attempt", iteration=it,
                                retry=consecutive):
                    y = self._attempt(body, x, it)
                    if (self.carry and self.finite_check
                            and not state_is_finite(y)):
                        raise NonFiniteState(
                            f"carried X contains NaN/Inf after "
                            f"iteration {it}")
            except Abort as e:
                self._event("fault", "aborted", iteration=it,
                            error=str(e))
                return x, False
            except WatchdogStalled as e:
                rec = flight.get_recorder()
                if rec is not None:
                    rec.seal(f"watchdog stalled: {e}")
                raise
            except Exception as e:
                reason = ("nan_detected"
                          if isinstance(e, NonFiniteState) else
                          "watchdog_timeout"
                          if isinstance(e, WatchdogTimeout) else
                          "iteration_error")
                if not isinstance(e, WatchdogTimeout):
                    # watchdog faults were already recorded at expiry
                    # (before the grace join, so a subsequent SIGKILL
                    # still leaves the fault in the blackbox).
                    self._fault(reason, it, e)
                consecutive += 1
                if consecutive > self.max_retries:
                    self._event("fault", "retries_exhausted",
                                iteration=it,
                                retries=self.max_retries)
                    return x, False
                time.sleep(self.policy.delay_s(consecutive,
                                               salt=f"{self.name}:it{it}"))
                x, it = self._rollback(x, it, like=x0)
                continue
            consecutive = 0
            if self.carry:
                x = y
            it += 1
            if (self.carry and self.checkpoint_path
                    and self.checkpoint_every > 0
                    and it % self.checkpoint_every == 0
                    and it < stop_it):
                self._save(x, it)
        if self.carry and self.checkpoint_path and stop_it > start_it:
            # Final-state save: the artifact chaos_gate compares
            # bit-for-bit, and the resume point for a longer rerun.
            self._save(x, stop_it)
        return x, True

    def summary(self) -> dict:
        return {"supervisor": self.name, "faults_seen": self.faults_seen,
                "recoveries": self.recoveries,
                "last_checkpoint_step": self.last_checkpoint_step}
