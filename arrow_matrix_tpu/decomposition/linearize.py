"""Linear-arrangement heuristics for the arrow decomposition.

Re-implementation of the reference's igraph-based linearization
(reference arrow/decomposition.py:147-281) on scipy.sparse.csgraph:

  * ``random_forest_order`` — draw random edge weights, take a minimum
    spanning forest, DFS-linearize each tree with children visited in
    increasing subtree-size order (minimizes expected linear-arrangement
    cost; reference linearize_with_random_forest / linearize_tree,
    decomposition.py:165-241).
  * ``bfs_order`` — deterministic per-component BFS fallback
    (reference linearize_with_ck, decomposition.py:147-162).

All functions take the *symmetrized structural* adjacency of the subgraph
to linearize and return positions as indices into that subgraph; callers
map back to original vertex ids.
"""

from __future__ import annotations

import numpy as np
from scipy import sparse
from scipy.sparse import csgraph


def _forest_children(order: np.ndarray, predecessors: np.ndarray):
    """Children lists + subtree sizes for a DFS-rooted tree.

    ``order`` is a DFS preorder, ``predecessors[v]`` the DFS parent
    (-9999 for the root, scipy convention).  Subtree sizes are accumulated
    in reverse preorder.
    """
    n = order.size
    sizes = {}
    children: dict[int, list[int]] = {int(v): [] for v in order}
    for v in order:
        sizes[int(v)] = 1
    for v in order[::-1]:
        p = predecessors[v]
        if p >= 0:
            sizes[int(p)] += sizes[int(v)]
            children[int(p)].append(int(v))
    return children, sizes


def _linearize_tree(root: int, children: dict[int, list[int]],
                    sizes: dict[int, int], out: list[int]) -> None:
    """Append a subtree-size-ordered DFS of the rooted tree to ``out``.

    Children with larger subtrees are visited last (pushed first on the
    stack, popped last), matching the reference's cost heuristic
    (decomposition.py:230-241).
    """
    stack = [root]
    while stack:
        v = stack.pop()
        out.append(v)
        kids = sorted(children[v], key=lambda u: sizes[u], reverse=True)
        stack.extend(kids)


def random_forest_order(adj_sym: sparse.csr_matrix, rng: np.random.Generator,
                        base_size: int = 16) -> np.ndarray:
    """Linearize via random minimum spanning forest + subtree-ordered DFS.

    Components of size <= base_size are emitted as-is (their bandwidth is
    bounded by their size, reference decomposition.py:185-189).
    """
    n = adj_sym.shape[0]
    if n == 0:
        return np.empty(0, dtype=np.int64)

    n_comp, labels = csgraph.connected_components(adj_sym, directed=False)

    # Random positive weights -> a uniformly random spanning forest flavor.
    w = adj_sym.tocsr(copy=True).astype(np.float64)
    w.data = rng.random(w.data.size) + 0.5
    forest = csgraph.minimum_spanning_tree(w)
    forest_sym = forest + forest.T  # undirected view for DFS

    comp_members: list[list[int]] = [[] for _ in range(n_comp)]
    for v, c in enumerate(labels):
        comp_members[c].append(v)

    order: list[int] = []
    for members in comp_members:
        if len(members) <= base_size:
            order.extend(members)
            continue
        root = members[0]
        dfs_order, preds = csgraph.depth_first_order(
            forest_sym, root, directed=False, return_predecessors=True)
        children, sizes = _forest_children(dfs_order, preds)
        _linearize_tree(int(root), children, sizes, order)

    assert len(order) == n
    return np.asarray(order, dtype=np.int64)


def bfs_order(adj_sym: sparse.csr_matrix, base_size: int = 2) -> np.ndarray:
    """Deterministic per-component BFS linearization."""
    n = adj_sym.shape[0]
    if n == 0:
        return np.empty(0, dtype=np.int64)
    n_comp, labels = csgraph.connected_components(adj_sym, directed=False)
    comp_members: list[list[int]] = [[] for _ in range(n_comp)]
    for v, c in enumerate(labels):
        comp_members[c].append(v)

    order: list[int] = []
    for members in comp_members:
        if len(members) <= base_size:
            order.extend(members)
            continue
        bfs = csgraph.breadth_first_order(adj_sym, members[0], directed=False,
                                          return_predecessors=False)
        order.extend(int(v) for v in bfs)
    assert len(order) == n
    return np.asarray(order, dtype=np.int64)
