"""ctypes loader for the native linearization kernels.

The compiled-performance decomposer layer (the reference's Julia module
role, reference julia/arrow/*.jl — SURVEY.md §2a).  The shared library
is built from ``_native/fast_decomp.cpp`` on first use with g++ (this
environment has no pybind11; plain ``extern "C"`` + ctypes needs no
build-time Python dependency at all) and cached next to the source.

Public surface mirrors ``linearize.py``:

    available() -> bool
    random_forest_order(adj_sym, rng, base_size) -> order
    bfs_order(adj_sym, base_size) -> order

Callers should treat this as an *equivalent alternative* to the numpy
implementation: both satisfy the decomposition invariants; the random
orders differ (different RNG streams), exactly as the reference's Julia
and Python decomposers differ.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np
from scipy import sparse

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_native", "fast_decomp.cpp")
_LIB_PATH = os.path.join(_DIR, "_native", "libfast_decomp.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_error: Exception | None = None


def _build() -> None:
    # Compile to a unique temp path in the target directory and
    # os.replace() into place: concurrent first-use builds (test
    # workers, multi-host launchers on a shared filesystem — where pids
    # can collide across hosts) must never dlopen a partially-written
    # .so.  mkstemp gives per-open uniqueness on the shared directory.
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=".so.tmp",
                               dir=os.path.dirname(_LIB_PATH))
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native decomposer build failed "
                f"({' '.join(cmd)}):\n{proc.stderr}")
        # mkstemp creates 0600; restore umask-default perms so other
        # users of a shared install can dlopen the library.
        os.chmod(tmp, 0o644)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load() -> ctypes.CDLL | None:
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            # <= not <: a source edit landing within the filesystem's
            # timestamp granularity of the build must still trigger a
            # rebuild (a fresh build always stamps the library strictly
            # newer than the source it came from).
            stale = (not os.path.exists(_LIB_PATH)
                     or os.path.getmtime(_LIB_PATH) <= os.path.getmtime(_SRC))
            if stale:
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            i64p = ctypes.POINTER(ctypes.c_int64)
            i32p = ctypes.POINTER(ctypes.c_int32)
            for suffix, idxp in (("", i64p), ("_i32", i32p)):
                f = getattr(lib, "amt_random_forest_order" + suffix)
                f.argtypes = [ctypes.c_int64, i64p, idxp,
                              ctypes.c_uint64, ctypes.c_int64, i64p]
                f.restype = ctypes.c_int
                f = getattr(lib,
                            "amt_random_forest_order_masked" + suffix)
                f.argtypes = [ctypes.c_int64, i64p, idxp,
                              ctypes.c_uint64, ctypes.c_int64,
                              ctypes.c_int64, i64p, i64p]
                f.restype = ctypes.c_int
                f = getattr(lib, "amt_bfs_order" + suffix)
                f.argtypes = [ctypes.c_int64, i64p, idxp,
                              ctypes.c_int64, i64p]
                f.restype = ctypes.c_int
                f = getattr(lib, "amt_symmetrize_structure" + suffix)
                f.argtypes = [ctypes.c_int64, i64p, idxp, i64p, i32p]
                f.restype = ctypes.c_int64
            f32p = ctypes.POINTER(ctypes.c_float)
            f64p = ctypes.POINTER(ctypes.c_double)
            for isuf, idxp in (("i32", i32p), ("i64", i64p)):
                for vsuf, valp in (("f32", f32p), ("f64", f64p)):
                    f = getattr(lib, f"amt_level_split_{isuf}_{vsuf}")
                    f.argtypes = [ctypes.c_int64, i64p, idxp, valp,
                                  i32p, ctypes.c_int64, ctypes.c_int,
                                  ctypes.c_int, i64p, i32p, valp, i64p,
                                  i32p, valp, i64p]
                    f.restype = ctypes.c_int
            _lib = lib
        except Exception as e:  # compiler missing, load failure, ...
            _load_error = e
        return _lib


def available() -> bool:
    """True when the native library is (or can be) loaded."""
    return _load() is not None


def load_error() -> Exception | None:
    """The build/load failure, for error messages from backend='native'."""
    _load()
    return _load_error


def _csr_native(adj_or_pair) -> tuple[np.ndarray, np.ndarray]:
    """(indptr int64, indices int32-or-int64) for the native calls.

    int32 indices (scipy's dtype below 2^31 nnz) pass through WITHOUT
    the int64 conversion copy v1 forced — the ``_i32`` kernel entry
    points read them directly (half the index traffic)."""
    if isinstance(adj_or_pair, tuple):
        indptr, indices = adj_or_pair
    else:
        indptr, indices = adj_or_pair.indptr, adj_or_pair.indices
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    if indices.dtype == np.int32:
        indices = np.ascontiguousarray(indices)
    else:
        indices = np.ascontiguousarray(indices, dtype=np.int64)
    return indptr, indices


def _idx_fn(lib, name: str, indices: np.ndarray):
    return getattr(lib,
                   name + ("_i32" if indices.dtype == np.int32 else ""))


def _ptr(a: np.ndarray):
    if a.dtype == np.int32:
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def random_forest_order(adj_sym: sparse.csr_matrix,
                        rng: np.random.Generator,
                        base_size: int = 16) -> np.ndarray:
    """Native random-spanning-forest linearization (see linearize.py for
    the algorithm contract)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = adj_sym.shape[0]
    out = np.empty(n, dtype=np.int64)
    if n == 0:
        return out
    indptr, indices = _csr_native(adj_sym)
    seed = int(rng.integers(0, 2**63 - 1))
    rc = _idx_fn(lib, "amt_random_forest_order", indices)(
        n, _ptr(indptr), _ptr(indices), seed, int(base_size), _ptr(out))
    if rc != 0:
        raise RuntimeError("native random_forest_order failed "
                           f"(rc={rc})")
    return out


def random_forest_order_masked(adj_sym, active: np.ndarray,
                               rng: np.random.Generator,
                               base_size: int = 16) -> np.ndarray:
    """Forest order of the induced submatrix ``adj_sym[active][:,
    active]`` without materializing it — same contract as
    ``random_forest_order(adj_sym[active][:, active], ...)`` (positions
    into ``active``), one O(n + m) native pass instead of scipy's
    fancy-indexed row+column extraction — saves a full per-level edge
    copy (measured ~5% end-to-end at n=2^22; the forest pass itself
    dominates).

    ``adj_sym`` may be a csr_matrix or a raw ``(indptr, indices)``
    pair (the output of :func:`symmetrize_structure` — no scipy
    wrapper needed on the all-native path)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    indptr, indices = _csr_native(adj_sym)
    n = int(indptr.size - 1)
    k = int(active.size)
    out = np.empty(k, dtype=np.int64)
    if k == 0:
        return out
    act = np.ascontiguousarray(active, dtype=np.int64)
    seed = int(rng.integers(0, 2**63 - 1))
    rc = _idx_fn(lib, "amt_random_forest_order_masked", indices)(
        n, _ptr(indptr), _ptr(indices), seed, int(base_size), k,
        _ptr(act), _ptr(out))
    if rc != 0:
        raise RuntimeError(
            "native random_forest_order_masked failed "
            f"(rc={rc}: invalid subset or non-permutation output)")
    return out


def symmetrize_structure(a: sparse.csr_matrix
                         ) -> tuple[np.ndarray, np.ndarray]:
    """Sorted deduped CSR STRUCTURE of ``A + A^T`` as a raw
    ``(indptr int64, indices int32)`` pair.

    The linear-order pipeline only ever consumes the symmetric
    *pattern* (degrees + edges); scipy's value-carrying ``A + A.T``
    was the largest single host phase of the v1 decompose profile
    (7.4 s of 37 s at n=2^21).  Rows of ``a`` need not be canonical
    (the kernel sorts/dedups per row).  Requires n < 2^31."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = a.shape[0]
    if n >= np.iinfo(np.int32).max:
        raise ValueError(f"native symmetrize requires n < 2^31, got {n}")
    indptr, indices = _csr_native(a)
    out_indptr = np.empty(n + 1, dtype=np.int64)
    out_indices = np.empty(max(2 * int(indptr[-1]), 1), dtype=np.int32)
    sym_nnz = _idx_fn(lib, "amt_symmetrize_structure", indices)(
        n, _ptr(indptr), _ptr(indices), _ptr(out_indptr),
        _ptr(out_indices))
    if sym_nnz < 0:
        raise RuntimeError(f"native symmetrize failed (rc={sym_nnz})")
    return out_indptr, out_indices[:sym_nnz]


class LevelSplitUnsupported(Exception):
    """The fused native split cannot handle this input (dtype,
    n >= 2^31, or the degenerate all-False selection) — the caller
    falls back to the numpy path."""


def level_split(a: sparse.csr_matrix, inv: np.ndarray, width: int,
                block_diagonal: bool, prune: bool
                ) -> tuple[sparse.csr_matrix, sparse.csr_matrix | None]:
    """Fused per-level edge routing: one native pass replaces the
    numpy chain (tocoo -> inv-gather -> boolean select -> two scipy
    COO->CSR builds), ~10 s of the 37 s v1 profile at n=2^21.

    Returns ``(level, rest)``: ``level`` is canonical CSR in permuted
    coordinates; ``rest`` is CSR in ORIGINAL coordinates (non-canonical,
    like the numpy path's coo build) or None when every edge fit the
    level.  Raises LevelSplitUnsupported for inputs the kernel does not
    cover (caller falls back)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = a.shape[0]
    if n >= np.iinfo(np.int32).max:
        raise LevelSplitUnsupported(f"n={n} >= 2^31")
    if a.data.dtype == np.float32:
        vsuf, vdt = "f32", np.float32
    elif a.data.dtype == np.float64:
        vsuf, vdt = "f64", np.float64
    else:
        raise LevelSplitUnsupported(f"dtype {a.data.dtype}")
    indptr, indices = _csr_native(a)
    isuf = "i32" if indices.dtype == np.int32 else "i64"
    data = np.ascontiguousarray(a.data, dtype=vdt)
    inv32 = np.ascontiguousarray(inv, dtype=np.int32)
    nnz = int(indptr[-1])
    lvl_indptr = np.empty(n + 1, dtype=np.int64)
    lvl_indices = np.empty(max(nnz, 1), dtype=np.int32)
    lvl_data = np.empty(max(nnz, 1), dtype=vdt)
    rest_indptr = np.empty(n + 1, dtype=np.int64)
    rest_indices = np.empty(max(nnz, 1), dtype=np.int32)
    rest_data = np.empty(max(nnz, 1), dtype=vdt)
    counts = np.zeros(2, dtype=np.int64)
    valp = (ctypes.POINTER(ctypes.c_float) if vsuf == "f32"
            else ctypes.POINTER(ctypes.c_double))
    fn = getattr(lib, f"amt_level_split_{isuf}_{vsuf}")
    rc = fn(n, _ptr(indptr), _ptr(indices),
            data.ctypes.data_as(valp), _ptr(inv32), int(width),
            int(bool(block_diagonal)), int(bool(prune)),
            _ptr(lvl_indptr), _ptr(lvl_indices),
            lvl_data.ctypes.data_as(valp), _ptr(rest_indptr),
            _ptr(rest_indices), rest_data.ctypes.data_as(valp),
            _ptr(counts))
    if rc == 4:
        raise LevelSplitUnsupported("all-False selection fallback")
    if rc != 0:
        raise RuntimeError(f"native level_split failed (rc={rc})")
    ln, rn = int(counts[0]), int(counts[1])
    # .copy() the trims: a slice would pin the full-nnz capacity
    # buffers alive through the whole recursion.
    lvl = sparse.csr_matrix(
        (lvl_data[:ln].copy(), lvl_indices[:ln].copy(), lvl_indptr),
        shape=(n, n))
    # The kernel emits canonical rows (sorted, deduped); tell scipy so
    # the decomposer's sum_duplicates/sort_indices are no-ops.
    lvl.has_canonical_format = True
    lvl.has_sorted_indices = True
    if rn == 0:
        return lvl, None
    rest = sparse.csr_matrix(
        (rest_data[:rn].copy(), rest_indices[:rn].copy(), rest_indptr),
        shape=(n, n))
    return lvl, rest


def bfs_order(adj_sym: sparse.csr_matrix, base_size: int = 2) -> np.ndarray:
    """Native deterministic per-component BFS linearization."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = adj_sym.shape[0]
    out = np.empty(n, dtype=np.int64)
    if n == 0:
        return out
    indptr, indices = _csr_native(adj_sym)
    rc = _idx_fn(lib, "amt_bfs_order", indices)(
        n, _ptr(indptr), _ptr(indices), int(base_size), _ptr(out))
    if rc != 0:
        raise RuntimeError("native bfs_order failed "
                           f"(rc={rc})")
    return out
