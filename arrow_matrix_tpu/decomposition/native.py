"""ctypes loader for the native linearization kernels.

The compiled-performance decomposer layer (the reference's Julia module
role, reference julia/arrow/*.jl — SURVEY.md §2a).  The shared library
is built from ``_native/fast_decomp.cpp`` on first use with g++ (this
environment has no pybind11; plain ``extern "C"`` + ctypes needs no
build-time Python dependency at all) and cached next to the source.

Public surface mirrors ``linearize.py``:

    available() -> bool
    random_forest_order(adj_sym, rng, base_size) -> order
    bfs_order(adj_sym, base_size) -> order

Callers should treat this as an *equivalent alternative* to the numpy
implementation: both satisfy the decomposition invariants; the random
orders differ (different RNG streams), exactly as the reference's Julia
and Python decomposers differ.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np
from scipy import sparse

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_native", "fast_decomp.cpp")
_LIB_PATH = os.path.join(_DIR, "_native", "libfast_decomp.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_error: Exception | None = None


def _build() -> None:
    # Compile to a unique temp path in the target directory and
    # os.replace() into place: concurrent first-use builds (test
    # workers, multi-host launchers on a shared filesystem — where pids
    # can collide across hosts) must never dlopen a partially-written
    # .so.  mkstemp gives per-open uniqueness on the shared directory.
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=".so.tmp",
                               dir=os.path.dirname(_LIB_PATH))
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native decomposer build failed "
                f"({' '.join(cmd)}):\n{proc.stderr}")
        # mkstemp creates 0600; restore umask-default perms so other
        # users of a shared install can dlopen the library.
        os.chmod(tmp, 0o644)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load() -> ctypes.CDLL | None:
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            # <= not <: a source edit landing within the filesystem's
            # timestamp granularity of the build must still trigger a
            # rebuild (a fresh build always stamps the library strictly
            # newer than the source it came from).
            stale = (not os.path.exists(_LIB_PATH)
                     or os.path.getmtime(_LIB_PATH) <= os.path.getmtime(_SRC))
            if stale:
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.amt_random_forest_order.argtypes = [
                ctypes.c_int64, i64p, i64p, ctypes.c_uint64,
                ctypes.c_int64, i64p]
            lib.amt_random_forest_order.restype = ctypes.c_int
            lib.amt_random_forest_order_masked.argtypes = [
                ctypes.c_int64, i64p, i64p, ctypes.c_uint64,
                ctypes.c_int64, ctypes.c_int64, i64p, i64p]
            lib.amt_random_forest_order_masked.restype = ctypes.c_int
            lib.amt_bfs_order.argtypes = [
                ctypes.c_int64, i64p, i64p, ctypes.c_int64, i64p]
            lib.amt_bfs_order.restype = ctypes.c_int
            _lib = lib
        except Exception as e:  # compiler missing, load failure, ...
            _load_error = e
        return _lib


def available() -> bool:
    """True when the native library is (or can be) loaded."""
    return _load() is not None


def load_error() -> Exception | None:
    """The build/load failure, for error messages from backend='native'."""
    _load()
    return _load_error


def _csr_int64(adj: sparse.csr_matrix) -> tuple[np.ndarray, np.ndarray]:
    indptr = np.ascontiguousarray(adj.indptr, dtype=np.int64)
    indices = np.ascontiguousarray(adj.indices, dtype=np.int64)
    return indptr, indices


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def random_forest_order(adj_sym: sparse.csr_matrix,
                        rng: np.random.Generator,
                        base_size: int = 16) -> np.ndarray:
    """Native random-spanning-forest linearization (see linearize.py for
    the algorithm contract)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = adj_sym.shape[0]
    out = np.empty(n, dtype=np.int64)
    if n == 0:
        return out
    indptr, indices = _csr_int64(adj_sym)
    seed = int(rng.integers(0, 2**63 - 1))
    rc = lib.amt_random_forest_order(n, _ptr(indptr), _ptr(indices),
                                     seed, int(base_size), _ptr(out))
    if rc != 0:
        raise RuntimeError("native random_forest_order failed "
                           "(emitted order is not a permutation)")
    return out


def random_forest_order_masked(adj_sym: sparse.csr_matrix,
                               active: np.ndarray,
                               rng: np.random.Generator,
                               base_size: int = 16) -> np.ndarray:
    """Forest order of the induced submatrix ``adj_sym[active][:,
    active]`` without materializing it — same contract as
    ``random_forest_order(adj_sym[active][:, active], ...)`` (positions
    into ``active``), one O(n + m) native pass instead of scipy's
    fancy-indexed row+column extraction — saves a full per-level edge
    copy (measured ~5% end-to-end at n=2^22; the forest pass itself
    dominates)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = adj_sym.shape[0]
    k = int(active.size)
    out = np.empty(k, dtype=np.int64)
    if k == 0:
        return out
    indptr, indices = _csr_int64(adj_sym)
    act = np.ascontiguousarray(active, dtype=np.int64)
    seed = int(rng.integers(0, 2**63 - 1))
    rc = lib.amt_random_forest_order_masked(
        n, _ptr(indptr), _ptr(indices), seed, int(base_size), k,
        _ptr(act), _ptr(out))
    if rc != 0:
        raise RuntimeError(
            "native random_forest_order_masked failed "
            f"(rc={rc}: invalid subset or non-permutation output)")
    return out


def bfs_order(adj_sym: sparse.csr_matrix, base_size: int = 2) -> np.ndarray:
    """Native deterministic per-component BFS linearization."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decomposer unavailable: {_load_error}")
    n = adj_sym.shape[0]
    out = np.empty(n, dtype=np.int64)
    if n == 0:
        return out
    indptr, indices = _csr_int64(adj_sym)
    rc = lib.amt_bfs_order(n, _ptr(indptr), _ptr(indices), int(base_size),
                           _ptr(out))
    if rc != 0:
        raise RuntimeError("native bfs_order failed "
                           "(emitted order is not a permutation)")
    return out
