from arrow_matrix_tpu.decomposition.decompose import (
    ArrowLevel,
    achieved_width,
    arrow_decomposition,
    decomposition_spmm,
    reconstruct,
)
from arrow_matrix_tpu.decomposition.linearize import bfs_order, random_forest_order
from arrow_matrix_tpu.decomposition import native

__all__ = [
    "ArrowLevel",
    "achieved_width",
    "arrow_decomposition",
    "decomposition_spmm",
    "reconstruct",
    "bfs_order",
    "random_forest_order",
]
