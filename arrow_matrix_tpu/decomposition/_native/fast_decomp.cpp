// Native linearization kernels for the arrow decomposition.
//
// Role: the compiled-performance decomposer layer — the counterpart of
// the reference's Julia module (reference julia/arrow/
// GraphAlgorithms.jl: union-find :7-41, Kruskal MSF :45-80, masked BFS
// :83-195; ArrowDecomposition.jl:_arrow_linear_order :102-135), which
// exists because the per-vertex bookkeeping of linearization is the only
// super-linear-constant hot spot of the offline pipeline at 10^8 rows.
//
// Operates directly on symmetrized CSR arrays (int64 indptr/indices),
// no graph library.  Exposed via ctypes (this environment has no
// pybind11); see ../native.py.
//
// Algorithms (matching arrow_matrix_tpu/decomposition/linearize.py):
//   amt_random_forest_order: uniformly random spanning forest by
//     shuffled-edge Kruskal + union-find, then per-component DFS with
//     children visited in increasing subtree-size order.  Components of
//     size <= base_size are emitted as-is (ascending vertex id).
//   amt_bfs_order: deterministic per-component BFS.
//
// Both write a permutation of [0, n) to `out` and return 0 on success.

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

// SplitMix64: tiny, high-quality, seedable — the RNG for edge shuffling.
inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Union-find with path halving + union by size (reference
// GraphAlgorithms.jl:7-41 uses path compression + rank; size works the
// same and doubles as the component-size lookup).
struct UnionFind {
  std::vector<int64_t> parent;
  std::vector<int64_t> size;

  explicit UnionFind(int64_t n) : parent(n), size(n, 1) {
    for (int64_t i = 0; i < n; ++i) parent[i] = i;
  }

  int64_t find(int64_t x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];
      x = parent[x];
    }
    return x;
  }

  bool unite(int64_t a, int64_t b) {
    a = find(a);
    b = find(b);
    if (a == b) return false;
    if (size[a] < size[b]) std::swap(a, b);
    parent[b] = a;
    size[a] += size[b];
    return true;
  }
};

// Linearize one rooted forest tree: DFS preorder + parents, subtree
// sizes in reverse preorder, then a second DFS visiting children in
// increasing subtree-size order (larger subtrees last — the linear-
// arrangement cost heuristic, reference
// ArrowDecomposition.jl/_linearize_tree, linearize.py:_linearize_tree).
void linearize_tree(int64_t root, const std::vector<int64_t> &adj_ptr,
                    const std::vector<int64_t> &adj,
                    std::vector<int64_t> &parent,
                    std::vector<int64_t> &subtree,
                    std::vector<int64_t> &preorder,
                    std::vector<int64_t> &stack, int64_t *out,
                    int64_t &out_pos) {
  // Pass 1: DFS preorder, recording parents.
  preorder.clear();
  stack.clear();
  stack.push_back(root);
  parent[root] = -1;
  while (!stack.empty()) {
    int64_t v = stack.back();
    stack.pop_back();
    preorder.push_back(v);
    for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
      int64_t u = adj[e];
      if (u != parent[v] && parent[u] == -2) {
        parent[u] = v;
        stack.push_back(u);
      }
    }
  }
  // Pass 2: subtree sizes in reverse preorder.
  for (auto it = preorder.rbegin(); it != preorder.rend(); ++it) {
    subtree[*it] = 1;
  }
  for (auto it = preorder.rbegin(); it != preorder.rend(); ++it) {
    int64_t v = *it;
    if (parent[v] >= 0) subtree[parent[v]] += subtree[v];
  }
  // Pass 3: DFS emitting children by increasing subtree size (push
  // descending so the smallest pops first).
  std::vector<std::pair<int64_t, int64_t>> kids;  // (size, child)
  stack.clear();
  stack.push_back(root);
  while (!stack.empty()) {
    int64_t v = stack.back();
    stack.pop_back();
    out[out_pos++] = v;
    kids.clear();
    for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
      int64_t u = adj[e];
      if (parent[u] == v) kids.emplace_back(subtree[u], u);
    }
    std::sort(kids.begin(), kids.end());
    for (auto it = kids.rbegin(); it != kids.rend(); ++it) {
      stack.push_back(it->second);
    }
  }
}

// Core of the random-forest linearization once the unique undirected
// edge list (u < v, vertex ids in [0, n)) is in hand: shuffled-edge
// Kruskal, forest adjacency, per-component emit.  Shared by the full
// and the masked (submatrix) entry points — the forest/DFS/BFS phases
// only ever touch TREE edges, so no compacted full CSR is needed.
int forest_order_from_edges(int64_t n, const std::vector<int64_t> &eu,
                            const std::vector<int64_t> &ev, uint64_t seed,
                            int64_t base_size, int64_t *out) {
  const int64_t m = static_cast<int64_t>(eu.size());

  // Shuffled-edge Kruskal == Kruskal on iid random weights == a random
  // spanning forest (reference GraphAlgorithms.jl:45-80 sorts random
  // weights; a Fisher-Yates shuffle of edge ids is the same ordering).
  std::vector<int64_t> edge_order(m);
  for (int64_t i = 0; i < m; ++i) edge_order[i] = i;
  uint64_t state = seed ^ 0xdeadbeefcafef00dULL;
  for (int64_t i = m - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix64(state) % (i + 1));
    std::swap(edge_order[i], edge_order[j]);
  }

  UnionFind uf(n);
  std::vector<int64_t> tu, tv;
  tu.reserve(n);
  tv.reserve(n);
  for (int64_t i = 0; i < m; ++i) {
    int64_t a = eu[edge_order[i]], b = ev[edge_order[i]];
    if (uf.unite(a, b)) {
      tu.push_back(a);
      tv.push_back(b);
    }
  }

  // Forest adjacency (CSR, both directions).
  std::vector<int64_t> adj_ptr(n + 1, 0);
  for (size_t i = 0; i < tu.size(); ++i) {
    ++adj_ptr[tu[i] + 1];
    ++adj_ptr[tv[i] + 1];
  }
  for (int64_t v = 0; v < n; ++v) adj_ptr[v + 1] += adj_ptr[v];
  std::vector<int64_t> adj(adj_ptr[n]);
  std::vector<int64_t> fill(adj_ptr.begin(), adj_ptr.end() - 1);
  for (size_t i = 0; i < tu.size(); ++i) {
    adj[fill[tu[i]]++] = tv[i];
    adj[fill[tv[i]]++] = tu[i];
  }

  // Emit components in order of smallest member (scipy's label order in
  // linearize.py).  parent doubles as the visited marker: -2 unvisited.
  std::vector<int64_t> parent(n, -2), subtree(n, 0), preorder, stack;
  std::vector<int64_t> members;
  int64_t out_pos = 0;
  for (int64_t v = 0; v < n; ++v) {
    if (parent[v] != -2) continue;
    int64_t root = uf.find(v);
    int64_t comp_size = uf.size[root];
    if (comp_size <= base_size) {
      // Small component: ascending vertex ids.  Collect by BFS over the
      // forest (spanning: reaches every member), then sort.
      members.clear();
      members.push_back(v);
      parent[v] = -1;
      for (size_t h = 0; h < members.size(); ++h) {
        int64_t w = members[h];
        for (int64_t e = adj_ptr[w]; e < adj_ptr[w + 1]; ++e) {
          int64_t u = adj[e];
          if (parent[u] == -2) {
            parent[u] = w;
            members.push_back(u);
          }
        }
      }
      std::sort(members.begin(), members.end());
      for (int64_t w : members) out[out_pos++] = w;
    } else {
      linearize_tree(v, adj_ptr, adj, parent, subtree, preorder, stack,
                     out, out_pos);
    }
  }
  return out_pos == n ? 0 : 1;
}

}  // namespace

extern "C" {

int amt_random_forest_order(int64_t n, const int64_t *indptr,
                            const int64_t *indices, uint64_t seed,
                            int64_t base_size, int64_t *out) {
  if (n == 0) return 0;

  // Unique undirected edges u < v from the symmetrized CSR.
  std::vector<int64_t> eu, ev;
  eu.reserve(indptr[n] / 2);
  ev.reserve(indptr[n] / 2);
  for (int64_t u = 0; u < n; ++u) {
    for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      int64_t v = indices[e];
      if (u < v) {
        eu.push_back(u);
        ev.push_back(v);
      }
    }
  }
  return forest_order_from_edges(n, eu, ev, seed, base_size, out);
}

int amt_random_forest_order_masked(int64_t n, const int64_t *indptr,
                                   const int64_t *indices, uint64_t seed,
                                   int64_t base_size, int64_t k,
                                   const int64_t *active, int64_t *out) {
  // Forest order of the induced submatrix sym[active][:, active]
  // WITHOUT materializing it: one O(n + m) label-and-filter pass
  // replaces scipy's fancy-indexed row+column extraction — a full
  // per-level edge copy saved (~5% end-to-end at n=2^22; the forest
  // pass itself dominates).  ``active`` holds the original
  // vertex id of each submatrix position (any order, e.g. by degree);
  // ``out`` receives a permutation of [0, k) in submatrix positions —
  // the same contract as running amt_random_forest_order on the
  // materialized submatrix.
  if (k == 0) return 0;
  std::vector<int64_t> label(n, -1);
  for (int64_t i = 0; i < k; ++i) {
    if (active[i] < 0 || active[i] >= n || label[active[i]] != -1)
      return 2;  // not a valid vertex subset
    label[active[i]] = i;
  }
  // Each undirected pair of the symmetric input appears in both
  // directions; keep exactly the direction whose COMPACT ids ascend,
  // so every submatrix edge lands once.
  std::vector<int64_t> eu, ev;
  eu.reserve(indptr[n] / 2);
  ev.reserve(indptr[n] / 2);
  for (int64_t i = 0; i < k; ++i) {
    int64_t u = active[i];
    for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      int64_t lv = label[indices[e]];
      if (lv > i) {
        eu.push_back(i);
        ev.push_back(lv);
      }
    }
  }
  return forest_order_from_edges(k, eu, ev, seed, base_size, out);
}

int amt_bfs_order(int64_t n, const int64_t *indptr, const int64_t *indices,
                  int64_t base_size, int64_t *out) {
  if (n == 0) return 0;
  std::vector<int64_t> queue;
  std::vector<char> visited(n, 0);
  int64_t out_pos = 0;
  for (int64_t v = 0; v < n; ++v) {
    if (visited[v]) continue;
    // BFS the component (reference masked BFS,
    // GraphAlgorithms.jl:83-195).
    queue.clear();
    queue.push_back(v);
    visited[v] = 1;
    for (size_t h = 0; h < queue.size(); ++h) {
      int64_t w = queue[h];
      for (int64_t e = indptr[w]; e < indptr[w + 1]; ++e) {
        int64_t u = indices[e];
        if (!visited[u]) {
          visited[u] = 1;
          queue.push_back(u);
        }
      }
    }
    if (static_cast<int64_t>(queue.size()) <= base_size) {
      std::sort(queue.begin(), queue.end());
    }
    for (int64_t w : queue) out[out_pos++] = w;
  }
  return out_pos == n ? 0 : 1;
}

}  // extern "C"
