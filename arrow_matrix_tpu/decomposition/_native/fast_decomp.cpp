// Native kernels for the arrow decomposition's offline pipeline.
//
// Role: the compiled-performance decomposer layer — the counterpart of
// the reference's Julia module (reference julia/arrow/
// GraphAlgorithms.jl: union-find :7-41, Kruskal MSF :45-80, masked BFS
// :83-195; ArrowDecomposition.jl:_arrow_linear_order :102-135), which
// exists because the per-vertex bookkeeping of linearization is the only
// super-linear-constant hot spot of the offline pipeline at 10^8 rows.
//
// Operates directly on CSR arrays, no graph library.  Exposed via
// ctypes (this environment has no pybind11); see ../native.py.
//
// v2 (round 4): vertex ids are int32 internally (the framework guards
// n < 2^31; half the memory traffic of the v1 int64 arrays), the edge
// shuffle permutes PACKED (u,v) pairs in place so Kruskal scans
// linearly instead of gathering by shuffled id, CSR `indices` may be
// int32 (scipy's native dtype — skips the int64 conversion copy), and
// a structure-only symmetrize replaces scipy's value-carrying A + A^T.
// The Fisher-Yates sequence is UNCHANGED (same splitmix64 stream, same
// swap order), so a given seed produces the identical forest — and
// identical decomposition — as v1.
//
// Threading: AMT_DECOMP_THREADS (default: hardware concurrency,
// clamped to 16) parallelizes edge extraction, symmetrize counting,
// the Kruskal scan (filter-Kruskal: parallel read-only connectivity
// filter between sequential unite passes — the unique-MSF argument
// makes the forest bit-identical to the plain scan), the forest-
// adjacency fill (destination-range partitioning), and large-
// component linearization (level-synchronous sweeps reproducing the
// DFS emit positions exactly — see linearize_tree_levelsync).  Every
// output is thread-count-invariant and bit-identical to the
// single-thread stream; only the Fisher-Yates shuffle is inherently
// sequential (it IS the seed contract).
//
// Algorithms (matching arrow_matrix_tpu/decomposition/linearize.py):
//   amt_random_forest_order[_i32]: uniformly random spanning forest by
//     shuffled-edge Kruskal + union-find, then per-component DFS with
//     children visited in increasing subtree-size order.  Components of
//     size <= base_size are emitted as-is (ascending vertex id).
//   amt_bfs_order[_i32]: deterministic per-component BFS.
//   amt_symmetrize_structure[_i32]: sorted deduped CSR structure of
//     A + A^T (values ignored — the linear-order pipeline only ever
//     consumes the pattern).
//
// Permutation outputs are int64 (numpy-native). All return 0 on
// success unless documented otherwise.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

using vid = int32_t;   // vertex id (n < 2^31 guarded by the caller)

// Phase timing to stderr under AMT_DECOMP_PROFILE=1 (pairs with the
// Python-side _phase timers in decompose.py — one switch for the whole
// offline pipeline's attribution).
struct PhaseTimer {
  const char *label;
  bool on;
  std::chrono::steady_clock::time_point t0;

  explicit PhaseTimer(const char *l)
      : label(l), on(std::getenv("AMT_DECOMP_PROFILE") != nullptr),
        t0(std::chrono::steady_clock::now()) {}

  ~PhaseTimer() {
    if (!on) return;
    auto dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
    std::fprintf(stderr, "[decomp-native] %s: %.2fs\n", label, dt);
  }
};

int n_threads() {
  if (const char *env = std::getenv("AMT_DECOMP_THREADS")) {
    int t = std::atoi(env);
    if (t >= 1) return std::min(t, 16);
  }
  unsigned hw = std::thread::hardware_concurrency();
  return hw ? std::min<int>(hw, 16) : 1;
}

// Run fn(t, lo, hi) over [0, n) split into T contiguous ranges.
// min_n: below this the call runs inline sequential (spawn cost floor);
// the level-synchronous sweeps pass a lower floor than the default —
// their per-element work is an adjacency scan + sort, not a counter.
template <typename F>
void parallel_ranges(int64_t n, int T, F fn, int64_t min_n = 1 << 16) {
  if (T <= 1 || n < min_n) {
    fn(0, 0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + T - 1) / T;
  for (int t = 0; t < T; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(fn, t, lo, hi);
  }
  for (auto &th : threads) th.join();
}

// SplitMix64: tiny, high-quality, seedable — the RNG for edge shuffling.
inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// Union-find with path halving + union by size (reference
// GraphAlgorithms.jl:7-41 uses path compression + rank; size works the
// same and doubles as the component-size lookup).
struct UnionFind {
  std::vector<vid> parent;
  std::vector<vid> size;

  explicit UnionFind(vid n) : parent(n), size(n, 1) {
    for (vid i = 0; i < n; ++i) parent[i] = i;
  }

  vid find(vid x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];
      x = parent[x];
    }
    return x;
  }

  // Read-only find for CONCURRENT use (no path halving, no writes).
  // Union-by-size bounds the chain at O(log n).  Only valid while no
  // thread is mutating — the filter-Kruskal phases alternate strictly
  // between parallel read-only filtering and sequential uniting.
  vid find_ro(vid x) const {
    while (parent[x] != x) x = parent[x];
    return x;
  }

  bool unite(vid a, vid b) {
    a = find(a);
    b = find(b);
    if (a == b) return false;
    if (size[a] < size[b]) std::swap(a, b);
    parent[b] = a;
    size[a] += size[b];
    return true;
  }
};

inline uint64_t pack_edge(vid u, vid v) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(u)) << 32) |
         static_cast<uint32_t>(v);
}

// Linearize one rooted forest tree: DFS preorder + parents, subtree
// sizes in reverse preorder, then a second DFS visiting children in
// increasing subtree-size order (larger subtrees last — the linear-
// arrangement cost heuristic, reference
// ArrowDecomposition.jl/_linearize_tree, linearize.py:_linearize_tree).
void linearize_tree(vid root, const std::vector<int64_t> &adj_ptr,
                    const std::vector<vid> &adj, std::vector<vid> &parent,
                    std::vector<vid> &subtree, std::vector<vid> &preorder,
                    std::vector<vid> &stack, int64_t *out,
                    int64_t &out_pos) {
  // Pass 1: DFS preorder, recording parents.
  preorder.clear();
  stack.clear();
  stack.push_back(root);
  parent[root] = -1;
  while (!stack.empty()) {
    vid v = stack.back();
    stack.pop_back();
    preorder.push_back(v);
    for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
      vid u = adj[e];
      if (u != parent[v] && parent[u] == -2) {
        parent[u] = v;
        stack.push_back(u);
      }
    }
  }
  // Pass 2: subtree sizes in reverse preorder.
  for (auto it = preorder.rbegin(); it != preorder.rend(); ++it) {
    subtree[*it] = 1;
  }
  for (auto it = preorder.rbegin(); it != preorder.rend(); ++it) {
    vid v = *it;
    if (parent[v] >= 0) subtree[parent[v]] += subtree[v];
  }
  // Pass 3: DFS emitting children by increasing subtree size (push
  // descending so the smallest pops first).
  std::vector<std::pair<vid, vid>> kids;  // (size, child)
  stack.clear();
  stack.push_back(root);
  while (!stack.empty()) {
    vid v = stack.back();
    stack.pop_back();
    out[out_pos++] = v;
    kids.clear();
    for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
      vid u = adj[e];
      if (parent[u] == v) kids.emplace_back(subtree[u], u);
    }
    std::sort(kids.begin(), kids.end());
    for (auto it = kids.rbegin(); it != kids.rend(); ++it) {
      stack.push_back(it->second);
    }
  }
}

// Level-synchronous linearization of ONE large tree — emit-order-
// IDENTICAL to linearize_tree, with every sweep parallel over a level:
//
// 1. parents by tree-BFS.  In a tree the parent of v is its unique
//    neighbor on the path to the root, so the parent array is a
//    property of (tree, root), not of traversal order — BFS and DFS
//    produce the same parents.  Each unvisited vertex is adjacent to
//    exactly ONE frontier vertex (its parent), so frontier expansion
//    has no write conflicts and needs no atomics.
// 2. subtree sizes bottom-up per level: subtree[v] = 1 + sum over
//    children (one level deeper, already final).
// 3. emit positions top-down per level.  The sequential DFS emits v at
//    the start of its subtree block, then the children blocks in
//    increasing (subtree, id) order — so pos[child] = pos[v] + 1 +
//    total size of smaller siblings, a per-vertex computation once
//    pos[v] is known.  Same comparator as linearize_tree's kids sort.
// 4. scatter out[pos[v]] = v (positions are a permutation — disjoint).
//
// Within-level ORDER of the bfs array depends on the thread partition,
// but nothing below derives from it (levels are sets); the OUTPUT is
// thread-count-invariant and bit-identical to the sequential path.
constexpr int64_t kLevelParMin = 1 << 13;

void linearize_tree_levelsync(vid root, const std::vector<int64_t> &adj_ptr,
                              const std::vector<vid> &adj,
                              std::vector<vid> &parent,
                              std::vector<vid> &subtree,
                              std::vector<vid> &pos, std::vector<vid> &order,
                              std::vector<int64_t> &level_lo, int T,
                              int64_t *out, int64_t &out_pos) {
  order.clear();
  level_lo.clear();
  order.push_back(root);
  parent[root] = -1;
  level_lo.push_back(0);
  // Pass 1: BFS levels.
  {
    std::vector<std::vector<vid>> parts(std::max(T, 1));
    size_t lo = 0;
    while (lo < order.size()) {
      size_t hi = order.size();
      int64_t width = static_cast<int64_t>(hi - lo);
      if (T <= 1 || width < kLevelParMin) {
        for (size_t i = lo; i < hi; ++i) {
          vid v = order[i];
          for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
            vid u = adj[e];
            if (u != parent[v]) {
              parent[u] = v;
              order.push_back(u);
            }
          }
        }
      } else {
        parallel_ranges(width, T, [&](int tid, int64_t a, int64_t b) {
          auto &buf = parts[tid];
          buf.clear();
          for (int64_t i = a; i < b; ++i) {
            vid v = order[lo + i];
            for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
              vid u = adj[e];
              if (u != parent[v]) {
                parent[u] = v;   // u's unique parent: conflict-free
                buf.push_back(u);
              }
            }
          }
        }, kLevelParMin);
        for (auto &p : parts) {
          order.insert(order.end(), p.begin(), p.end());
          p.clear();
        }
      }
      lo = hi;
      level_lo.push_back(static_cast<int64_t>(order.size()));
    }
  }
  const int n_levels = static_cast<int>(level_lo.size()) - 1;
  if (std::getenv("AMT_DECOMP_PROFILE") != nullptr) {
    int64_t widest = 0;
    for (int L = 0; L < n_levels; ++L) {
      widest = std::max(widest, level_lo[L + 1] - level_lo[L]);
    }
    // widest >= kLevelParMin (2^13) means the per-level sweeps
    // actually ran their parallel branch, not just the level-sync
    // dispatch — the attribution the parity tests need.
    std::fprintf(stderr,
                 "[decomp-native] levelsync: %lld vertices, %d levels, "
                 "widest %lld\n",
                 static_cast<long long>(order.size()), n_levels,
                 static_cast<long long>(widest));
  }
  // Pass 2: subtree sizes, deepest level first.
  for (int L = n_levels - 1; L >= 0; --L) {
    int64_t lo = level_lo[L], width = level_lo[L + 1] - level_lo[L];
    parallel_ranges(width, T, [&](int, int64_t a, int64_t b) {
      for (int64_t i = a; i < b; ++i) {
        vid v = order[lo + i];
        vid s = 1;
        for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
          vid u = adj[e];
          if (parent[u] == v) s += subtree[u];
        }
        subtree[v] = s;
      }
    }, kLevelParMin);
  }
  // Pass 3: positions, top level first.
  pos[root] = static_cast<vid>(out_pos);
  for (int L = 0; L < n_levels; ++L) {
    int64_t lo = level_lo[L], width = level_lo[L + 1] - level_lo[L];
    parallel_ranges(width, T, [&](int, int64_t a, int64_t b) {
      std::vector<std::pair<vid, vid>> kids;
      for (int64_t i = a; i < b; ++i) {
        vid v = order[lo + i];
        kids.clear();
        for (int64_t e = adj_ptr[v]; e < adj_ptr[v + 1]; ++e) {
          vid u = adj[e];
          if (parent[u] == v) kids.emplace_back(subtree[u], u);
        }
        std::sort(kids.begin(), kids.end());
        vid p = pos[v] + 1;
        for (auto &su : kids) {
          pos[su.second] = p;
          p += su.first;
        }
      }
    }, kLevelParMin);
  }
  // Pass 4: scatter.
  int64_t total = static_cast<int64_t>(order.size());
  parallel_ranges(total, T, [&](int, int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      vid v = order[i];
      out[pos[v]] = v;
    }
  }, kLevelParMin);
  out_pos += total;
}

// Core of the random-forest linearization once the unique undirected
// edge list (u < v, packed, vertex ids in [0, n)) is in hand:
// shuffled-edge Kruskal, forest adjacency, per-component emit.
int forest_order_from_edges(vid n, std::vector<uint64_t> &edges,
                            uint64_t seed, int64_t base_size,
                            int64_t *out) {
  const int64_t m = static_cast<int64_t>(edges.size());

  // Shuffled-edge Kruskal == Kruskal on iid random weights == a random
  // spanning forest (reference GraphAlgorithms.jl:45-80 sorts random
  // weights; a Fisher-Yates shuffle of edge ids is the same ordering).
  // v2: the PACKED pairs are shuffled in place — the same splitmix64
  // swap sequence as v1's id shuffle applies the identical permutation,
  // but the Kruskal pass below then scans LINEARLY instead of gathering
  // 16 B per edge at random (the v1 profile's hottest native phase).
  {
    PhaseTimer t("shuffle");
    uint64_t state = seed ^ 0xdeadbeefcafef00dULL;
    for (int64_t i = m - 1; i > 0; --i) {
      int64_t j = static_cast<int64_t>(splitmix64(state) % (i + 1));
      std::swap(edges[i], edges[j]);
    }
  }

  const int T = n_threads();
  UnionFind uf(n);
  std::vector<vid> tu, tv;
  {
    PhaseTimer t(T > 1 && m >= (1 << 19) ? "kruskal-filter" : "kruskal");
    tu.reserve(n);
    tv.reserve(n);
    auto unite_edge = [&](int64_t i) {
      vid a = static_cast<vid>(edges[i] >> 32);
      vid b = static_cast<vid>(edges[i] & 0xffffffffu);
      if (uf.unite(a, b)) {
        tu.push_back(a);
        tv.push_back(b);
      }
    };
    if (T <= 1 || m < (1 << 19)) {
      for (int64_t i = 0; i < m; ++i) unite_edge(i);
    } else {
      // Filter-Kruskal over the shuffled stream (the shuffled position
      // IS the random weight, so the MSF is unique): unite the first
      // chunk sequentially, then for each subsequent (doubling) chunk
      // first drop — in parallel, with the read-only find — every edge
      // whose endpoints are already connected.  Filtering only removes
      // edges that can never be tree edges at their position, so the
      // tree-edge sequence (and the forest) is BIT-IDENTICAL to the
      // plain scan for every thread count.  After the first ~2n edges
      // the forest is nearly complete and the filter kills almost all
      // of the remaining stream, leaving the sequential unite with
      // O(n)-ish survivors.
      int64_t done = std::min<int64_t>(
          m, std::max<int64_t>(2 * static_cast<int64_t>(n), 1 << 19));
      for (int64_t i = 0; i < done; ++i) unite_edge(i);
      std::vector<char> keep;
      int64_t chunk = done;
      while (done < m) {
        int64_t c = std::min(m - done, chunk);
        keep.assign(c, 0);
        parallel_ranges(c, T, [&](int, int64_t lo, int64_t hi) {
          for (int64_t i = lo; i < hi; ++i) {
            vid a = static_cast<vid>(edges[done + i] >> 32);
            vid b = static_cast<vid>(edges[done + i] & 0xffffffffu);
            keep[i] = uf.find_ro(a) != uf.find_ro(b);
          }
        });
        for (int64_t i = 0; i < c; ++i) {
          if (keep[i]) unite_edge(done + i);
        }
        done += c;
        chunk *= 2;
      }
    }
  }
  edges.clear();
  edges.shrink_to_fit();

  // Forest adjacency (CSR, both directions).  Parallel mode partitions
  // by DESTINATION vertex range (the sym-transpose-count recipe): each
  // thread scans the whole tree-edge list but touches only its own
  // disjoint adj_ptr/fill/adj slice, in the same scan order — output
  // identical to the sequential fill, no atomics.
  std::vector<int64_t> adj_ptr(n + 1, 0);
  std::vector<vid> adj;
  {
    PhaseTimer t("forest-adjacency");
    const int64_t nt = static_cast<int64_t>(tu.size());
    if (T <= 1 || n < (1 << 18)) {
      for (int64_t i = 0; i < nt; ++i) {
        ++adj_ptr[tu[i] + 1];
        ++adj_ptr[tv[i] + 1];
      }
    } else {
      parallel_ranges(n, T, [&](int, int64_t v_lo, int64_t v_hi) {
        for (int64_t i = 0; i < nt; ++i) {
          if (tu[i] >= v_lo && tu[i] < v_hi) ++adj_ptr[tu[i] + 1];
          if (tv[i] >= v_lo && tv[i] < v_hi) ++adj_ptr[tv[i] + 1];
        }
      });
    }
    for (vid v = 0; v < n; ++v) adj_ptr[v + 1] += adj_ptr[v];
    adj.resize(adj_ptr[n]);
    std::vector<int64_t> fill(adj_ptr.begin(), adj_ptr.end() - 1);
    if (T <= 1 || n < (1 << 18)) {
      for (int64_t i = 0; i < nt; ++i) {
        adj[fill[tu[i]]++] = tv[i];
        adj[fill[tv[i]]++] = tu[i];
      }
    } else {
      parallel_ranges(n, T, [&](int, int64_t v_lo, int64_t v_hi) {
        for (int64_t i = 0; i < nt; ++i) {
          if (tu[i] >= v_lo && tu[i] < v_hi) adj[fill[tu[i]]++] = tv[i];
          if (tv[i] >= v_lo && tv[i] < v_hi) adj[fill[tv[i]]++] = tu[i];
        }
      });
    }
  }

  // Emit components in order of smallest member (scipy's label order in
  // linearize.py).  parent doubles as the visited marker: -2 unvisited.
  PhaseTimer t_emit(T > 1 ? "linearize-emit-par" : "linearize-emit");
  std::vector<vid> parent(n, -2), subtree(n, 0), preorder, stack;
  std::vector<vid> members;
  // Scratch for the level-synchronous path, allocated on first use.
  std::vector<vid> ls_pos, ls_order;
  std::vector<int64_t> ls_levels;
  int64_t out_pos = 0;
  for (vid v = 0; v < n; ++v) {
    if (parent[v] != -2) continue;
    vid root = uf.find(v);
    int64_t comp_size = uf.size[root];
    if (comp_size <= base_size) {
      // Small component: ascending vertex ids.  Collect by BFS over the
      // forest (spanning: reaches every member), then sort.
      members.clear();
      members.push_back(v);
      parent[v] = -1;
      for (size_t h = 0; h < members.size(); ++h) {
        vid w = members[h];
        for (int64_t e = adj_ptr[w]; e < adj_ptr[w + 1]; ++e) {
          vid u = adj[e];
          if (parent[u] == -2) {
            parent[u] = w;
            members.push_back(u);
          }
        }
      }
      std::sort(members.begin(), members.end());
      for (vid w : members) out[out_pos++] = w;
    } else if (T > 1 && comp_size >= (1 << 16)) {
      if (ls_pos.empty()) {
        ls_pos.resize(n);
        ls_order.reserve(comp_size);
      }
      linearize_tree_levelsync(v, adj_ptr, adj, parent, subtree, ls_pos,
                               ls_order, ls_levels, T, out, out_pos);
    } else {
      linearize_tree(v, adj_ptr, adj, parent, subtree, preorder, stack,
                     out, out_pos);
    }
  }
  return out_pos == n ? 0 : 1;
}

// Indices accessor generic over the CSR index dtype (int32 = scipy's
// native dtype below 2^31 nnz — v1 forced an int64 conversion COPY of
// the whole index array per call).
template <typename IDX>
void extract_edges(vid n, const int64_t *indptr, const IDX *indices,
                   std::vector<uint64_t> &edges) {
  PhaseTimer t("edge-extract");
  int T = n_threads();
  std::vector<std::vector<uint64_t>> parts(std::max(T, 1));
  parallel_ranges(n, T, [&](int tid, int64_t lo, int64_t hi) {
    auto &buf = parts[tid];
    buf.reserve((indptr[hi] - indptr[lo]) / 2);
    for (int64_t u = lo; u < hi; ++u) {
      for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
        int64_t v = static_cast<int64_t>(indices[e]);
        if (u < v)
          buf.push_back(pack_edge(static_cast<vid>(u),
                                  static_cast<vid>(v)));
      }
    }
  });
  size_t total = 0;
  for (auto &p : parts) total += p.size();
  edges.clear();
  edges.reserve(total);
  for (auto &p : parts) {   // in tid order: deterministic edge order
    edges.insert(edges.end(), p.begin(), p.end());
    p.clear();
    p.shrink_to_fit();
  }
}

template <typename IDX>
void extract_edges_masked(vid n, const int64_t *indptr, const IDX *indices,
                          int64_t k, const int64_t *active,
                          const std::vector<vid> &label,
                          std::vector<uint64_t> &edges) {
  PhaseTimer t("edge-extract-masked");
  int T = n_threads();
  std::vector<std::vector<uint64_t>> parts(std::max(T, 1));
  parallel_ranges(k, T, [&](int tid, int64_t lo, int64_t hi) {
    auto &buf = parts[tid];
    for (int64_t i = lo; i < hi; ++i) {
      int64_t u = active[i];
      for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
        vid lv = label[indices[e]];
        if (lv > i)
          buf.push_back(pack_edge(static_cast<vid>(i), lv));
      }
    }
  });
  size_t total = 0;
  for (auto &p : parts) total += p.size();
  edges.clear();
  edges.reserve(total);
  for (auto &p : parts) {
    edges.insert(edges.end(), p.begin(), p.end());
    p.clear();
    p.shrink_to_fit();
  }
}

template <typename IDX>
int forest_order_impl(int64_t n64, const int64_t *indptr,
                      const IDX *indices, uint64_t seed,
                      int64_t base_size, int64_t *out) {
  if (n64 == 0) return 0;
  if (n64 > INT32_MAX) return 3;
  vid n = static_cast<vid>(n64);
  std::vector<uint64_t> edges;
  extract_edges(n, indptr, indices, edges);
  return forest_order_from_edges(n, edges, seed, base_size, out);
}

template <typename IDX>
int forest_order_masked_impl(int64_t n64, const int64_t *indptr,
                             const IDX *indices, uint64_t seed,
                             int64_t base_size, int64_t k,
                             const int64_t *active, int64_t *out) {
  // Forest order of the induced submatrix sym[active][:, active]
  // WITHOUT materializing it: one O(n + m) label-and-filter pass
  // replaces scipy's fancy-indexed row+column extraction.  ``active``
  // holds the original vertex id of each submatrix position; ``out``
  // receives a permutation of [0, k) in submatrix positions — the same
  // contract as running the full forest order on the materialized
  // submatrix.
  if (k == 0) return 0;
  if (n64 > INT32_MAX || k > INT32_MAX) return 3;
  vid n = static_cast<vid>(n64);
  std::vector<vid> label(n, -1);
  for (int64_t i = 0; i < k; ++i) {
    if (active[i] < 0 || active[i] >= n64 || label[active[i]] != -1)
      return 2;  // not a valid vertex subset
    label[active[i]] = static_cast<vid>(i);
  }
  std::vector<uint64_t> edges;
  extract_edges_masked(n, indptr, indices, k, active, label, edges);
  return forest_order_from_edges(static_cast<vid>(k), edges, seed,
                                 base_size, out);
}

template <typename IDX>
int bfs_order_impl(int64_t n64, const int64_t *indptr, const IDX *indices,
                   int64_t base_size, int64_t *out) {
  if (n64 == 0) return 0;
  if (n64 > INT32_MAX) return 3;
  vid n = static_cast<vid>(n64);
  std::vector<vid> queue;
  std::vector<char> visited(n, 0);
  int64_t out_pos = 0;
  for (vid v = 0; v < n; ++v) {
    if (visited[v]) continue;
    // BFS the component (reference masked BFS,
    // GraphAlgorithms.jl:83-195).
    queue.clear();
    queue.push_back(v);
    visited[v] = 1;
    for (size_t h = 0; h < queue.size(); ++h) {
      vid w = queue[h];
      for (int64_t e = indptr[w]; e < indptr[w + 1]; ++e) {
        vid u = static_cast<vid>(indices[e]);
        if (!visited[u]) {
          visited[u] = 1;
          queue.push_back(u);
        }
      }
    }
    if (static_cast<int64_t>(queue.size()) <= base_size) {
      std::sort(queue.begin(), queue.end());
    }
    for (vid w : queue) out[out_pos++] = w;
  }
  return out_pos == n64 ? 0 : 1;
}

// Structure-only A + A^T: sorted, deduped CSR pattern (what the whole
// linear-order pipeline consumes — scipy's value-carrying A + A.T was
// the single largest host phase in the v1 profile).  out_indices must
// have capacity 2 * nnz; returns the symmetric nnz, or -1 on error.
template <typename IDX>
int64_t symmetrize_structure_impl(int64_t n64, const int64_t *indptr,
                                  const IDX *indices, int64_t *out_indptr,
                                  int32_t *out_indices) {
  if (n64 > INT32_MAX) return -1;
  vid n = static_cast<vid>(n64);
  const int64_t nnz = indptr[n];
  int T = n_threads();

  // Transpose counts.  Parallel mode partitions by DESTINATION column
  // range — each thread scans the whole index array but increments
  // only its disjoint slice of the ONE shared histogram (no per-thread
  // O(n) copies: T x 8 B x n transient histograms would rival the
  // graph's own index arrays at the 10^8-row target).  Deterministic
  // and race-free by construction.
  std::vector<int64_t> t_ptr(static_cast<size_t>(n) + 1, 0);
  {
    PhaseTimer t("sym-transpose-count");
    if (T <= 1 || nnz < (1 << 18)) {
      for (int64_t e = 0; e < nnz; ++e) ++t_ptr[indices[e] + 1];
    } else {
      parallel_ranges(n, T, [&](int, int64_t col_lo, int64_t col_hi) {
        for (int64_t e = 0; e < nnz; ++e) {
          int64_t c = static_cast<int64_t>(indices[e]);
          if (c >= col_lo && c < col_hi) ++t_ptr[c + 1];
        }
      });
    }
    for (vid v = 0; v < n; ++v) t_ptr[v + 1] += t_ptr[v];
  }

  // Transpose fill: the ascending row scan makes every transpose row
  // sorted by construction.  Above a size cutoff the single-pass
  // scatter (random writes across the whole t_idx span) is replaced
  // by a BUCKETED two-pass fill: pass A streams (col, row) pairs into
  // ~256 column-range buckets (sequential writes), pass B scatters
  // within one bucket at a time (its fill span fits cache).  Each
  // bucket receives entries in ascending row order, so the per-column
  // order — and therefore the output — is bit-identical.
  std::vector<vid> t_idx(nnz);
  {
    PhaseTimer t("sym-transpose-fill");
    if (nnz < (1 << 22)) {
      std::vector<int64_t> fill(t_ptr.begin(), t_ptr.end() - 1);
      for (vid u = 0; u < n; ++u) {
        for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
          t_idx[fill[indices[e]]++] = u;
        }
      }
    } else {
      const int n_buckets = 256;
      // Shift must be derived from the MAX ID (n-1), not n: bucket
      // index is (id >> shift) and must stay < n_buckets for every
      // id.  Deriving it from n left id n-1 mapping to bucket 256
      // for any n in (256*2^s, 257*2^s] — an out-of-bounds b_count/
      // bf write AND a bucket pass B never scattered (ADVICE r4).
      const int shift = [&] {
        int s = 0;
        while ((static_cast<int64_t>(n - 1) >> s) >= n_buckets) ++s;
        return s;
      }();
      std::vector<int64_t> b_count(n_buckets + 1, 0);
      for (vid u = 0; u < n; ++u) {
        for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
          ++b_count[(indices[e] >> shift) + 1];
        }
      }
      for (int b = 0; b < n_buckets; ++b) b_count[b + 1] += b_count[b];
      std::vector<uint64_t> pairs(nnz);   // (col << 32) | row
      {
        std::vector<int64_t> bf(b_count.begin(), b_count.end() - 1);
        for (vid u = 0; u < n; ++u) {
          for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
            vid c = static_cast<vid>(indices[e]);
            pairs[bf[c >> shift]++] = pack_edge(c, u);
          }
        }
      }
      std::vector<int64_t> fill(t_ptr.begin(), t_ptr.end() - 1);
      for (int b = 0; b < n_buckets; ++b) {
        for (int64_t i = b_count[b]; i < b_count[b + 1]; ++i) {
          vid c = static_cast<vid>(pairs[i] >> 32);
          t_idx[fill[c]++] = static_cast<vid>(pairs[i] & 0xffffffffu);
        }
      }
    }
  }

  // Per-row union of the A row (sorted on demand) and the transpose
  // row (sorted by construction), deduped, written compacted.
  {
    PhaseTimer t("sym-merge");
    std::vector<vid> arow;
    int64_t pos = 0;
    out_indptr[0] = 0;
    for (vid u = 0; u < n; ++u) {
      const int64_t a_lo = indptr[u], a_hi = indptr[u + 1];
      arow.assign(indices + a_lo, indices + a_hi);
      // Input CSR rows are not guaranteed canonical (the decomposer
      // accepts any tocsr()); sort+dedup the A row locally.  Most
      // rows ARE already sorted (level_split emits canonical levels
      // and row-ordered rests) — the linear is_sorted check skips the
      // O(d log d) sort for them.
      if (!std::is_sorted(arow.begin(), arow.end())) {
        std::sort(arow.begin(), arow.end());
      }
      arow.erase(std::unique(arow.begin(), arow.end()), arow.end());
      const vid *b = t_idx.data() + t_ptr[u];
      const vid *b_end = t_idx.data() + t_ptr[u + 1];
      const vid *a = arow.data();
      const vid *a_end = a + arow.size();
      while (a < a_end && b < b_end) {
        vid av = *a, bv = *b;
        vid w = av < bv ? av : bv;
        out_indices[pos++] = w;
        if (av <= bv) ++a;
        if (bv <= av) {
          // Skip duplicate transpose entries (parallel edges).
          do {
            ++b;
          } while (b < b_end && *b == bv);
        }
      }
      while (a < a_end) out_indices[pos++] = *a++;
      while (b < b_end) {
        vid bv = *b;
        out_indices[pos++] = bv;
        do {
          ++b;
        } while (b < b_end && *b == bv);
      }
      out_indptr[u + 1] = pos;
    }
    return pos;
  }
}

// Fused per-level edge routing (v2): one pass over the source CSR
// replaces the numpy chain tocoo -> inv-gather -> boolean select ->
// two scipy COO->CSR builds (+ sum_duplicates + sort_indices) that the
// v1 profile measured at ~10 s of 37 s (n=2^21).  Classifies every
// entry by the arrow criterion in PERMUTED coordinates, emits
//   * the level matrix as canonical CSR in permuted coordinates
//     (rows sorted, duplicates summed — what the tiling builders
//     require), and
//   * the remainder as CSR in ORIGINAL coordinates (the recursion
//     re-linearizes it; canonical form not required, matching the
//     numpy path's coo build).
// data == nullptr means implicit-ones values (level_data still
// emitted, as ones, so the scipy wrapper is uniform).
template <typename IDX, typename VAL>
int level_split_impl(int64_t n64, const int64_t *indptr,
                     const IDX *indices, const VAL *data,
                     const int32_t *inv, int64_t width,
                     int block_diagonal, int prune,
                     int64_t *lvl_indptr, int32_t *lvl_indices,
                     VAL *lvl_data, int64_t *rest_indptr,
                     int32_t *rest_indices, VAL *rest_data,
                     int64_t *counts /* [lvl_nnz, rest_nnz] out */) {
  if (n64 > INT32_MAX) return 3;
  vid n = static_cast<vid>(n64);
  const int64_t w = width;

  auto in_level = [&](vid rp, vid cp) -> bool {
    bool in;
    if (block_diagonal) {
      in = (rp / w) == (cp / w);
    } else {
      int64_t d = static_cast<int64_t>(rp) - cp;
      in = (d < 0 ? -d : d) <= w;
    }
    if (prune) in = in || rp < w || cp < w;
    return in;
  };

  // Pass 1: count level entries per PERMUTED row, rest entries per
  // SOURCE row.  The permuted columns are CACHED (one int32 per
  // entry) so pass 2 reruns no random inv[] gather — the gathers are
  // the passes' dominant cost (split profile, PERFORMANCE.md).
  const int64_t nnz = indptr[n];
  std::vector<int64_t> lvl_count(static_cast<size_t>(n) + 1, 0);
  std::vector<vid> cp_cache(nnz);
  int64_t rest_total = 0;
  {
    PhaseTimer t("split-count");
    rest_indptr[0] = 0;
    for (vid u = 0; u < n; ++u) {
      vid rp = inv[u];
      int64_t rest_row = 0;
      for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
        vid cp = inv[indices[e]];
        cp_cache[e] = cp;
        if (in_level(rp, cp)) {
          ++lvl_count[rp + 1];
        } else {
          ++rest_row;
        }
      }
      rest_total += rest_row;
      rest_indptr[u + 1] = rest_total;
    }
  }
  int64_t lvl_total = nnz - rest_total;
  if (lvl_total == 0 && rest_total > 0) {
    // Degenerate all-False case: the caller keeps every edge in the
    // level instead (decompose.py's fallback) — signal it.
    return 4;
  }

  // Level row offsets.
  {
    lvl_indptr[0] = 0;
    for (vid v = 0; v < n; ++v)
      lvl_indptr[v + 1] = lvl_indptr[v] + lvl_count[v + 1];
  }

  // Pass 2: fill both outputs.
  {
    PhaseTimer t("split-fill");
    std::vector<int64_t> fill(lvl_indptr, lvl_indptr + n);
    int64_t rpos = 0;
    for (vid u = 0; u < n; ++u) {
      vid rp = inv[u];
      for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
        vid cp = cp_cache[e];
        VAL val = data ? data[e] : static_cast<VAL>(1);
        if (in_level(rp, cp)) {
          int64_t p = fill[rp]++;
          lvl_indices[p] = cp;
          lvl_data[p] = val;
        } else {
          rest_indices[rpos] = static_cast<int32_t>(indices[e]);
          rest_data[rpos] = val;
          ++rpos;
        }
      }
    }
  }

  // Pass 3: canonicalize the level rows (sort by column, sum
  // duplicates, compact).  Rows are short (<= a few hundred); an
  // insertion-friendly std::sort per row is cache-local.
  {
    PhaseTimer t("split-canonicalize");
    std::vector<std::pair<int32_t, VAL>> row;
    int64_t wpos = 0;
    int64_t read_base = 0;
    for (vid v = 0; v < n; ++v) {
      int64_t lo = read_base, hi = lvl_indptr[v + 1];
      read_base = hi;
      row.clear();
      for (int64_t e = lo; e < hi; ++e)
        row.emplace_back(lvl_indices[e], lvl_data[e]);
      std::sort(row.begin(), row.end(),
                [](const auto &x, const auto &y) {
                  return x.first < y.first;
                });
      int64_t row_start = wpos;
      for (size_t i = 0; i < row.size(); ++i) {
        if (wpos > row_start &&
            lvl_indices[wpos - 1] == row[i].first) {
          lvl_data[wpos - 1] += row[i].second;
        } else {
          lvl_indices[wpos] = row[i].first;
          lvl_data[wpos] = row[i].second;
          ++wpos;
        }
      }
      lvl_indptr[v + 1] = wpos;
    }
    lvl_total = wpos;
  }

  counts[0] = lvl_total;
  counts[1] = rest_total;
  return 0;
}

}  // namespace

extern "C" {

int amt_random_forest_order(int64_t n, const int64_t *indptr,
                            const int64_t *indices, uint64_t seed,
                            int64_t base_size, int64_t *out) {
  return forest_order_impl(n, indptr, indices, seed, base_size, out);
}

int amt_random_forest_order_i32(int64_t n, const int64_t *indptr,
                                const int32_t *indices, uint64_t seed,
                                int64_t base_size, int64_t *out) {
  return forest_order_impl(n, indptr, indices, seed, base_size, out);
}

int amt_random_forest_order_masked(int64_t n, const int64_t *indptr,
                                   const int64_t *indices, uint64_t seed,
                                   int64_t base_size, int64_t k,
                                   const int64_t *active, int64_t *out) {
  return forest_order_masked_impl(n, indptr, indices, seed, base_size, k,
                                  active, out);
}

int amt_random_forest_order_masked_i32(int64_t n, const int64_t *indptr,
                                       const int32_t *indices,
                                       uint64_t seed, int64_t base_size,
                                       int64_t k, const int64_t *active,
                                       int64_t *out) {
  return forest_order_masked_impl(n, indptr, indices, seed, base_size, k,
                                  active, out);
}

int amt_bfs_order(int64_t n, const int64_t *indptr, const int64_t *indices,
                  int64_t base_size, int64_t *out) {
  return bfs_order_impl(n, indptr, indices, base_size, out);
}

int amt_bfs_order_i32(int64_t n, const int64_t *indptr,
                      const int32_t *indices, int64_t base_size,
                      int64_t *out) {
  return bfs_order_impl(n, indptr, indices, base_size, out);
}

int64_t amt_symmetrize_structure(int64_t n, const int64_t *indptr,
                                 const int64_t *indices,
                                 int64_t *out_indptr,
                                 int32_t *out_indices) {
  return symmetrize_structure_impl(n, indptr, indices, out_indptr,
                                   out_indices);
}

int64_t amt_symmetrize_structure_i32(int64_t n, const int64_t *indptr,
                                     const int32_t *indices,
                                     int64_t *out_indptr,
                                     int32_t *out_indices) {
  return symmetrize_structure_impl(n, indptr, indices, out_indptr,
                                   out_indices);
}

#define AMT_LEVEL_SPLIT(NAME, IDX, VAL)                                   \
  int NAME(int64_t n, const int64_t *indptr, const IDX *indices,          \
           const VAL *data, const int32_t *inv, int64_t width,            \
           int block_diagonal, int prune, int64_t *lvl_indptr,            \
           int32_t *lvl_indices, VAL *lvl_data, int64_t *rest_indptr,     \
           int32_t *rest_indices, VAL *rest_data, int64_t *counts) {      \
    return level_split_impl(n, indptr, indices, data, inv, width,         \
                            block_diagonal, prune, lvl_indptr,            \
                            lvl_indices, lvl_data, rest_indptr,           \
                            rest_indices, rest_data, counts);             \
  }

AMT_LEVEL_SPLIT(amt_level_split_i32_f32, int32_t, float)
AMT_LEVEL_SPLIT(amt_level_split_i32_f64, int32_t, double)
AMT_LEVEL_SPLIT(amt_level_split_i64_f32, int64_t, float)
AMT_LEVEL_SPLIT(amt_level_split_i64_f64, int64_t, double)

#undef AMT_LEVEL_SPLIT

}  // extern "C"
