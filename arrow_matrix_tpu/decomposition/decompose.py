"""Offline arrow decomposition of a sparse matrix.

Decomposes a square sparse matrix ``A`` (typically a graph adjacency) into
levels ``B_0..B_{K-1}`` with permutations ``sigma_0..sigma_{K-1}`` such
that  ``A = sum_i P_i^T B_i P_i``  where ``P_i`` permutes index ``r`` to
``sigma_i[r]``; equivalently ``B_i = A[sigma_i][:, sigma_i]`` restricted
to level-i edges.  Each ``B_i`` is *arrow-shaped*: nonzeros only in the
first ``width`` rows, the first ``width`` columns, and a band (or the
block diagonal) of width ``width`` around the diagonal.

Host-side algorithm (numpy/scipy), re-designed from the reference's
igraph version (reference arrow/decomposition.py:32-144):
  per level: prune the ``width`` highest-degree vertices to the front,
  linearize the rest by random-spanning-forest DFS, select the edges that
  fit the arrow (vectorized band/block criterion on COO coordinates —
  replacing the reference's per-edge ``es.select`` lambdas, a noted
  hotspot, decomposition.py:84), recurse on the remainder.

The decomposition runs on the host: it is graph preprocessing, not device
code.  The online runtime consumes its output via
``arrow_matrix_tpu.io``.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from dataclasses import dataclass

import numpy as np
from scipy import sparse

from arrow_matrix_tpu.decomposition.linearize import bfs_order, random_forest_order
from arrow_matrix_tpu.utils.graphs import symmetrize


@contextmanager
def _phase(label: str):
    """Phase timer for the offline pipeline (AMT_DECOMP_PROFILE=1):
    prints per-phase wall seconds to stderr so the scale-ladder rungs
    can attribute decompose time between the native kernels and the
    scipy host work (the optimization targeting data for the
    reference's Julia-layer role)."""
    if not os.environ.get("AMT_DECOMP_PROFILE"):
        yield
        return
    import sys

    t0 = time.perf_counter()
    yield
    print(f"[decomp] {label}: {time.perf_counter() - t0:.2f}s",
          file=sys.stderr, flush=True)


@dataclass
class ArrowLevel:
    """One level of an arrow decomposition.

    matrix:       the permuted, arrow-shaped sparse matrix B_i (CSR).
    permutation:  sigma_i; ``permutation[r]`` is the original index of
                  row r of ``matrix``.
    arrow_width:  the width bound satisfied by ``matrix`` (the last level
                  may exceed the requested width; see
                  ``arrow_decomposition``).
    """

    matrix: sparse.csr_matrix
    permutation: np.ndarray
    arrow_width: int

    @property
    def nonzero_rows(self) -> int:
        """Number of structurally nonzero rows/cols (correct count — the
        reference stores the number of *zero*-degree vertices under this
        name, a known bug; SURVEY.md §7)."""
        sym = self.matrix + self.matrix.T
        return int(np.count_nonzero(np.diff(sym.tocsr().indptr)))

    @property
    def inverse_permutation(self) -> np.ndarray:
        return np.argsort(self.permutation)


def achieved_width(coo_rows: np.ndarray, coo_cols: np.ndarray, width: int) -> int:
    """Smallest band width >= ``width`` covering all edges outside the
    arrow head (rows/cols < width are head edges and always covered)."""
    outside = (coo_rows >= width) & (coo_cols >= width)
    if not np.any(outside):
        return width
    return max(width, int(np.max(np.abs(coo_rows[outside] - coo_cols[outside]))))


def _resolve_backend(backend: str):
    """Pick the linearization implementation.

    ``numpy``: the scipy/csgraph implementation in ``linearize.py``.
    ``native``: the C++ kernels (``native.py``; error if unavailable) —
        the compiled-performance layer, the reference's Julia-module
        role (julia/arrow/*.jl).
    ``auto``: native when it loads, numpy otherwise.
    """
    if backend not in ("auto", "native", "numpy"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend == "numpy":
        return bfs_order, random_forest_order
    from arrow_matrix_tpu.decomposition import native

    if native.available():
        return native.bfs_order, native.random_forest_order
    if backend == "native":
        raise RuntimeError(
            f"backend='native' requested but the native decomposer "
            f"failed to build/load: {native.load_error()}")
    return bfs_order, random_forest_order


def _linear_order(a: sparse.csr_matrix, width: int, deterministic: bool,
                  rng: np.random.Generator,
                  backend: str = "auto") -> np.ndarray:
    """Level ordering: width highest-degree vertices first, then the
    forest-linearized middle, then zero-degree singletons."""
    n = a.shape[0]
    bfs_fn, forest_fn = _resolve_backend(backend)
    from arrow_matrix_tpu.decomposition import native as _native

    # All-native fast path: structure-only symmetrize (the largest
    # single host phase of the v1 profile — scipy's A + A.T carries
    # values the pipeline never reads) feeding the masked forest
    # kernel, no scipy matrix ever built.  Same sorted/deduped
    # structure as symmetrize(), so the resulting decomposition is
    # bit-identical (the v1-vs-v2 parity test pins this).
    native_path = (not deterministic
                   and forest_fn is _native.random_forest_order
                   and n < np.iinfo(np.int32).max)
    if native_path:
        with _phase("symmetrize"):
            sym = _native.symmetrize_structure(a)   # (indptr, indices)
        deg = np.diff(sym[0])
    else:
        with _phase("symmetrize"):
            sym = symmetrize(a)
        deg = np.diff(sym.indptr)

    with _phase("degree-argsort"):
        by_degree = np.argsort(-deg, kind="stable")
    head = by_degree[:width]
    tail = by_degree[width:]
    tail_deg = deg[tail]
    middle = tail[tail_deg > 0]
    singletons = tail[tail_deg == 0]

    if middle.size:
        if native_path:
            # The induced submatrix never materializes — one
            # label-and-filter pass inside the C++ replaces scipy's
            # fancy-indexed sym[middle][:, middle] (saves a full
            # per-level edge copy; PERFORMANCE.md decomposer profile).
            with _phase("forest-native"):
                sub_order = _native.random_forest_order_masked(
                    sym, middle, rng, base_size=min(width - 1, 16))
        else:
            sub = sym[middle][:, middle]
            if deterministic:
                sub_order = bfs_fn(sub)
            else:
                sub_order = forest_fn(sub, rng,
                                      base_size=min(width - 1, 16))
        middle_order = middle[sub_order]
    else:
        middle_order = middle

    order = np.concatenate([head, middle_order, singletons])
    assert order.size == n
    return order.astype(np.int64)


def _single_banded_level(a: sparse.csr_matrix,
                         perm: np.ndarray | None,
                         arrow_width: int) -> ArrowLevel:
    """One-level decomposition of an (optionally reordered) banded
    matrix.  Reports the REQUESTED width — artifacts are saved/loaded
    under the level-0 width, so the tighter achieved bound would break
    the file-naming round-trip — and canonicalizes like every other
    level construction (the tiling builders require it)."""
    if perm is None:
        b = a.copy()
        perm = np.arange(a.shape[0], dtype=np.int64)
    else:
        b = a[perm][:, perm].tocsr()
    b.sum_duplicates()
    b.sort_indices()
    return ArrowLevel(matrix=b, permutation=perm,
                      arrow_width=arrow_width)


def arrow_decomposition(a: sparse.spmatrix,
                        arrow_width: int = 512,
                        max_levels: int = 2,
                        block_diagonal: bool = False,
                        prune: bool = True,
                        seed: int | None = None,
                        backend: str = "numpy",
                        band_detect: bool = True) -> list[ArrowLevel]:
    """Compute an arrow decomposition of a square sparse matrix.

    :param a: square sparse matrix (any scipy format; values preserved).
    :param arrow_width: desired head / band / block width.  The last
        level keeps all remaining edges and may report a larger
        ``arrow_width``.
    :param max_levels: maximum number of levels.
    :param block_diagonal: if True, in-level edges must fall in
        width-by-width blocks on the diagonal (required by the slim
        runtime layout); otherwise a band of width ``arrow_width``.
    :param prune: place the ``arrow_width`` highest-degree vertices first;
        their rows/columns always belong to the level (the arrow head).
    :param seed: RNG seed for the random-spanning-forest linearization.
    :param band_detect: detect banded/bandable inputs (identity or
        reverse-Cuthill-McKee order within ``arrow_width`` of the
        diagonal — the planar/mesh class) and return ONE level with
        zero inter-level routing instead of linearizing.  On by
        default; costs O(nnz) on graphs that fail the gate.
    :param backend: linearization implementation — "numpy" (scipy/
        csgraph; the default), "native" (C++ kernels, the reference's
        Julia-layer role; ~10x faster on large graphs), or "auto"
        (native when available).  The two backends use different RNG
        streams, so for a fixed seed the level structure depends on the
        backend; the default is "numpy" so seeded results never depend
        on toolchain presence — opt into "native"/"auto" for large
        graphs (the reference has the same split between its Python and
        Julia decomposers).
    """
    a = a.tocsr()
    if a.shape[0] != a.shape[1]:
        raise ValueError(f"matrix must be square, got {a.shape}")
    if arrow_width > a.shape[0]:
        raise ValueError(f"arrow_width {arrow_width} exceeds matrix side {a.shape[0]}")

    if backend not in ("auto", "native", "numpy"):
        raise ValueError(f"unknown backend {backend!r}")

    # Already-banded fast path: when every nonzero sits within
    # ``arrow_width`` of the diagonal, the matrix IS a one-level arrow
    # decomposition under the identity permutation (B_0 = A, sigma =
    # id; the runtime tiles a last level banded regardless of the
    # block_diagonal flag).  This is the planar/minor-excluded graph
    # class the reference paper's communication bound targets — e.g. a
    # row-major 2-D grid has bandwidth = side — and the forest
    # linearization would only scramble it into multiple levels with
    # inter-level routing that the natural order never needed.  O(nnz)
    # check; power-law graphs (hub rows reach everywhere) never take
    # it.
    if a.nnz and band_detect:
        coo = a.tocoo()
        # achieved_width at width 0 = the full bandwidth max|r-c| (one
        # band-math implementation for the gate and the per-level
        # accounting).
        bw = achieved_width(coo.row.astype(np.int64),
                            coo.col.astype(np.int64), 0)
        if bw <= arrow_width:
            return [_single_banded_level(a, None, arrow_width)]
        # Bandable under a reordering: reverse Cuthill-McKee (O(nnz),
        # measured 0.9 s at 16.8M nnz) recovers the natural band of a
        # planar/mesh graph in ANY input order.  Necessary-condition
        # pre-gate WITHOUT building A+A^T: deg_sym(i) <= row_deg(i) +
        # col_deg(i), and a band of half-width w holds <= 2w+1 entries
        # per symmetric row, so ub > 2*(2w+1) rejects hub graphs from
        # indptr + one bincount (no matrix construction); graphs that
        # pass pay one symmetrize shared with the RCM call.
        row_deg = np.diff(a.indptr)
        col_deg = np.bincount(coo.col, minlength=a.shape[0])
        ub = int((row_deg + col_deg).max())
        if ub <= 2 * (2 * arrow_width + 1):
            sym = symmetrize(a)
            max_deg = int(np.diff(sym.indptr).max()) if sym.nnz else 0
            if max_deg <= 2 * arrow_width + 1:
                from scipy.sparse import csgraph

                rcm = np.asarray(csgraph.reverse_cuthill_mckee(
                    sym, symmetric_mode=True), dtype=np.int64)
                inv = np.argsort(rcm)
                bw = achieved_width(inv[coo.row], inv[coo.col], 0)
                if bw <= arrow_width:
                    return [_single_banded_level(a, rcm, arrow_width)]

    rng = np.random.default_rng(seed)
    levels: list[ArrowLevel] = []
    _decompose(a, arrow_width, levels, max_levels, block_diagonal, prune, rng,
               backend)
    return levels


def _decompose(a: sparse.csr_matrix, width: int, levels: list[ArrowLevel],
               max_levels: int, block_diagonal: bool, prune: bool,
               rng: np.random.Generator, backend: str = "auto") -> None:
    n = a.shape[0]
    last = len(levels) + 1 >= max_levels

    with _phase("linear-order-total"):
        order = _linear_order(a, width, deterministic=last, rng=rng,
                              backend=backend)
    with _phase("inv-argsort"):
        inv = np.argsort(order)
        if n < np.iinfo(np.int32).max:
            # int32 positions halve the permute/select traffic and save
            # scipy the internal downcast copy its int32-index CSR
            # builders would otherwise make.
            inv = inv.astype(np.int32)

    if not last:
        # Fused native split: one C++ pass replaces the whole
        # tocoo/gather/select/two-CSR-build chain below (~10 s of the
        # 37 s v1 profile at n=2^21).  Bit-identical on duplicate-free
        # inputs (canonical CSR is unique; with duplicate input
        # entries only the f32 summation order can differ, inside the
        # numerics tolerance).  achieved_width is statically `width`
        # here: every non-head in-level edge satisfies |r-c| <= width
        # by the band/block criterion.
        from arrow_matrix_tpu.decomposition import native as _native

        if (backend in ("auto", "native") and _native.available()
                and n < np.iinfo(np.int32).max):
            try:
                with _phase("native-level-split"):
                    b, rest_m = _native.level_split(
                        a, inv, width, block_diagonal, prune)
                levels.append(ArrowLevel(b, order, width))
                if rest_m is not None:
                    _decompose(rest_m, width, levels, max_levels,
                               block_diagonal, prune, rng, backend)
                return
            except _native.LevelSplitUnsupported:
                pass   # numpy path below handles the degenerate cases

    with _phase("coo-permute"):
        coo = a.tocoo()
        r = inv[coo.row]  # positions in the new order
        c = inv[coo.col]

    if not last:
        with _phase("edge-select"):
            if block_diagonal:
                in_level = (r // width) == (c // width)
            else:
                in_level = np.abs(r - c) <= width
            if prune:
                in_level |= (r < width) | (c < width)

            if not np.any(in_level):
                in_level = np.ones(r.size, dtype=bool)

            rest = ~in_level
        with _phase("level-csr-build"):
            b = sparse.csr_matrix(
                (coo.data[in_level], (r[in_level], c[in_level])),
                shape=(n, n))
            b.sum_duplicates()
            b.sort_indices()
        # The all-False fallback above keeps every edge, so the level's
        # width bound is whatever those edges achieve, not the request.
        levels.append(ArrowLevel(b, order,
                                 achieved_width(r[in_level], c[in_level],
                                                width)))

        if np.any(rest):
            # Remainder keeps original indexing; recursion re-linearizes.
            with _phase("rest-csr-build"):
                a_rest = sparse.csr_matrix(
                    (coo.data[rest], (coo.row[rest], coo.col[rest])),
                    shape=(n, n))
            _decompose(a_rest, width, levels, max_levels, block_diagonal,
                       prune, rng, backend)
    else:
        # Last level: keep everything, report the width actually achieved.
        with _phase("level-csr-build"):
            b = sparse.csr_matrix((coo.data, (r, c)), shape=(n, n))
            b.sum_duplicates()
            b.sort_indices()
        levels.append(ArrowLevel(b, order, achieved_width(r, c, width)))


def reconstruct(levels: list[ArrowLevel]) -> sparse.csr_matrix:
    """Un-permute and sum all levels: returns sum_i P_i^T B_i P_i,
    which must equal the decomposed matrix (the core invariant)."""
    n = levels[0].matrix.shape[0]
    total = sparse.csr_matrix((n, n), dtype=levels[0].matrix.dtype)
    for lvl in levels:
        p = lvl.permutation
        coo = lvl.matrix.tocoo()
        total = total + sparse.csr_matrix(
            (coo.data, (p[coo.row], p[coo.col])), shape=(n, n))
    total.sum_duplicates()
    total.sort_indices()
    return total.tocsr()


def decomposition_spmm(levels: list[ArrowLevel], x: np.ndarray) -> np.ndarray:
    """Golden host-side SpMM through the decomposition:
    ``A @ X = sum_i (B_i @ X[sigma_i])[inv sigma_i]``
    (reference tests/test_arrowdecomposition.py:139-156)."""
    out = np.zeros_like(x)
    for lvl in levels:
        partial = lvl.matrix @ x[lvl.permutation]
        out += partial[lvl.inverse_permutation]
    return out
