"""graft-sync runtime: declared concurrency contracts + lock-order witness.

PRs 8-14 made the package threaded (ArrowServer workers, fleet
dispatch/probe threads, pulse callbacks, flock'd caches).  This module
is the *runtime* half of graft-sync: a vocabulary for declaring the
locking discipline those classes follow, and an opt-in witness that
checks real executions against it.

Vocabulary
----------
``@guarded_by("_lock", node="arrow_server", attrs=(...), callbacks=(...),
aliases=("_cond",))``
    Class decorator declaring the concurrency contract: ``attrs`` may
    only be mutated while holding ``self.<lock>`` (``__init__`` is
    exempt — pre-publication); ``callbacks`` (user-supplied hooks that
    may re-enter the package) must never be invoked while the lock is
    held; ``aliases`` name attributes that guard via the same lock
    (e.g. a ``threading.Condition`` wrapping it).  ``node`` is the
    class's vertex name in the package lock graph — shared between the
    static analyzer (RC1-RC5, ``arrow_matrix_tpu.analysis.sync``) and
    the runtime witness.  The decorator only attaches
    ``__sync_contract__``; it costs nothing at runtime.

``witnessed(node, lock)``
    Wrap a freshly created ``threading.Lock``/``RLock`` so the witness
    sees its acquisitions.  When the witness is off (the default) the
    lock is returned *unchanged* — zero per-acquisition overhead.

``flock_witness(node)``
    Context manager registering a held ``fcntl.flock`` region as the
    graph vertex ``flock:<node>`` (no-op context when the witness is
    off).  The package's two flock disciplines — the artifacts sidecar
    lock and the preemption registry — both route through it.

The witness
-----------
Enabled by ``AMT_LOCK_WITNESS=1`` in the environment at import time
(read exactly once — the R9 discipline), or in-process via
:func:`enable_witness`.  Each thread keeps its held-lock stack; every
*first* acquisition of a node while others are held adds an edge
``held -> acquired`` to a process-wide digraph seeded with
:data:`DECLARED_ORDER`.  An edge that would close a cycle — i.e. an
acquisition order inconsistent with the declared partial order or with
any previously observed order — raises :class:`LockOrderViolation` in
the acquiring thread *before* it blocks, so a potential deadlock
surfaces as a traceback instead of a hang.  Reentrant re-acquisition
(RLock) bumps a per-entry count and adds no edge.

serve_gate / fleet_gate / reshard_gate run with the witness on, so
every chaos scenario doubles as a lock-order execution test; tests
assert the off-by-default path leaves no registry behind.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "DECLARED_ORDER",
    "FLOCK_NODES",
    "LockOrderViolation",
    "LockRegistry",
    "WITNESS_ENV",
    "disable_witness",
    "enable_witness",
    "flock_witness",
    "guarded_by",
    "witness_registry",
    "witnessed",
]

WITNESS_ENV = "AMT_LOCK_WITNESS"

#: The package's declared lock partial order: ``(before, after)`` means
#: ``before`` may be held while acquiring ``after`` — never the
#: reverse.  The static analyzer folds these edges into the RC2 graph;
#: the runtime witness seeds its digraph with them, so an execution
#: that inverts any pair raises immediately.  Keep this the *minimal*
#: true order: an observed edge that is merely new (no cycle) is
#: recorded, not rejected.
DECLARED_ORDER: Tuple[Tuple[str, str], ...] = (
    # ArrowServer._event funnels every serve event while holding the
    # scheduler lock: flight.record / pulse.observe / (via pulse
    # dispatch on the same call stack) watchdog.on_window all nest
    # under it, as do admission-ledger updates and metric emissions.
    ("arrow_server", "flight_recorder"),
    ("arrow_server", "pulse_monitor"),
    ("arrow_server", "slo_watchdog"),
    ("arrow_server", "hbm_accountant"),
    ("arrow_server", "metrics_registry"),
    # Fleet dispatch threads update worker health and the blackbox
    # while holding the router lock.
    ("fleet_router", "health_monitor"),
    ("fleet_router", "flight_recorder"),
    ("fleet_router", "metrics_registry"),
    # graft-host: the shm segment pool is a LEAF below the router —
    # the data plane may be entered with routing state held, but pool
    # methods never call back into the router (the reverse order is a
    # witness violation by construction).
    ("fleet_router", "shm_pool"),
    # A router quorum coordinates member routers (submit fan-out,
    # failover resubmission) while holding its own lock; each member
    # then takes its fleet_router lock underneath.
    ("router_quorum", "fleet_router"),
    # PulseMonitor.snapshot() reads the watchdog's burning set while
    # holding the pulse lock (one consistent ring document); the
    # watchdog never takes the pulse lock (on_burn dispatches with
    # every watchdog lock released), so the edge is acyclic.
    ("pulse_monitor", "slo_watchdog"),
    # Pulse/watchdog otherwise dispatch callbacks and flight records
    # with their own locks *released* (the on_burn ladder re-enters
    # the scheduler), so they contribute no further edges.
)

#: Known flock vertices (``flock:<node>``) — the sidecar lock helper in
#: utils/artifacts.py and the preemption registry in utils/platform.py.
FLOCK_NODES: Tuple[str, ...] = ("flock:sidecar", "flock:preempt_registry")


class LockOrderViolation(RuntimeError):
    """An acquisition order inconsistent with the declared/observed
    lock partial order — a potential deadlock, raised in the acquiring
    thread before it blocks."""


def guarded_by(lock: str, *, node: Optional[str] = None,
               attrs: Sequence[str] = (),
               callbacks: Sequence[str] = (),
               aliases: Sequence[str] = ()):
    """Declare a class's concurrency contract (see module docstring).

    Purely declarative: attaches ``__sync_contract__`` for the static
    analyzer (which reads it from the AST, so the contract is enforced
    even on never-imported code paths) and for humans.
    """
    contract = {
        "lock": str(lock),
        "node": node,
        "attrs": tuple(attrs),
        "callbacks": tuple(callbacks),
        "aliases": tuple(aliases),
    }

    def deco(cls):
        cls.__sync_contract__ = dict(contract, node=node or cls.__name__)
        return cls

    return deco


class LockRegistry:
    """Per-process acquisition-order recorder (one per enabled witness).

    Thread-safe; the digraph and counters are guarded by an internal
    meta-lock that is never held while user code runs.
    """

    def __init__(self, declared: Sequence[Tuple[str, str]] = DECLARED_ORDER):
        self._meta = threading.Lock()
        self._succ: Dict[str, Set[str]] = {}
        self._declared_edges: Set[Tuple[str, str]] = set()
        self._observed_edges: Set[Tuple[str, str]] = set()
        self._tls = threading.local()
        self.acquisitions = 0
        self.reentries = 0
        self.threads_seen: Set[str] = set()
        self.violations: List[str] = []
        for a, b in declared:
            self.declare(a, b)

    # -- declared order -------------------------------------------------

    def declare(self, before: str, after: str) -> None:
        """Add a declared edge; a self-loop or a declaration that
        contradicts the existing graph is a programming error."""
        if before == after:
            raise ValueError(f"self-edge {before!r} -> {after!r}")
        with self._meta:
            path = self._path(after, before)
            if path is not None:
                raise ValueError(
                    f"declared order {before!r} -> {after!r} contradicts "
                    f"existing path {' -> '.join(path)}")
            self._succ.setdefault(before, set()).add(after)
            self._declared_edges.add((before, after))

    # -- per-thread held stack ------------------------------------------

    def _held(self) -> List[List]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def note_acquire(self, node: str) -> None:
        """Record intent to acquire ``node``; raises
        :class:`LockOrderViolation` (before the caller blocks) if the
        order contradicts the declared/observed partial order."""
        held = self._held()
        for entry in held:
            if entry[0] == node:     # reentrant (RLock): no new edge
                entry[1] += 1
                self.reentries += 1
                return
        with self._meta:
            self.acquisitions += 1
            self.threads_seen.add(threading.current_thread().name)
            for prior, _ in held:
                self._add_edge_locked(prior, node)
        held.append([node, 1])

    def note_release(self, node: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == node:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return
        # A release the witness never saw acquired (e.g. enabled
        # mid-flight): tolerated, not an error.

    def note_release_all(self, node: str) -> None:
        """Drop every recursion level of ``node`` (Condition.wait's
        ``_release_save`` path on an RLock)."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == node:
                del held[i]

    # -- the digraph ----------------------------------------------------

    def _add_edge_locked(self, a: str, b: str) -> None:
        if b in self._succ.get(a, ()):
            return
        path = self._path(b, a)
        if path is not None:
            kind = ("declared" if any(
                (path[i], path[i + 1]) in self._declared_edges
                for i in range(len(path) - 1)) else "observed")
            msg = (f"lock order violation: acquiring {b!r} while holding "
                   f"{a!r}, but the {kind} order already has "
                   f"{' -> '.join(path)} (thread "
                   f"{threading.current_thread().name!r})")
            self.violations.append(msg)
            raise LockOrderViolation(msg)
        self._succ.setdefault(a, set()).add(b)
        self._observed_edges.add((a, b))

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """Shortest path ``src -> ... -> dst`` in the digraph, or None.
        Caller holds ``self._meta`` (or is single-threaded init)."""
        if src == dst:
            return [src]
        frontier = [(src, [src])]
        seen = {src}
        while frontier:
            cur, path = frontier.pop(0)
            for nxt in sorted(self._succ.get(cur, ())):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, path + [nxt]))
        return None

    def snapshot(self) -> dict:
        """Stable summary for gate logs and the stress test."""
        with self._meta:
            return {
                "acquisitions": self.acquisitions,
                "reentries": self.reentries,
                "threads": sorted(self.threads_seen),
                "declared_edges": sorted(self._declared_edges),
                "observed_edges": sorted(self._observed_edges),
                "violations": list(self.violations),
            }


class _WitnessLock:
    """Proxy wrapping a real Lock/RLock; every acquisition path —
    including ``threading.Condition``'s ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` protocol — reports to the
    registry, so ``Condition(witnessed(...))`` stays fully witnessed."""

    __slots__ = ("_lock", "_node", "_registry")

    def __init__(self, node: str, lock, registry: LockRegistry):
        self._lock = lock
        self._node = node
        self._registry = registry

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._registry.note_acquire(self._node)
        ok = False
        try:
            ok = self._lock.acquire(blocking, timeout)
        finally:
            if not ok:
                self._registry.note_release(self._node)
        return ok

    def release(self) -> None:
        self._registry.note_release(self._node)
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol --------------------------------------------

    def _is_owned(self) -> bool:
        inner = getattr(self._lock, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._lock.acquire(False):   # plain-Lock probe (CPython's own
            self._lock.release()        # generic Condition fallback)
            return False
        return True

    def _release_save(self):
        state = None
        inner = getattr(self._lock, "_release_save", None)
        if inner is not None:
            state = inner()
        else:
            self._lock.release()
        self._registry.note_release_all(self._node)
        return state

    def _acquire_restore(self, state) -> None:
        self._registry.note_acquire(self._node)
        try:
            inner = getattr(self._lock, "_acquire_restore", None)
            if inner is not None:
                inner(state)
            else:
                self._lock.acquire()
        except BaseException:
            self._registry.note_release(self._node)
            raise

    def locked(self) -> bool:
        inner = getattr(self._lock, "locked", None)
        return bool(inner()) if inner is not None else self._is_owned()

    def __repr__(self) -> str:
        return f"<witnessed {self._node!r} {self._lock!r}>"


class _FlockWitness:
    """Context manager marking a held flock region in the lock graph."""

    __slots__ = ("_node", "_registry")

    def __init__(self, registry: LockRegistry, node: str):
        self._registry = registry
        self._node = node

    def __enter__(self):
        self._registry.note_acquire(self._node)
        return self

    def __exit__(self, *exc) -> None:
        self._registry.note_release(self._node)


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_CM = _NullContext()

_REGISTRY: Optional[LockRegistry] = None


def witness_registry() -> Optional[LockRegistry]:
    """The active registry, or None when the witness is off."""
    return _REGISTRY


def enable_witness(registry: Optional[LockRegistry] = None) -> LockRegistry:
    """Turn the witness on in-process (gates/tests; construct the
    objects under test *after* this so their locks are wrapped)."""
    global _REGISTRY
    _REGISTRY = registry if registry is not None else LockRegistry()
    return _REGISTRY


def disable_witness() -> None:
    global _REGISTRY
    _REGISTRY = None


def witnessed(node: str, lock):
    """Wrap ``lock`` for the witness; returns ``lock`` unchanged (zero
    overhead, not even a proxy allocation) when the witness is off."""
    reg = _REGISTRY
    if reg is None:
        return lock
    return _WitnessLock(node, lock, reg)


def flock_witness(node: str):
    """Witness context for a held ``fcntl.flock`` region (vertex
    ``flock:<node>``); a shared no-op context when the witness is off."""
    reg = _REGISTRY
    if reg is None:
        return _NULL_CM
    return _FlockWitness(reg, "flock:" + node)


def _env_on(name: str) -> bool:
    # Read exactly once at import (the R9 discipline: no AMT_* env
    # reads in hot scopes).
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


if _env_on(WITNESS_ENV):
    enable_witness()
