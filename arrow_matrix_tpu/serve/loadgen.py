"""Deterministic load generation + SLO reporting for graft-serve.

``synthetic_trace`` derives every request — tenant assignment and
feature payload — from ``numpy.random.default_rng(seed)``: no
wall-clock randomness anywhere, so two runs of the same trace through
a fault-free server complete with bit-identical per-request results
and identical admission censuses (the replay property every chaos
scenario in tools/serve_gate.py compares against).

``slo_summary`` folds the server's census and the tickets' latencies
into the serving SLO report (requests/s, p50/p90/p99 latency, shed and
rejection counts, HBM occupancy, per-tenant breakdown) —
tools/obs_gate.py requires these fields in every serve report, and
PERFORMANCE.md's serving table is this dict verbatim.

**One schema with graft-pulse.**  The report's field names are the
same vocabulary the streaming time series uses
(``obs/pulse.py:SLO_SERIES_FIELDS`` / ``LATENCY_FIELDS``):
``completed``/``failed``/``shed``/``rejected``, ``requests_per_s``,
``latency_ms{count,p50,p90,p99,mean,max}``, ``hbm``, ``per_tenant``.
A summary built with ``pulse=`` additionally embeds the monitor's
closed-window series under ``"pulse"``, so a replay artifact carries
both the end-state report and the time-resolved path to it, and the
two can be diffed field-for-field (the obs gate asserts the pooled
window histograms match the report's quantiles).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from arrow_matrix_tpu.ledger import store as ledger_store
from arrow_matrix_tpu.serve import request as rq
from arrow_matrix_tpu.serve.scheduler import ArrowServer, ExecConfig
from arrow_matrix_tpu.utils.artifacts import atomic_write_json


def synthetic_trace(n_rows: int, *, tenants: int = 4,
                    requests: int = 16, k: int = 4,
                    iterations: int = 3, seed: int = 0,
                    deadline_s: Optional[float] = None
                    ) -> List[rq.Request]:
    """A reproducible heavy-traffic trace: ``requests`` requests from
    ``tenants`` synthetic tenants, feature payloads and tenant
    assignment both drawn from one seeded generator."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(requests):
        tenant = f"tenant{int(rng.integers(tenants))}"
        x = rng.standard_normal((n_rows, k)).astype(np.float32)
        out.append(rq.Request(request_id=f"r{i:04d}", tenant=tenant,
                              x=x, iterations=iterations,
                              deadline_s=deadline_s))
    return out


def run_trace(server: ArrowServer,
              trace: List[rq.Request]) -> List[rq.Ticket]:
    """Submit the whole trace, then drain synchronously (or, when the
    server's worker thread is running, wait for every ticket) —
    returns the tickets in trace order."""
    tickets = [server.submit(r) for r in trace]
    if server._thread is not None and server._thread.is_alive():
        for t in tickets:
            t.wait()
    else:
        server.drain()
    return tickets


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def latency_summary_ms(tickets: List[rq.Ticket]) -> Dict[str, float]:
    lats = [t.latency_s * 1e3 for t in tickets
            if t.status == rq.COMPLETED and t.latency_s is not None]
    if not lats:
        return {"count": 0, "p50": None, "p90": None, "p99": None,
                "mean": None, "max": None}
    return {"count": len(lats),
            "p50": _pct(lats, 0.5), "p90": _pct(lats, 0.9),
            "p99": _pct(lats, 0.99),
            "mean": sum(lats) / len(lats), "max": max(lats)}


def slo_summary(server: ArrowServer, tickets: List[rq.Ticket],
                wall_s: float, pulse=None) -> dict:
    """The serving SLO report tools/obs_gate.py validates; pass the
    run's :class:`~arrow_matrix_tpu.obs.pulse.PulseMonitor` to embed
    its windowed time series (one schema, see the module docstring)."""
    base = server.summary()
    per_tenant = {}
    for name, rec in base["tenants"].items():
        mine = [t for t in tickets if t.request.tenant == name]
        rec = dict(rec)
        rec["latency_ms"] = latency_summary_ms(mine)
        per_tenant[name] = rec
    # graft-classes: the per-class mirror of per_tenant — latency
    # quantiles keyed by the class actually served (post-fallback), so
    # an SLO read can tell approx tail latency from exact.
    per_class = {}
    for klass, rec in (base.get("classes") or {}).items():
        mine = [t for t in tickets if t.served_class == klass]
        rec = dict(rec)
        rec["latency_ms"] = latency_summary_ms(mine)
        per_class[klass] = rec
    completed = base["completed"]
    pulse_section = None
    if pulse is not None:
        pulse_section = {
            "window_s": pulse.window_s,
            "windows": pulse.series(),
            "totals": pulse.totals_dict(),
            "burn_events": list(pulse.burn_events),
            "dropped_windows": pulse.dropped_windows,
            "ring_path": pulse.ring_path,
        }
    return {
        "server": base["server"],
        "requests": len(tickets),
        "completed": completed,
        "failed": base["failed"],
        "shed": base["shed"],
        "rejected": base["rejected"],
        "wall_s": wall_s,
        "requests_per_s": (completed / wall_s) if wall_s > 0 else None,
        "latency_ms": latency_summary_ms(tickets),
        "hbm": base["hbm"],
        "batches": base["batches"],
        "batched_requests": base["batched_requests"],
        "faults_seen": base["faults_seen"],
        "recoveries": base["recoveries"],
        "checkpoint_corruptions": base["checkpoint_corruptions"],
        "per_tenant": per_tenant,
        "per_class": per_class,
        "class_fallback": base.get("class_fallback", 0),
        "certificates": base.get("certificates", {}),
        "pulse": pulse_section,
    }


def write_serve_artifacts(run_dir: str, summary: dict,
                          registry=None) -> str:
    """Persist ``serve_summary.json`` (+ the registry's
    ``metrics.jsonl``) under ``run_dir``; returns the summary path."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, "serve_summary.json")
    atomic_write_json(path, summary, indent=2, sort_keys=True)
    if registry is not None:
        registry.write_jsonl(os.path.join(run_dir, "metrics.jsonl"))
    return path


def ba_executor_factory(n: int, width: int, seed: int,
                        fmt: str = "fold", mesh=None,
                        feature_dtype=None, plan=None,
                        plan_k=None):
    """Factory-of-executors over one Barabasi-Albert decomposition:
    the decomposition is computed once (the resident operator), each
    :class:`ExecConfig` rung builds its own executor over the same
    levels.  Returns ``(factory, n_rows)``.

    ``plan`` (graft-tune) threads into every rung's build: the rung's
    ExecConfig still wins on kernel/overlap/repl — the degradation
    ladder must be able to step a tuned knob down — while the plan
    contributes the structural knobs (tier split, chunk, carriage
    dtype) and the fused-kernel call opts."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.utils import barabasi_albert

    a = barabasi_albert(n, 3, seed=seed)
    levels = arrow_decomposition(a, width, max_levels=10,
                                 block_diagonal=True, seed=seed)

    resolved = None
    if plan is not None:
        from arrow_matrix_tpu.tune.plan import resolve_plan

        resolved = resolve_plan(plan, levels=levels, width=width,
                                plan_k=plan_k)

    def factory(cfg: ExecConfig):
        from arrow_matrix_tpu.parallel import MultiLevelArrow

        kwargs = dict(fmt=fmt, feature_dtype=feature_dtype)
        kernel_opts = None
        if resolved is not None:
            bk = resolved.build_kwargs()
            kwargs.update(fmt=bk["fmt"], chunk=bk["chunk"],
                          fold_growth=bk["fold_growth"],
                          fold_align=bk["fold_align"],
                          feature_dtype=bk["feature_dtype"])
            kernel_opts = resolved.kernel_opts()
        # graft-classes: the rung's class carriage wins over both the
        # factory default and the plan — an approx batch must build a
        # reduced-precision executor even under an exact-tuned plan.
        if getattr(cfg, "feature_dtype", None) is not None:
            kwargs["feature_dtype"] = cfg.feature_dtype
        return MultiLevelArrow(levels, width, mesh=mesh,
                               kernel=cfg.kernel,
                               overlap_slabs=cfg.overlap_slabs,
                               repl=cfg.repl,
                               kernel_opts=kernel_opts,
                               **kwargs)

    return factory, n


def smoke_serve(run_dir: str, *, n: int = 96, width: int = 16,
                k: int = 2, tenants: int = 2, requests: int = 4,
                iterations: int = 2, seed: int = 3,
                queue_capacity: int = 8,
                hbm_budget_bytes: Optional[int] = None,
                max_batch_k: int = 0, registry=None) -> dict:
    """One tiny end-to-end serve run on the host-CPU backend: build a
    BA operator, serve a deterministic trace with a PulseMonitor
    attached, write the SLO artifacts (``serve_summary.json``,
    ``pulse_ring.json``, ``pulse_metrics.prom``) into ``run_dir``,
    return the summary.  The amt_doctor SERVE probe and
    tools/obs_gate.py both ride this."""
    from arrow_matrix_tpu.obs import pulse as pulse_mod

    if registry is None:
        from arrow_matrix_tpu.obs.metrics import MetricsRegistry

        registry = MetricsRegistry(run_dir=run_dir)
    os.makedirs(run_dir, exist_ok=True)
    factory, n_rows = ba_executor_factory(n, width, seed, fmt="fold")
    server = ArrowServer(factory, ExecConfig(),
                         hbm_budget_bytes=hbm_budget_bytes,
                         queue_capacity=queue_capacity,
                         max_batch_k=max_batch_k,
                         registry=registry, name="smoke")
    monitor = pulse_mod.PulseMonitor(
        window_s=0.25, name="smoke",
        ring_path=os.path.join(run_dir, "pulse_ring.json"),
        ledger_dir=os.path.join(run_dir, "ledger"),
        watchdog=pulse_mod.SloWatchdog())
    server.attach_pulse(monitor)
    trace = synthetic_trace(n_rows, tenants=tenants,
                            requests=requests, k=k,
                            iterations=iterations, seed=seed)
    t0 = time.perf_counter()
    tickets = run_trace(server, trace)
    wall = time.perf_counter() - t0
    monitor.close()
    with open(os.path.join(run_dir, "pulse_metrics.prom"), "w",
              encoding="utf-8") as fh:
        fh.write(monitor.exposition_text())
    summary = slo_summary(server, tickets, wall, pulse=monitor)
    # graft-ledger: the SLO report also lands in a RUN-DIR-LOCAL
    # store (smoke runs ride gates and tests; they must never append
    # to the committed ledger).  tools/obs_gate.py requires the id.
    rec = ledger_store.record(
        "serve", "requests_per_s", summary.get("requests_per_s"),
        directory=os.path.join(run_dir, "ledger"),
        unit="req/s",
        knobs={"n": n, "width": width, "k": k, "seed": seed,
               "tenants": tenants, "requests": requests,
               "iterations": iterations,
               "max_batch_k": max_batch_k},
        payload={key: summary[key] for key in
                 ("requests", "completed", "failed", "shed",
                  "rejected", "wall_s", "latency_ms", "batches",
                  "batched_requests") if key in summary})
    summary["ledger_record_id"] = rec["record_id"] if rec else None
    write_serve_artifacts(run_dir, summary, registry=registry)
    return summary
