"""The graft-serve scheduler: an always-on, multi-tenant SpMM server.

The batch world's unit of supervision was one run; here the graft-heal
Supervisor (faults/supervisor.py) is promoted to a per-request process
manager inside a long-lived server:

  * **admission control** — every request is priced against the live
    HBM accountant (serve/admission.py) *before* enqueue; over-budget
    requests are rejected explicitly (429-style), and a full bounded
    queue sheds explicitly — no silent drops, ever.
  * **request-level supervision** — each scheduled batch runs under a
    fresh Supervisor stamped from the server's one
    :class:`~arrow_matrix_tpu.faults.policy.RetryPolicy` (watchdog,
    bounded retry, deterministic seeded backoff jitter) with an
    idempotent per-request checkpoint path: a killed server resumes
    every in-flight request from its last sha256-verified checkpoint,
    and already-completed requests replay for free from their final
    saves.
  * **graceful degradation** — repeated faults on a tenant's requests
    walk that tenant down the ladder pallas_sell -> xla, repl=c -> 1,
    overlap S -> 1 (:func:`degradation_ladder`) instead of failing the
    request; only a tenant already on the last rung can fail.
  * **dynamic batching** — compatible queued requests (same effective
    execution config, same iteration count) are concatenated along the
    feature axis and split back after the run.  SpMM is
    column-separable (the graft-repl/graft-stream slab law:
    ``routing.overlap_slices`` / ``repl_slab_width``), so each
    request's slice of the batched result is bit-identical to running
    it alone — asserted by tools/serve_gate.py.

Determinism contract: with a deterministic trace (serve/loadgen.py)
and the synchronous ``drain()`` mode, the admission census
(accepted/shed/rejected counts per tenant) and every completed
request's result bytes are replay-identical — the property the chaos
scenarios lean on.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from arrow_matrix_tpu.faults import RetryPolicy, Supervisor
from arrow_matrix_tpu.obs import flight
from arrow_matrix_tpu.serve import request as rq
from arrow_matrix_tpu.serve.admission import (
    HBMAccountant,
    ServeCapacityError,
    request_price_bytes,
)
from arrow_matrix_tpu.sync import guarded_by, witnessed
from arrow_matrix_tpu.utils.checkpoint import CheckpointIntegrityError


@dataclasses.dataclass(frozen=True)
class ExecConfig:
    """One rung of the execution ladder: the three knobs graceful
    degradation can trade away (fused kernel, 2.5D column replication,
    overlap sub-slabs) without changing the result's row order or the
    carriage layout — a degraded rerun resumes the same checkpoints.

    ``feature_dtype`` (graft-classes) is NOT a degradation knob: it is
    the carriage dtype of the traffic class a request is served under
    (None = f32 exact, "bf16" = certified approx), constant along a
    ticket's ladder walk.  It lives here because it is part of the
    executor cache key — an approx batch must never share an executor
    (or a batch) with an exact one."""

    kernel: str = "xla"
    repl: int = 1
    overlap_slabs: int = 1
    feature_dtype: Optional[str] = None

    def accepts_k(self, k: int) -> bool:
        """Whether a feature width is schedulable under this config
        (the graft-repl/graft-stream divisibility contracts: c | k and
        S | k/c)."""
        if k <= 0 or k % self.repl:
            return False
        return (k // self.repl) % self.overlap_slabs == 0


def degradation_ladder(base: ExecConfig) -> Tuple[ExecConfig, ...]:
    """Cumulative degradation rungs from ``base`` down to the plain
    XLA c=1 S=1 executor: fused kernel first (cheapest to give up),
    then replication, then overlap."""
    rungs = [base]
    cur = base
    if cur.kernel != "xla":
        cur = dataclasses.replace(cur, kernel="xla")
        rungs.append(cur)
    if cur.repl > 1:
        cur = dataclasses.replace(cur, repl=1)
        rungs.append(cur)
    if cur.overlap_slabs > 1:
        cur = dataclasses.replace(cur, overlap_slabs=1)
        rungs.append(cur)
    return tuple(rungs)


class _Tenant:
    __slots__ = ("rung", "fault_score", "degradations",
                 "allow_approx", "class_degraded")

    def __init__(self):
        self.rung = 0
        self.fault_score = 0
        self.degradations: List[dict] = []
        # graft-classes: exact -> approx is one more (opt-in) rung
        # below the terminal config rung; never taken silently.
        self.allow_approx = False
        self.class_degraded = False


@guarded_by(
    "_lock", node="arrow_server", aliases=("_cond",),
    callbacks=("_factory",),
    attrs=("_queue", "_counts", "_executors", "_tenants",
           "_latencies_s", "_tenant_latencies_s",
           "_class_latencies_s", "batches", "batched_requests",
           "faults_seen", "recoveries", "checkpoint_corruptions",
           "checkpoints_resharded", "_grown", "grows", "_stop"))
class ArrowServer:
    """Long-lived multi-tenant server over one resident arrow operator.

    ``executor_factory(config: ExecConfig)`` builds an executor
    (``set_features`` / ``step`` / ``gather_result`` plus the memview
    HBM model) for one ladder rung; executors are built lazily and
    cached — the base rung is built eagerly so the resident operator
    is charged before the first request.

    Two execution modes share all logic: ``start()`` spawns a worker
    thread (the always-on deployment; ``shutdown(wait=True)`` drains
    the queue first), while ``drain()`` processes synchronously in the
    caller's thread — the deterministic mode every test and gate uses.
    """

    def __init__(self, executor_factory: Callable[[ExecConfig], Any],
                 base_config: ExecConfig = ExecConfig(), *,
                 hbm_budget_bytes: Optional[int] = None,
                 queue_capacity: int = 64,
                 policy: Optional[RetryPolicy] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 2,
                 max_batch_k: int = 0,
                 degrade_after: int = 2,
                 itemsize: int = 4,
                 registry=None,
                 tracer=None,
                 name: str = "serve",
                 verbose: bool = False,
                 tune_plan=None,
                 certificates=None,
                 structure_hash: Optional[str] = None,
                 cert_ledger_dir: Optional[str] = None,
                 approx_opt_in=(),
                 grow_config: Optional[ExecConfig] = None,
                 grow_factory: Optional[
                     Callable[[ExecConfig], Any]] = None,
                 reshard_budget_bytes: int = 1 << 20):
        # graft-tune pickup: a cached TunePlan (or its dict) becomes
        # the BASE ladder rung — admitted requests run the tuned
        # kernel/repl/overlap at zero search cost, and the degradation
        # ladder below still steps every tuned knob back down under
        # pressure.  The executor_factory sees the tuned ExecConfig
        # like any other rung; factories that also consume the plan's
        # structural knobs thread ``plan=`` themselves
        # (serve/loadgen.ba_executor_factory).
        self.tune_plan = None
        if tune_plan is not None:
            from arrow_matrix_tpu.tune.plan import resolve_plan

            resolved = resolve_plan(tune_plan)
            if resolved is not None:
                self.tune_plan = resolved
                base_config = resolved.exec_config()
        if base_config.feature_dtype is not None:
            # The BASE rung serves the exact class; a carriage dtype
            # on it (e.g. an approx-class tune plan) is a class
            # property, applied per ticket by _effective_config, never
            # a default every tenant silently inherits.
            base_config = dataclasses.replace(base_config,
                                              feature_dtype=None)
        if queue_capacity < 1:
            raise ValueError(f"queue_capacity must be >= 1, got "
                             f"{queue_capacity}")
        self.name = name
        self.verbose = verbose
        self.registry = registry
        self.tracer = tracer
        self.pulse = None   # a PulseMonitor, via attach_pulse()
        self.policy = policy or RetryPolicy()
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.queue_capacity = int(queue_capacity)
        self.max_batch_k = int(max_batch_k)
        self.degrade_after = max(int(degrade_after), 1)
        self.itemsize = int(itemsize)
        self._factory = executor_factory
        self.base_config = base_config
        self.ladder = degradation_ladder(base_config)
        # graft-classes: the approx class serves bf16 carriage only
        # (the int8 (q, scale) carry is an executor/bench capability —
        # its tuple pytree has no serving checkpoint story), and only
        # for structures holding a covering certificate.  Certificates
        # come from (priority order) the explicit argument, an
        # approx-class tune plan, or a ledger lookup by structure hash.
        from arrow_matrix_tpu.classes import (
            Certificate,
            find_certificate,
        )

        self.approx_dtype = "bf16"
        self._certificates: Dict[str, Certificate] = {}
        certs = certificates or ()
        if isinstance(certs, dict):   # {dtype: cert} or an iterable
            certs = certs.values()
        for c in certs:
            cert = (c if isinstance(c, Certificate)
                    else Certificate.from_dict(dict(c)))
            self._certificates[cert.dtype] = cert
        if self.tune_plan is not None and self.tune_plan.certificate:
            cert = Certificate.from_dict(self.tune_plan.certificate)
            self._certificates.setdefault(cert.dtype, cert)
        shash = structure_hash or (self.tune_plan.structure_hash
                                   if self.tune_plan else None)
        if shash and cert_ledger_dir is not None \
                and self.approx_dtype not in self._certificates:
            cert = find_certificate(shash, self.approx_dtype,
                                    ledger_dir=cert_ledger_dir)
            if cert is not None:
                self._certificates[cert.dtype] = cert
        self._executors: Dict[ExecConfig, Any] = {}
        self._tenants: Dict[str, _Tenant] = {}
        for t in approx_opt_in or ():
            self._tenant(t).allow_approx = True
        self._queue: collections.deque = collections.deque()
        # graft-sync: the worker thread, N submitter threads, and the
        # pulse/flight observers all meet on this one RLock; _cond is
        # an alias view of it (declared on the contract) so a
        # ``with self._cond:`` region counts as holding ``_lock``.
        self._lock = witnessed("arrow_server", threading.RLock())
        self._cond = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._counts = collections.Counter()
        self._latencies_s: List[float] = []
        self._tenant_latencies_s: Dict[str, List[float]] = {}
        self._class_latencies_s: Dict[str, List[float]] = {}
        self.batches = 0
        self.batched_requests = 0
        self.faults_seen = 0
        self.recoveries = 0
        self.checkpoint_corruptions = 0
        # graft-reshard grow direction: a declared grow target (config
        # and/or a factory building the grown layout — e.g. more mesh
        # blocks) note_slo_pressure can cut over to WITHOUT a cold
        # restart, migrating per-request checkpoints through a staged
        # redistribution plan whose per-stage scratch is bounded by
        # ``reshard_budget_bytes``.
        self.grow_config = grow_config
        self.grow_factory = grow_factory
        self.reshard_budget_bytes = int(reshard_budget_bytes)
        self._grown: Optional[Tuple[Any, ExecConfig]] = None
        self.grows = 0
        self.checkpoints_resharded = 0

        base = self._build_executor(base_config)
        if hbm_budget_bytes is None:
            from arrow_matrix_tpu.obs.comm import hbm_budget_bytes as _b

            hbm_budget_bytes = _b(None)
        self.accountant = HBMAccountant(hbm_budget_bytes,
                                        registry=registry, name=name)
        from arrow_matrix_tpu.obs.memview import predicted_bytes_for

        resident = predicted_bytes_for(base, 0, itemsize=self.itemsize,
                                       repl=base_config.repl) or 0
        self.accountant.charge_resident(resident)
        self._event("started", resident_bytes=resident,
                    budget_bytes=self.accountant.budget_bytes,
                    ladder=[dataclasses.asdict(c) for c in self.ladder])
        if self._certificates:
            self._event("certificates_loaded",
                        structure_hash=shash,
                        certificates={
                            dt: {"iterations": c.iterations,
                                 "tolerance": c.tolerance,
                                 "bound": c.bound_at(c.iterations)}
                            for dt, c in
                            sorted(self._certificates.items())})
        if self.tune_plan is not None:
            self._event("tune_plan_applied",
                        structure_hash=self.tune_plan.structure_hash,
                        candidate=self.tune_plan.candidate,
                        k=self.tune_plan.k,
                        measured_ms=self.tune_plan.measured_ms,
                        margin=self.tune_plan.margin,
                        base_config=dataclasses.asdict(base_config))

    # -- plumbing ----------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[graft-serve {self.name}] {msg}", flush=True)

    def _event(self, event: str, **data) -> None:
        """The one serve-event funnel: the flight recorder gets every
        event, and — when a PulseMonitor is attached — so does the
        streaming telemetry layer."""
        flight.record("serve", event, server=self.name, **data)
        if self.pulse is not None:
            try:
                self.pulse.observe(event, **data)
            except Exception:  # graft-lint: disable=R8 — telemetry
                # must never take down the server it observes.
                pass

    def _span(self, name: str, **attrs):
        """A tracer span when a tracer is attached, else a no-op (the
        request context stamps request_id/tenant onto the span)."""
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, **attrs)

    def _count(self, what: str, tenant: Optional[str] = None,
               klass: Optional[str] = None, **labels) -> None:
        # Counter.__iadd__ is read-modify-write: two unlocked bumps
        # from the worker and a submitter can lose one (RC1).  The
        # registry dispatch stays outside the critical section.
        with self._lock:
            self._counts[what] += 1
            if tenant is not None:
                self._counts[f"{what}:{tenant}"] += 1
            if klass is not None:
                self._counts[f"{what}:class:{klass}"] += 1
        if self.registry is not None:
            lb = dict(labels)
            if tenant is not None:
                lb["tenant"] = tenant
            if klass is not None:
                lb["traffic_class"] = klass
            self.registry.counter(f"serve_{what}", server=self.name,
                                  **lb).inc()

    def _tenant(self, tenant: str) -> _Tenant:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = _Tenant()
        return t

    def _build_executor(self, cfg: ExecConfig):
        with self._lock:
            ex = self._executors.get(cfg)
        if ex is None:
            # The factory is a user callback — it compiles kernels and
            # can take seconds, so it runs with NO lock held (RC3).
            # Two racing builders both build; the first to publish
            # wins and the loser's executor is dropped.
            built = self._factory(cfg)
            with self._lock:
                ex = self._executors.setdefault(cfg, built)
        return ex

    def _effective_config(self, ticket: rq.Ticket) -> ExecConfig:
        """The ladder rung this ticket runs on: its tenant's current
        rung, or the terminal rung when the request's feature width
        fails the rung's divisibility contract (repl/overlap need
        c | k and S | k/c; the terminal rung accepts every k).
        Approx-served tickets get the class carriage dtype stamped on
        the rung — a distinct executor cache key, so exact and approx
        never share a compiled step or a batch."""
        tenant = self._tenant(ticket.request.tenant)
        cfg = self.ladder[tenant.rung]
        if not cfg.accepts_k(ticket.request.k):
            cfg = self.ladder[-1]
        if ticket.served_class == "exact" and tenant.class_degraded:
            # Opt-in class degradation (never silent): the tenant
            # consented via approx_opt_in and its ladder is exhausted.
            cert = self._certificates.get(self.approx_dtype)
            if cert is not None and cert.covers(
                    ticket.request.iterations):
                ticket.served_class = "approx"
                ticket.class_fallback = "degraded_opt_in"
                ticket.certified_bound = cert.bound_at(
                    ticket.request.iterations)
                self._event("class_degraded_applied",
                            request=ticket.request.request_id,
                            tenant=ticket.request.tenant,
                            traffic_class="approx",
                            certified_bound=ticket.certified_bound)
        if ticket.served_class == "approx":
            cfg = dataclasses.replace(cfg,
                                      feature_dtype=self.approx_dtype)
        return cfg

    def _resolve_class(self, request: rq.Request):
        """Admission-time class decision: ``(served_class,
        fallback_reason, certificate)``.  An approx request without a
        covering certificate is served EXACT — the loud fallback the
        class contract promises (never silent approx)."""
        if request.traffic_class == "exact":
            return "exact", None, None
        cert = self._certificates.get(self.approx_dtype)
        if cert is None:
            return "exact", "no_certificate", None
        if not cert.covers(request.iterations):
            reason = ("curve_shorter_than_request"
                      if cert.bound_at(request.iterations) is None
                      else "certified_bound_exceeds_tolerance")
            return "exact", reason, None
        return "approx", None, cert

    # -- admission ---------------------------------------------------------

    def submit(self, request: rq.Request) -> rq.Ticket:
        """Admission-control one request: price, reserve, enqueue —
        or reject (HBM) / shed (queue overflow) explicitly.  Returns
        the ticket immediately; it resolves when processed.

        The whole admission path runs inside the request's correlation
        context, so the shed/reject/admit events and the ``admission``
        span all carry its ``request_id``/``tenant``."""
        with flight.request_context(request.request_id, request.tenant), \
                self._span("admission", k=request.k,
                           iterations=request.iterations):
            return self._submit(request)

    def _submit(self, request: rq.Request) -> rq.Ticket:
        from arrow_matrix_tpu.classes import (
            TRAFFIC_CLASSES,
            class_itemsize,
        )

        ticket = rq.Ticket(request)
        ticket.submitted_s = time.monotonic()
        # Keep the submit-time correlation context (fleet trace_id and
        # friends) on the ticket: _process_batch runs on the worker
        # thread, where the submitting thread's contextvars are out of
        # reach — the ticket is the handoff.
        ctx = flight.current_request()
        ticket.trace = dict(ctx) if ctx else None
        self._count("submitted", request.tenant)
        if request.traffic_class not in TRAFFIC_CLASSES:
            ticket._finish(
                rq.REJECTED, reason="unknown_class",
                error=f"unknown traffic class "
                      f"{request.traffic_class!r} (expected one of "
                      f"{TRAFFIC_CLASSES})")
            self._count("rejected", request.tenant,
                        reason="unknown_class")
            self._event("rejected", request=request.request_id,
                        tenant=request.tenant, reason="unknown_class",
                        traffic_class=request.traffic_class)
            return ticket
        served, fallback, cert = self._resolve_class(request)
        ticket.served_class = served
        ticket.class_fallback = fallback
        if cert is not None:
            ticket.certified_bound = cert.bound_at(request.iterations)
        if fallback is not None:
            self._count("class_fallback", request.tenant,
                        reason=fallback)
            self._event("class_fallback", request=request.request_id,
                        tenant=request.tenant,
                        requested_class=request.traffic_class,
                        traffic_class=served, reason=fallback)
            self._log(f"class fallback {request.request_id}: "
                      f"approx -> exact ({fallback})")
        # Approx carriage is priced at its TRUE (smaller) itemsize —
        # the admitted-requests-per-GB lever the class exists for.
        itemsize = (class_itemsize(self.approx_dtype)
                    if served == "approx" else self.itemsize)
        price = request_price_bytes(
            self._build_executor(self.base_config), request.k,
            itemsize=itemsize, repl=self.base_config.repl)
        ticket.predicted_bytes = price
        with self._cond:
            if self._stop:
                ticket._finish(rq.SHED, reason="server_stopped")
                self._count("shed", request.tenant,
                            reason="server_stopped")
                self._event("shed", request=request.request_id,
                            tenant=request.tenant,
                            reason="server_stopped")
                return ticket
            if not self.accountant.reserve(price):
                ticket._finish(
                    rq.REJECTED, reason="hbm_budget",
                    error=f"predicted {price} B exceeds remaining HBM "
                          f"headroom "
                          f"{self.accountant.headroom_bytes()} B")
                self._count("rejected", request.tenant,
                            klass=ticket.served_class,
                            reason="hbm_budget")
                self._event("rejected", request=request.request_id,
                            tenant=request.tenant, reason="hbm_budget",
                            traffic_class=ticket.served_class,
                            predicted_bytes=price,
                            headroom_bytes=self.accountant
                            .headroom_bytes())
                self._log(f"rejected {request.request_id} "
                          f"(hbm_budget: {price} B over headroom)")
                return ticket
            if len(self._queue) >= self.queue_capacity:
                self.accountant.release(price)
                ticket._finish(
                    rq.SHED, reason="queue_full",
                    error=f"queue at capacity {self.queue_capacity}")
                self._count("shed", request.tenant,
                            reason="queue_full")
                self._event("shed", request=request.request_id,
                            tenant=request.tenant, reason="queue_full",
                            queue_depth=len(self._queue))
                self._log(f"shed {request.request_id} (queue_full)")
                return ticket
            ticket.status = rq.ADMITTED
            self._queue.append(ticket)
            self._count("admitted", request.tenant,
                        klass=ticket.served_class)
            self._event("admitted", request=request.request_id,
                        tenant=request.tenant, k=request.k,
                        predicted_bytes=price,
                        traffic_class=ticket.served_class,
                        queue_depth=len(self._queue))
            self._cond.notify_all()
        return ticket

    # -- scheduling --------------------------------------------------------

    def _shed_expired(self, ticket: rq.Ticket) -> bool:
        dl = ticket.request.deadline_s
        if dl is None or ticket.submitted_s is None:
            return False
        if time.monotonic() - ticket.submitted_s <= dl:
            return False
        self.accountant.release(ticket.predicted_bytes)
        ticket._finish(rq.SHED, reason="deadline",
                       error=f"queued past the {dl:.3f}s deadline")
        self._count("shed", ticket.request.tenant, reason="deadline")
        self._event("shed", request=ticket.request.request_id,
                    tenant=ticket.request.tenant, reason="deadline")
        self._log(f"shed {ticket.request.request_id} (deadline)")
        return True

    def _take_batch(self) -> Tuple[List[rq.Ticket],
                                   Optional[ExecConfig]]:
        """Pop the head request plus every compatible queued request
        (same effective config + iteration count, combined width under
        ``max_batch_k`` and schedulable) — FIFO, deterministic."""
        with self._lock:
            head: Optional[rq.Ticket] = None
            while self._queue:
                t = self._queue.popleft()
                if self._shed_expired(t):
                    continue
                head = t
                break
            if head is None:
                return [], None
            cfg = self._effective_config(head)
            batch = [head]
            k_total = head.request.k
            if self.max_batch_k > k_total:
                keep: List[rq.Ticket] = []
                for t in list(self._queue):
                    k2 = t.request.k
                    # Class separation: config equality already
                    # differs on feature_dtype, but the served-class
                    # check is the explicit contract — a batch never
                    # mixes accuracy classes.
                    if (t.request.iterations == head.request.iterations
                            and self._effective_config(t) == cfg
                            and t.served_class == head.served_class
                            and k_total + k2 <= self.max_batch_k
                            and cfg.accepts_k(k_total + k2)
                            and not self._shed_expired(t)):
                        batch.append(t)
                        k_total += k2
                    elif not t.done:
                        keep.append(t)
                self._queue = collections.deque(keep)
            return batch, cfg

    def _pump_once(self) -> bool:
        batch, cfg = self._take_batch()
        if not batch:
            return False
        self._process_batch(batch, cfg)
        return True

    def drain(self) -> None:
        """Synchronously process the queue to empty in the caller's
        thread (the deterministic test/gate mode)."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "drain() is the synchronous mode; a worker thread is "
                "already running — use shutdown(wait=True)")
        while self._pump_once():
            pass

    def start(self) -> None:
        """Spawn the always-on worker thread."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._serve_loop, daemon=True,
                name=f"graft-serve-{self.name}")
            self._thread.start()

    def _serve_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.05)
                if self._stop and not self._queue:
                    return
            try:
                self._pump_once()
            except Exception as e:  # noqa: BLE001 — the serving loop
                # must survive anything a batch throws; the batch's
                # tickets were already failed explicitly.
                self._log(f"worker survived unexpected error: "
                          f"{type(e).__name__}: {e}")

    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Graceful stop: the worker finishes the queued requests,
        then exits; later submissions are shed explicitly."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if wait and t is not None:
            t.join(timeout)
        self._event("stopped")

    # -- execution ---------------------------------------------------------

    def _executor_for(self, cfg: ExecConfig):
        """Build (or fetch) the executor for a rung, walking further
        down the ladder when a rung's build itself fails; returns
        ``(executor, actual_cfg)`` or ``(None, cfg)``."""
        with self._lock:
            grown = self._grown
        if grown is not None and cfg in (self.base_config, grown[1]):
            # Post-grow, base-rung traffic runs the grown layout (its
            # checkpoints were migrated by grow()); degraded rungs and
            # class-stamped configs keep their own executors.
            return grown
        if cfg in self.ladder:
            rungs = list(self.ladder[self.ladder.index(cfg):])
        else:
            # A class-stamped rung (feature_dtype set by
            # _effective_config) is not a ladder member: try it
            # first, and only degrade into the exact ladder — losing
            # the carriage dtype, loudly, via rung_build_failed —
            # when the class rung itself cannot build.
            rungs = [cfg] + list(self.ladder)
        for rung in rungs:
            try:
                return self._build_executor(rung), rung
            except Exception as e:  # noqa: BLE001 — a rung that cannot
                # build is one more thing to degrade past, loudly.
                self._log(f"rung {rung} failed to build "
                          f"({type(e).__name__}: {e}); degrading")
                self._event("rung_build_failed",
                            config=dataclasses.asdict(rung),
                            error=f"{type(e).__name__}: {e}")
        return None, cfg

    def _ck_path(self, key: str) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        import os

        os.makedirs(self.checkpoint_dir, exist_ok=True)
        return os.path.join(self.checkpoint_dir, f"ck_{key}")

    def _discard_checkpoint(self, path: str, key: str,
                            err: Exception) -> None:
        import os

        with self._lock:
            self.checkpoint_corruptions += 1
        self._count("checkpoint_corrupt")
        self._event("checkpoint_corrupt_discarded", request=key,
                    path=path, error=f"{type(err).__name__}: {err}")
        print(f"[graft-serve {self.name}] WARNING: discarding "
              f"unusable checkpoint for request {key}: {err}",
              flush=True)
        for p in (path + ".npz", path + ".npz.sha256",
                  path + ".meta.json"):
            try:
                os.remove(p)
            except OSError:
                pass

    def _process_batch(self, batch: List[rq.Ticket],
                       cfg: ExecConfig) -> None:
        """Run one batch inside its correlation context: the batched
        key ``"r0001+r0002"`` names every member request, so each
        member's spans/events are recoverable from one Perfetto track
        (membership in the joined key)."""
        key = "+".join(t.request.request_id for t in batch)
        tenants = sorted({t.request.tenant for t in batch})
        tenant = "+".join(tenants)
        # Rejoin the members' fleet trace ids on this worker thread
        # (class-pure batches of one make the join a single id).
        trace_ids = sorted({(t.trace or {}).get("trace_id")
                            for t in batch
                            if (t.trace or {}).get("trace_id")})
        with flight.request_context(
                key, tenant,
                trace_id="+".join(trace_ids) if trace_ids else None), \
                self._span("batch", requests=len(batch),
                           k_total=sum(t.request.k for t in batch),
                           iterations=batch[0].request.iterations,
                           traffic_class=batch[0].served_class,
                           config=dataclasses.asdict(cfg)):
            self._run_batch(batch, cfg, key)

    def _run_batch(self, batch: List[rq.Ticket], cfg: ExecConfig,
                   key: str) -> None:
        iters = batch[0].request.iterations
        k_total = sum(t.request.k for t in batch)
        for t in batch:
            t.status = rq.RUNNING
            t.attempts += 1
        with self._lock:
            self.batches += 1
            self.batched_requests += len(batch)
        if self.registry is not None:
            self.registry.counter("serve_batches",
                                  server=self.name).inc()
            self.registry.record("serve_batch_k", float(k_total),
                                 server=self.name)
        executor, cfg = self._executor_for(cfg)
        if executor is None:
            self._fail_batch(batch, "no executor rung could be built")
            return
        x_cat = np.concatenate([t.request.x for t in batch], axis=1)
        ck = self._ck_path(key)
        layout = f"serve/{key}/k{k_total}/it{iters}"
        sup = Supervisor(f"{self.name}:{key}", carry=True,
                         policy=self.policy, checkpoint_path=ck,
                         checkpoint_every=(self.checkpoint_every
                                           if ck else 0),
                         layout=layout, registry=self.registry,
                         tracer=self.tracer, verbose=False)
        with self._span("set_features", k_total=k_total):
            x0 = executor.set_features(x_cat)
        start = 0
        if ck:
            try:
                st = sup.resume(x0)
            except CheckpointIntegrityError as e:
                self._discard_checkpoint(ck, key, e)
                st = None
            except Exception as e:  # noqa: BLE001 — a stale/mismatched
                # checkpoint (different batch composition, layout tag,
                # truncated file) must not wedge the server: discard
                # loudly and recompute.
                self._discard_checkpoint(ck, key, e)
                st = None
            if st is not None:
                x0, start = st
                for t in batch:
                    t.resumed_step = start
                self._event("resumed_request", request=key, step=start)
                # The chaos kill scenario greps this line in the CLI's
                # stdout; print it regardless of verbosity.
                print(f"[graft-serve {self.name}] resumed request "
                      f"{key} at iteration {start}", flush=True)
        y, ok, err = None, False, None
        body = lambda x, it: executor.step(x)   # noqa: E731
        try:
            y, ok = sup.run(body, x0, start, iters)
        except CheckpointIntegrityError as e:
            # Corruption surfaced mid-run (rollback hit a corrupted
            # save): discard and recompute once from scratch.
            self._discard_checkpoint(ck or "", key, e)
            try:
                y, ok = sup.run(body, executor.set_features(x_cat), 0,
                                iters)
            except Exception as e2:  # noqa: BLE001
                err = e2
        except Exception as e:  # noqa: BLE001 — WatchdogStalled or an
            # unexpected executor error: the request fails/degrades,
            # the server survives.
            err = e
        with self._lock:
            self.faults_seen += sup.faults_seen
            self.recoveries += sup.recoveries
        for t in batch:
            t.faults_seen += sup.faults_seen
            t.recoveries += sup.recoveries
        if sup.faults_seen or sup.recoveries:
            # Surface supervised-fault pressure into the event funnel:
            # this is what the pulse fault_rate burn rule windows over.
            self._event("supervised", request=key,
                        faults=sup.faults_seen,
                        recoveries=sup.recoveries)
        if ok:
            with self._span("finalize", requests=len(batch)):
                self._finalize_completed(batch, y, executor, cfg)
            self._note_faults(batch, sup.faults_seen)
        else:
            self._handle_failure(batch, err)

    def _note_faults(self, batch: List[rq.Ticket],
                     faults: int) -> None:
        """Accumulate recovered-fault pressure per tenant; repeated
        faults degrade the tenant's rung even when every request still
        completes (the ladder is preventive, not just reactive)."""
        if not faults:
            return
        with self._lock:
            for tenant in {t.request.tenant for t in batch}:
                self._degrade_tenant(tenant, faults,
                                     reason="repeated_faults")

    def _degrade_tenant(self, tenant: str, faults: int,
                        reason: str) -> bool:
        t = self._tenant(tenant)
        t.fault_score += faults
        if t.fault_score < self.degrade_after:
            return False
        if t.rung + 1 >= len(self.ladder):
            # graft-classes: one more rung exists below the terminal
            # config — exact -> approx — but ONLY for tenants that
            # opted in, and only with a certificate to serve under.
            if (t.allow_approx and not t.class_degraded
                    and self.approx_dtype in self._certificates):
                t.class_degraded = True
                t.fault_score = 0
                rec = {"tenant": tenant,
                       "from": {"traffic_class": "exact"},
                       "to": {"traffic_class": "approx",
                              "feature_dtype": self.approx_dtype},
                       "reason": f"{reason}:class_opt_in"}
                t.degradations.append(rec)
                self._count("degraded", tenant, reason=reason)
                self._event("degraded", **rec)
                self._log(f"degraded tenant {tenant} to the approx "
                          f"class ({reason}; explicit opt-in)")
                return True
            return False
        frm, t.rung = t.rung, t.rung + 1
        t.fault_score = 0
        rec = {"tenant": tenant,
               "from": dataclasses.asdict(self.ladder[frm]),
               "to": dataclasses.asdict(self.ladder[t.rung]),
               "reason": reason}
        t.degradations.append(rec)
        self._count("degraded", tenant, reason=reason)
        self._event("degraded", **rec)
        self._log(f"degraded tenant {tenant} to rung {t.rung} "
                  f"{self.ladder[t.rung]} ({reason})")
        return True

    def _handle_failure(self, batch: List[rq.Ticket],
                        err: Optional[Exception]) -> None:
        """Retries exhausted (or the attempt escalated): degrade the
        batch's tenants one rung and requeue at the FRONT; only
        tenants already on the terminal rung fail their requests —
        explicitly."""
        detail = (f"{type(err).__name__}: {err}" if err is not None
                  else "supervised run exhausted its retries")
        degraded = False
        with self._lock:
            for tenant in {t.request.tenant for t in batch}:
                degraded |= self._degrade_tenant(
                    tenant, max(self.degrade_after, 1),
                    reason="request_failure")
        if degraded:
            with self._cond:
                for t in reversed(batch):
                    t.status = rq.ADMITTED
                    self._queue.appendleft(t)
                self._cond.notify_all()
            self._event("requeued_degraded",
                        requests=[t.request.request_id for t in batch],
                        error=detail)
            self._log(f"requeued {len(batch)} request(s) on a "
                      f"degraded rung after: {detail}")
            return
        self._fail_batch(batch, detail)

    def _fail_batch(self, batch: List[rq.Ticket], detail: str) -> None:
        for t in batch:
            self.accountant.release(t.predicted_bytes)
            t._finish(rq.FAILED, reason="exhausted", error=detail)
            self._count("failed", t.request.tenant)
            self._event("failed", request=t.request.request_id,
                        tenant=t.request.tenant, error=detail)
            self._log(f"FAILED {t.request.request_id}: {detail}")

    def _finalize_completed(self, batch: List[rq.Ticket], y,
                            executor, cfg: ExecConfig) -> None:
        gathered = executor.gather_result(y)
        off = 0
        for t in batch:
            k = t.request.k
            t.result = np.ascontiguousarray(gathered[:, off:off + k])
            off += k
            t.exec_config = cfg
            self.accountant.release(t.predicted_bytes)
            t._finish(rq.COMPLETED)
            self._count("completed", t.request.tenant,
                        klass=t.served_class)
            lat_ms = (t.latency_s or 0.0) * 1e3
            with self._lock:
                self._latencies_s.append(t.latency_s or 0.0)
                self._tenant_latencies_s.setdefault(
                    t.request.tenant, []).append(t.latency_s or 0.0)
                self._class_latencies_s.setdefault(
                    t.served_class, []).append(t.latency_s or 0.0)
            if self.registry is not None:
                self.registry.record("serve_latency_ms", lat_ms,
                                     server=self.name)
                self.registry.record("serve_latency_ms", lat_ms,
                                     server=self.name,
                                     tenant=t.request.tenant)
                self.registry.record("serve_latency_ms", lat_ms,
                                     server=self.name,
                                     traffic_class=t.served_class)
            self._event("completed", request=t.request.request_id,
                        tenant=t.request.tenant,
                        traffic_class=t.served_class,
                        latency_ms=round(lat_ms, 3),
                        faults_seen=t.faults_seen)

    # -- live telemetry (graft-pulse) --------------------------------------

    def attach_pulse(self, monitor) -> Any:
        """Wire a :class:`~arrow_matrix_tpu.obs.pulse.PulseMonitor`
        into this server: every serve event (the :meth:`_event`
        funnel) flows into its sliding windows, HBM occupancy is
        sampled from the live accountant, and — when the monitor
        carries a watchdog with no callback yet — SLO-burn trips feed
        the per-tenant degradation ladder via
        :meth:`note_slo_pressure`.  Measured SLO pressure then drives
        the same rungs faults do.  Returns the monitor."""
        self.pulse = monitor
        acct = self.accountant
        monitor.hbm_sampler = lambda: (acct.in_use_bytes,
                                       acct.occupancy())
        wd = getattr(monitor, "watchdog", None)
        if wd is not None and wd.on_burn is None:
            wd.on_burn = self._on_slo_burn
        return monitor

    def _on_slo_burn(self, rule, window: dict, event: dict) -> None:
        """SloWatchdog trip callback: the tenants active in the
        burning window (all known tenants when it names none) take
        one forced ladder rung."""
        tenants = sorted((window.get("per_tenant") or {}).keys())
        self.note_slo_pressure(f"slo_burn:{rule.name}",
                               tenants=tenants or None)

    def note_slo_pressure(self, reason: str,
                          tenants: Optional[List[str]] = None,
                          score: Optional[int] = None,
                          direction: str = "drop") -> List[str]:
        """Feed measured SLO pressure into the degradation ladder:
        each named tenant (default: every known tenant) takes
        ``score`` fault-score points (default: enough to force one
        rung immediately).  Returns the tenants that degraded.

        ``direction="grow"`` (graft-reshard) spends pressure the other
        way: instead of shedding features, cut the base rung over to
        the declared grow target (``grow_config`` / ``grow_factory``)
        via :meth:`grow` — returns ``["*"]`` when the cutover
        happened."""
        if direction == "grow":
            return ["*"] if self.grow(reason=reason) else []
        if direction != "drop":
            raise ValueError(f"unknown pressure direction "
                             f"{direction!r} (expected 'drop'/'grow')")
        degraded = []
        with self._lock:
            names = (list(tenants) if tenants is not None
                     else sorted(self._tenants))
            pts = self.degrade_after if score is None else int(score)
            for tenant in names:
                if self._degrade_tenant(tenant, pts, reason=reason):
                    degraded.append(tenant)
        return degraded

    # -- graft-reshard: live elasticity (grow direction) -------------------

    def grow(self, reason: str = "slo_pressure") -> bool:
        """Cut the base rung over to the grown layout without a cold
        restart: build the grow target, migrate every per-request
        checkpoint onto its carriage through a staged redistribution
        plan (per-stage scratch <= ``reshard_budget_bytes``;
        parallel/reshard.py), swap the resident HBM charge, then route
        base-rung traffic to the grown executor.  Idempotent — a
        second call (e.g. a rerun resuming after a kill mid-migration)
        re-migrates only checkpoints still on the old layout.  Returns
        whether the server is serving the grown layout afterwards."""
        if self._grown is not None:
            return True
        if self.grow_config is None and self.grow_factory is None:
            self._event("grow_unavailable", reason=reason)
            self._log(f"grow requested ({reason}) but no grow target "
                      f"is declared")
            return False
        cfg = self.grow_config or self.base_config
        factory = self.grow_factory or self._factory
        try:
            new_exec = factory(cfg)
        except Exception as e:  # noqa: BLE001 — a grow target that
            # cannot build must not take the serving rung down with it.
            self._event("grow_failed", reason=reason,
                        error=f"{type(e).__name__}: {e}")
            self._log(f"grow target failed to build "
                      f"({type(e).__name__}: {e}); staying put")
            return False
        old_exec = self._build_executor(self.base_config)
        from arrow_matrix_tpu.obs.memview import predicted_bytes_for

        old_res = predicted_bytes_for(
            old_exec, 0, itemsize=self.itemsize,
            repl=self.base_config.repl) or 0
        new_res = predicted_bytes_for(
            new_exec, 0, itemsize=self.itemsize, repl=cfg.repl) or 0
        try:
            self.accountant.swap_resident(old_res, new_res)
        except ServeCapacityError as e:
            self._event("grow_failed", reason=reason, error=str(e))
            self._log(f"grow refused: {e}")
            return False
        try:
            migrated, stages = self._migrate_checkpoints(old_exec,
                                                         new_exec)
        except Exception:
            # Leave the ledger honest before surfacing the failure.
            self.accountant.swap_resident(new_res, old_res)
            raise
        with self._lock:
            self._grown = (new_exec, cfg)
            self.grows += 1
        self._event("grown", reason=reason,
                    config=dataclasses.asdict(cfg),
                    resident_bytes={"old": old_res, "new": new_res},
                    checkpoints_migrated=migrated,
                    plan_stages=stages)
        # The reshard gate greps this line; print it regardless of
        # verbosity (like the resumed-request marker).
        print(f"[graft-serve {self.name}] grew to {cfg} ({reason}): "
              f"{migrated} checkpoint(s) migrated through {stages} "
              f"staged plan step(s)", flush=True)
        return True

    def _migrate_checkpoints(self, old_exec, new_exec
                             ) -> Tuple[int, int]:
        """Replay every per-request checkpoint still on the old layout
        through a staged plan onto the grown layout, in place (atomic
        save; a SIGKILL mid-migration leaves each checkpoint either on
        the old or the new layout, never torn — the rerun's grow()
        finishes the stragglers).  Returns (migrated, total stages)."""
        import os

        if not self.checkpoint_dir \
                or not os.path.isdir(self.checkpoint_dir):
            return 0, 0
        src_fn = getattr(old_exec, "reshard_layout", None)
        dst_fn = getattr(new_exec, "reshard_layout", None)
        if src_fn is None or dst_fn is None:
            self._event("grow_migration_skipped",
                        error="executor pair exposes no reshard_layout")
            return 0, 0
        from arrow_matrix_tpu.parallel.reshard import (
            apply_plan_host,
            redistribution_plan,
        )
        from arrow_matrix_tpu.utils.checkpoint import (
            checkpoint_layout_tag,
            list_checkpoints,
            load_state,
            save_state,
        )

        src_lay, dst_lay = src_fn(), dst_fn()
        ps = np.asarray(old_exec.perm0)
        pd = np.asarray(new_exec.perm0)
        if (src_lay.stored_rows == dst_lay.stored_rows
                and np.array_equal(ps, pd)):
            return 0, 0   # identical carriage: nothing to migrate
        if src_lay.stored_rows == dst_lay.stored_rows:
            # Equal-size relayout cannot be told apart from an
            # already-migrated file by shape — refusing beats silently
            # double-permuting a checkpoint on a rerun.
            raise ValueError(
                "grow between equal-size layouts with different row "
                "orders is not idempotently resumable; grow must "
                "change total_rows/n_dev/repl")
        inv_s = np.asarray(old_exec.inv_perm0)
        n = int(new_exec.n)
        perm_map = np.where(pd < n, inv_s[np.minimum(pd, len(inv_s) - 1)],
                            np.int64(-1))
        migrated = stages = 0
        for stem in list_checkpoints(self.checkpoint_dir):
            key = os.path.basename(stem)[len("ck_"):]
            tag = checkpoint_layout_tag(stem)
            try:
                got = load_state(stem, layout=tag)
            except Exception as e:  # noqa: BLE001 — unreadable file:
                # the normal resume path already discards it loudly.
                self._event("grow_migration_skipped", request=key,
                            error=f"{type(e).__name__}: {e}")
                continue
            if got is None:
                continue
            x, step = got
            x = np.asarray(x)
            if x.ndim != 2:
                self._event("grow_migration_skipped", request=key,
                            error=f"unmigratable carriage shape "
                                  f"{x.shape}")
                continue
            # Orientation: flat carriage is (rows, k), folded carriage
            # is feature-major (k, rows).
            if x.shape[0] == src_lay.stored_rows:
                transpose = False
            elif x.shape[1] == src_lay.stored_rows:
                transpose = True
            elif dst_lay.stored_rows in x.shape:
                continue   # already on the grown layout (rerun)
            else:
                self._event("grow_migration_skipped", request=key,
                            error=f"carriage shape {x.shape} matches "
                                  f"neither layout")
                continue
            k = int(x.shape[0] if transpose else x.shape[1])
            plan = redistribution_plan(src_lay, dst_lay,
                                       self.reshard_budget_bytes, k=k,
                                       perm_map=perm_map)
            y = (apply_plan_host(plan, x.T).T if transpose
                 else apply_plan_host(plan, x))
            save_state(stem, y, step, layout=tag)
            migrated += 1
            stages += plan.n_stages
            with self._lock:
                self.checkpoints_resharded += 1
            self._event("checkpoint_resharded", request=key, step=step,
                        stages=plan.n_stages,
                        max_stage_scratch_bytes=
                        plan.max_stage_scratch_bytes,
                        budget_bytes=plan.scratch_budget_bytes)
        return migrated, stages

    # -- reporting ---------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def latency_samples_ms(self) -> List[float]:
        """Every completed request's latency in ms, in completion
        order — the raw samples graft-fleet ships over the wire so
        the router's merged fleet quantiles are pooled over ALL
        workers' samples exactly, not approximated from summaries."""
        with self._lock:
            return [lat * 1e3 for lat in self._latencies_s]

    def class_latency_samples_ms(self) -> Dict[str, List[float]]:
        """Completed-request latencies (ms) keyed by served class —
        the per-class half of the SLO report."""
        with self._lock:
            return {cls: [lat * 1e3 for lat in vals]
                    for cls, vals in
                    sorted(self._class_latencies_s.items())}

    def opt_in_approx(self, tenant: str) -> None:
        """Record a tenant's explicit consent to exact -> approx class
        degradation (the ladder rung below the terminal config; never
        taken without this)."""
        with self._lock:
            self._tenant(tenant).allow_approx = True

    def summary(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            tenants = {
                name: {
                    "rung": t.rung,
                    "config": dataclasses.asdict(self.ladder[t.rung]),
                    "fault_score": t.fault_score,
                    "allow_approx": t.allow_approx,
                    "class_degraded": t.class_degraded,
                    "completed": counts.get(f"completed:{name}", 0),
                    "failed": counts.get(f"failed:{name}", 0),
                    "shed": counts.get(f"shed:{name}", 0),
                    "rejected": counts.get(f"rejected:{name}", 0),
                    "degradations": list(t.degradations),
                }
                for name, t in sorted(self._tenants.items())
            }
            classes = {
                cls: {
                    "admitted": counts.get(f"admitted:class:{cls}", 0),
                    "completed": counts.get(
                        f"completed:class:{cls}", 0),
                    "requests": len(self._class_latencies_s.get(
                        cls, ())),
                }
                for cls in ("exact", "approx")
            }
            # The bare fault/batch counters are read under the same
            # lock their writers hold — a summary taken mid-batch is
            # a consistent cut, not a torn one.  The accountant
            # snapshot nests its own lock inside ours: the declared
            # arrow_server -> hbm_accountant order.
            return {
                "server": self.name,
                "submitted": counts.get("submitted", 0),
                "admitted": counts.get("admitted", 0),
                "completed": counts.get("completed", 0),
                "failed": counts.get("failed", 0),
                "shed": counts.get("shed", 0),
                "rejected": counts.get("rejected", 0),
                "class_fallback": counts.get("class_fallback", 0),
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "faults_seen": self.faults_seen,
                "recoveries": self.recoveries,
                "checkpoint_corruptions": self.checkpoint_corruptions,
                "hbm": self.accountant.snapshot(),
                "tenants": tenants,
                "classes": classes,
                "certificates": {
                    dt: {"iterations": c.iterations,
                         "tolerance": c.tolerance,
                         "bound": c.bound_at(c.iterations),
                         "record_id": c.record_id}
                    for dt, c in sorted(self._certificates.items())
                },
            }
