"""Request/ticket model of the graft-serve runtime.

A :class:`Request` is what a tenant hands the server: host features in
original row order, an iteration count, and an optional deadline.  A
:class:`Ticket` is what the server hands back immediately — the
request's supervised life (admission decision, queueing, execution,
recovery, completion) is recorded on it, and every ticket reaches
exactly one terminal state.  The explicit-outcome contract is the
load-shedding half of the robustness story: a shed or rejected request
is *told* so (429-style), never silently dropped, and
tools/serve_gate.py asserts the terminal-state census is deterministic
under replay.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

#: Ticket states.  pending -> admitted -> running -> one of the
#: terminal states; rejected/shed may be assigned straight from
#: pending (admission control / queue overflow / expired deadline).
PENDING = "pending"
ADMITTED = "admitted"
RUNNING = "running"
COMPLETED = "completed"
FAILED = "failed"
SHED = "shed"
REJECTED = "rejected"

TERMINAL = frozenset({COMPLETED, FAILED, SHED, REJECTED})


@dataclasses.dataclass
class Request:
    """One tenant request: iterate ``X := A @ X`` ``iterations`` times
    over the server's resident operator, starting from the tenant's
    ``x`` (host ``(n, k)`` array, original row order).

    ``deadline_s`` is a relative budget from submission: a request
    still queued past its deadline is shed explicitly at dequeue time
    (running work is governed by the watchdog, not the deadline).
    """

    request_id: str
    tenant: str
    x: np.ndarray
    iterations: int
    deadline_s: Optional[float] = None
    # graft-classes: the accuracy class the tenant is asking for.
    # "exact" (default) is f32 bit-identity, today's contract; "approx"
    # asks for certified reduced-precision carriage — granted only when
    # the server holds a covering certificate for this iteration count,
    # otherwise served exact with a loud class_fallback event.
    traffic_class: str = "exact"

    @property
    def k(self) -> int:
        return int(self.x.shape[1])


class Ticket:
    """The server's receipt for one request; thread-safe to wait on."""

    def __init__(self, request: Request):
        self.request = request
        self.status = PENDING
        self.reason: Optional[str] = None
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.predicted_bytes = 0      # admission price (reserved HBM)
        self.submitted_s: Optional[float] = None
        self.latency_s: Optional[float] = None
        self.faults_seen = 0
        self.recoveries = 0
        self.attempts = 0             # executions (1 + degraded reruns)
        self.exec_config = None       # ExecConfig the result came from
        self.resumed_step: Optional[int] = None
        # graft-classes: the class actually served (may differ from
        # request.traffic_class on a certificate-miss fallback) and,
        # when it does differ, why — never a silent substitution.
        self.served_class: str = request.traffic_class
        self.class_fallback: Optional[str] = None
        self.certified_bound: Optional[float] = None
        # graft-xray: the correlation context captured at submit time
        # ({"trace_id": ...} and friends) — the handoff that carries
        # the fleet-level trace onto the batch worker thread.
        self.trace: Optional[dict] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self.status in TERMINAL

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the ticket reaches a terminal state."""
        return self._done.wait(timeout)

    def _finish(self, status: str, reason: Optional[str] = None,
                error: Optional[str] = None) -> None:
        assert status in TERMINAL, status
        self.status = status
        self.reason = reason
        self.error = error
        if self.submitted_s is not None:
            self.latency_s = time.monotonic() - self.submitted_s
        self._done.set()

    def summary(self) -> dict:
        return {
            "request_id": self.request.request_id,
            "tenant": self.request.tenant,
            "k": self.request.k,
            "iterations": self.request.iterations,
            "status": self.status,
            "reason": self.reason,
            "traffic_class": self.request.traffic_class,
            "served_class": self.served_class,
            "class_fallback": self.class_fallback,
            "certified_bound": self.certified_bound,
            "predicted_bytes": self.predicted_bytes,
            "latency_s": self.latency_s,
            "faults_seen": self.faults_seen,
            "recoveries": self.recoveries,
            "attempts": self.attempts,
        }
