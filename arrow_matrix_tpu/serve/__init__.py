"""graft-serve: the always-on multi-tenant SpMM serving runtime.

ROADMAP item 1's pivot from batch to serving: the decomposed arrow
operator stays HBM-resident while a stream of concurrent tenant
requests runs over it, each under graft-heal supervision.  The pieces:

  * :mod:`~arrow_matrix_tpu.serve.request` — the request/ticket model
    (every request reaches exactly one explicit terminal state).
  * :mod:`~arrow_matrix_tpu.serve.admission` — the live HBM
    accountant; requests are priced via the memview static model
    *before* enqueue and rejected 429-style when over budget.
  * :mod:`~arrow_matrix_tpu.serve.scheduler` — bounded queue +
    deterministic FIFO scheduler with dynamic feature-axis batching,
    per-request Supervisor (watchdog / seeded-backoff retry /
    sha256-verified checkpoint resume), and the graceful-degradation
    ladder pallas_sell -> xla, repl=c -> 1, overlap S -> 1.
  * :mod:`~arrow_matrix_tpu.serve.loadgen` — deterministic synthetic
    traces and the SLO report (requests/s, p50/p99, shed counts, HBM
    occupancy) obs_gate validates — one field vocabulary with the
    graft-pulse streaming series (obs/pulse.py), which attaches to a
    server via ``ArrowServer.attach_pulse`` for live windowed
    telemetry and SLO-burn-driven degradation.

Gates: ``tools/serve_gate.py`` (chaos under load — hang/kill/corrupt/
overflow with >= 4 tenants in flight, surviving requests bit-identical
to fault-free replay), wired into ``tools/chaos_gate.py``'s matrix.
CLI: ``graft_serve`` (cli/graft_serve.py).
"""

from arrow_matrix_tpu.serve.admission import (
    HBMAccountant,
    ServeCapacityError,
    request_price_bytes,
)
from arrow_matrix_tpu.serve.loadgen import (
    ba_executor_factory,
    latency_summary_ms,
    run_trace,
    slo_summary,
    smoke_serve,
    synthetic_trace,
    write_serve_artifacts,
)
from arrow_matrix_tpu.serve.request import Request, Ticket
from arrow_matrix_tpu.serve.scheduler import (
    ArrowServer,
    ExecConfig,
    degradation_ladder,
)

__all__ = [
    "ArrowServer",
    "ExecConfig",
    "HBMAccountant",
    "Request",
    "ServeCapacityError",
    "Ticket",
    "ba_executor_factory",
    "degradation_ladder",
    "latency_summary_ms",
    "request_price_bytes",
    "run_trace",
    "slo_summary",
    "smoke_serve",
    "synthetic_trace",
    "write_serve_artifacts",
]
