"""Admission control: the live HBM accountant.

The serving pivot keeps the decomposed arrow operator HBM-resident
across requests, so the only per-request memory is carriage — the
``2 * rows_per_device * k * itemsize`` input+output feature slabs the
static model already prices (``MultiLevelArrow.carriage_hbm_bytes``,
surfaced through ``obs/memview.request_bytes_for``).  The accountant
holds one budget: the resident operator is charged once at server
start, every admitted request reserves its carriage price *before*
enqueue, and the reservation is released only when the ticket reaches
a terminal state.  A request whose price does not fit the remaining
headroom is rejected explicitly (429-style) — never queued in hope.

This is the admission-control lens of "Memory-efficient array
redistribution through portable collective communication" (arXiv
2112.01075): bound the footprint *before* committing to the work, so
the resident operator can never be wedged by accepted load.
"""

from __future__ import annotations

import threading
from typing import Optional

from arrow_matrix_tpu.sync import guarded_by, witnessed


class ServeCapacityError(RuntimeError):
    """The configured HBM budget cannot even host the resident
    operator: the server refuses to start (serving from swap-in-denial
    is not graceful degradation)."""


@guarded_by("_lock", node="hbm_accountant",
            attrs=("in_use_bytes", "peak_in_use_bytes",
                   "resident_bytes"))
class HBMAccountant:
    """Thread-safe reserve/release ledger against one byte budget.

    ``budget_bytes`` is the total per-device budget; ``charge`` takes
    a permanent reservation (the resident operator), ``reserve`` a
    releasable one (request carriage).  ``reserve`` is
    all-or-nothing and exact: a request *exactly* at the remaining
    headroom is admitted (<=), one byte over is not.
    """

    def __init__(self, budget_bytes: int, registry=None,
                 name: str = "serve"):
        self.budget_bytes = int(budget_bytes)
        if self.budget_bytes < 0:
            raise ValueError(f"budget_bytes must be >= 0, got "
                             f"{budget_bytes}")
        self.in_use_bytes = 0
        self.peak_in_use_bytes = 0
        self.resident_bytes = 0
        self._lock = witnessed("hbm_accountant", threading.Lock())
        self._registry = registry
        self._name = name

    def _gauges(self) -> None:
        if self._registry is None:
            return
        self._registry.gauge("serve_hbm_in_use_bytes",
                             server=self._name).set(self.in_use_bytes)
        self._registry.gauge("serve_hbm_occupancy",
                             server=self._name).set(self.occupancy())

    def charge_resident(self, nbytes: int) -> None:
        """Permanent charge for the operator that stays HBM-resident
        across every request; raises :class:`ServeCapacityError` when
        it alone exceeds the budget."""
        nbytes = max(int(nbytes), 0)
        with self._lock:
            if self.in_use_bytes + nbytes > self.budget_bytes:
                raise ServeCapacityError(
                    f"resident operator needs {nbytes} B but the HBM "
                    f"budget is {self.budget_bytes} B (in use "
                    f"{self.in_use_bytes} B) — the server cannot host "
                    f"the decomposition; raise the budget or shrink "
                    f"the operator")
            self.resident_bytes += nbytes
            self.in_use_bytes += nbytes
            self.peak_in_use_bytes = max(self.peak_in_use_bytes,
                                         self.in_use_bytes)
        self._gauges()

    def reserve(self, nbytes: int) -> bool:
        """Reserve ``nbytes`` if (and only if) they fit the remaining
        headroom; returns whether the reservation was taken."""
        nbytes = max(int(nbytes), 0)
        with self._lock:
            if self.in_use_bytes + nbytes > self.budget_bytes:
                return False
            self.in_use_bytes += nbytes
            self.peak_in_use_bytes = max(self.peak_in_use_bytes,
                                         self.in_use_bytes)
        self._gauges()
        return True

    def swap_resident(self, old_bytes: int, new_bytes: int) -> None:
        """Atomically replace part of the permanent resident charge —
        graft-reshard's grow direction retires the old operator as the
        grown one lands.  Raises :class:`ServeCapacityError` (leaving
        the ledger untouched) when the swap would overrun the budget:
        both operators are briefly live during a migration, but the
        steady state must fit."""
        old_bytes = max(int(old_bytes), 0)
        new_bytes = max(int(new_bytes), 0)
        with self._lock:
            grown = self.in_use_bytes - old_bytes + new_bytes
            if grown > self.budget_bytes:
                raise ServeCapacityError(
                    f"grown resident operator needs {new_bytes} B "
                    f"(replacing {old_bytes} B) but the HBM budget is "
                    f"{self.budget_bytes} B (in use "
                    f"{self.in_use_bytes} B) — refusing to grow past "
                    f"the certificate")
            self.resident_bytes = max(
                self.resident_bytes - old_bytes, 0) + new_bytes
            self.in_use_bytes = max(grown, 0)
            self.peak_in_use_bytes = max(self.peak_in_use_bytes,
                                         self.in_use_bytes)
        self._gauges()

    def release(self, nbytes: int) -> None:
        nbytes = max(int(nbytes), 0)
        with self._lock:
            self.in_use_bytes = max(self.in_use_bytes - nbytes,
                                    self.resident_bytes)
        self._gauges()

    def occupancy(self) -> float:
        if self.budget_bytes <= 0:
            return 1.0 if self.in_use_bytes else 0.0
        return self.in_use_bytes / self.budget_bytes

    def headroom_bytes(self) -> int:
        return max(self.budget_bytes - self.in_use_bytes, 0)

    def snapshot(self) -> dict:
        with self._lock:
            budget = self.budget_bytes
            in_use = self.in_use_bytes
            peak = self.peak_in_use_bytes
            resident = self.resident_bytes
        return {
            "budget_bytes": budget,
            "resident_bytes": resident,
            "in_use_bytes": in_use,
            "peak_in_use_bytes": peak,
            "occupancy": (in_use / budget) if budget > 0 else
                         (1.0 if in_use else 0.0),
            "peak_occupancy": (peak / budget) if budget > 0 else
                              (1.0 if peak else 0.0),
        }


def request_price_bytes(executor, k: int, itemsize: int = 4,
                        repl: int = 1) -> int:
    """Admission price of one request of feature width ``k`` against
    ``executor``: the static model's incremental carriage bytes
    (``obs/memview.request_bytes_for``).  An executor with no model
    prices at 0 with a loud warning — admission control degrades to
    queue-bounding only, it does not guess."""
    from arrow_matrix_tpu.obs.memview import request_bytes_for

    price: Optional[int] = request_bytes_for(executor, k,
                                             itemsize=itemsize,
                                             repl=repl)
    if price is None:
        import sys

        print(f"[graft-serve] WARNING: executor "
              f"{type(executor).__name__} exposes no HBM model; "
              f"admitting width-{k} request unpriced", file=sys.stderr)
        return 0
    return int(price)
