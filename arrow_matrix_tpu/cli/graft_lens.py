"""``graft_lens`` — operator surface of the graft-lens cost model.

Subcommands close the profile → fit → predict loop:

* ``profile`` — per-degree-ladder-level chained timing of one
  structure's fold step (``obs/lens.py:profile_fold``) per carriage
  dtype, each measurement paired with its static counters; writes the
  profile document (``--out``) and optionally sinks ``kind="lens"``
  ledger records (``--ledger-dir``).
* ``fit`` — fit the per-level-family model
  ``t ≈ α·nnz + β·rows + γ·streamed_bytes`` from a profile document
  and write it as a versioned CostModel JSON.
* ``predict`` — predict one candidate's iteration ms from a model and
  a structure source, WITHOUT running anything (the tune compute
  screen's primitive).
* ``explain`` — attribute the bf16-vs-f32 (or any dtype pair)
  full-iteration gap per level and name the dominant segment
  (gather-bytes / decode-accumulate / dma-wait).
* ``check`` — validate a profile (+model): schema, attribution
  coverage, calibration ratios in band; exits nonzero on problems
  (``tools/lens_gate.py`` engine).

Prints ONE JSON line as its last stdout line (CLI contract).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _add_source_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--ba", type=str, default=None,
                   help="Barabasi-Albert source: N,WIDTH,SEED")
    p.add_argument("--ba_m", type=int, default=3,
                   help="BA attachment parameter m")
    p.add_argument("--max_levels", type=int, default=10)
    p.add_argument("--base", type=str, default=None,
                   help="committed graphio artifact directory "
                        "(e.g. bench_cache/ba_16384_8_w512_s7_L12)")
    p.add_argument("--width", type=int, default=None,
                   help="decomposition width inside --base (default: "
                        "autodetect)")


def _source_from_args(args) -> dict:
    if args.ba and args.base:
        raise SystemExit("graft_lens: --ba and --base are exclusive")
    if args.ba:
        try:
            n, width, seed = (int(v) for v in args.ba.split(","))
        except ValueError:
            raise SystemExit("graft_lens: --ba wants N,WIDTH,SEED "
                             "(e.g. --ba 256,32,0)")
        return {"kind": "ba", "n": n, "m": args.ba_m, "width": width,
                "seed": seed, "max_levels": args.max_levels}
    if args.base:
        src = {"kind": "dir", "base": args.base}
        if args.width:
            src["width"] = args.width
        return src
    raise SystemExit("graft_lens: need --ba N,WIDTH,SEED or "
                     "--base DIR")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_lens", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("profile", help="per-level chained timing of "
                                        "one structure's fold step")
    _add_source_args(pr)
    pr.add_argument("--k", type=int, default=64,
                    help="feature width to profile (default 64 — "
                         "enough per-tier work that prefix "
                         "differencing resolves the small tiers)")
    pr.add_argument("--kernel", choices=("auto", "xla", "pallas"),
                    default="auto")
    pr.add_argument("--dtypes", type=str, default="f32,bf16",
                    help="comma-separated carriage dtypes "
                         "(default f32,bf16 — the pair separates the "
                         "byte coefficient)")
    pr.add_argument("--iters", type=int, default=100,
                    help="chained iterations per measurement")
    pr.add_argument("--ring-sweep", action="store_true",
                    help="re-time each tier at ring=1 (pallas only): "
                         "the excess is the DMA wait the ring hides")
    pr.add_argument("--out", type=str, default=None,
                    help="write the profile document here")
    pr.add_argument("--ledger-dir", type=str, default=None,
                    help="sink kind='lens' records (ms + coverage; "
                         "with --fit also the calibration ratios)")
    pr.add_argument("--fit", type=str, default=None, metavar="MODEL",
                    help="also fit and write the CostModel JSON here")

    f = sub.add_parser("fit", help="fit the per-level-family cost "
                                   "model from a profile")
    f.add_argument("profile", help="profile JSON (graft_lens profile "
                                   "--out)")
    f.add_argument("--out", type=str, default=None,
                   help="write the CostModel JSON here")
    f.add_argument("--dtypes", type=str, default=None,
                   help="restrict the fit to these carriage dtypes")

    pd = sub.add_parser("predict", help="predict iteration ms for a "
                                        "structure from a model — no "
                                        "execution")
    pd.add_argument("model", help="CostModel JSON (graft_lens fit "
                                  "--out)")
    _add_source_args(pd)
    pd.add_argument("--k", type=int, default=64)
    pd.add_argument("--kernel", choices=("xla", "pallas"),
                    default="xla")
    pd.add_argument("--dtype", type=str, default="f32",
                    help="carriage dtype (f32 / bf16)")
    pd.add_argument("--ring", type=int, default=None,
                    help="ring depth: 1 adds the per-level DMA wait "
                         "the deep ring would hide")

    e = sub.add_parser("explain", help="attribute a dtype pair's "
                                       "full-iteration gap per level")
    e.add_argument("profile")
    e.add_argument("--model", type=str, default=None,
                   help="CostModel JSON: classifies the dominant "
                        "delta into gather-bytes vs decode/accumulate")
    e.add_argument("--base", dest="base_dtype", type=str,
                   default="f32")
    e.add_argument("--other", dest="other_dtype", type=str,
                   default="bf16")

    c = sub.add_parser("check", help="validate a profile (+model); "
                                     "nonzero on problems")
    c.add_argument("profile")
    c.add_argument("--model", type=str, default=None)
    c.add_argument("--coverage-tol", type=float, default=None,
                   help="override LENS_COVERAGE_TOL")
    return p


def _load_json(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _load_model(path: str):
    from arrow_matrix_tpu.obs.costmodel import CostModel
    return CostModel.from_dict(_load_json(path))


def _levels(args):
    from arrow_matrix_tpu.tune.search import load_levels_from_source
    return load_levels_from_source(_source_from_args(args))


def cmd_profile(args) -> int:
    from arrow_matrix_tpu.obs import lens

    levels, width = _levels(args)
    dtypes = tuple(d for d in args.dtypes.split(",") if d)
    profile = lens.profile_fold(
        levels, width, args.k, kernel=args.kernel,
        feature_dtypes=dtypes, iters=args.iters,
        ring_sweep=args.ring_sweep)
    model = None
    if args.fit:
        model = lens.fit_from_profile(profile)
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json
        atomic_write_json(args.fit, model.to_dict(), indent=2,
                          sort_keys=True)
    if args.out:
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json
        atomic_write_json(args.out, profile, indent=2, sort_keys=True)
    record_ids: List[str] = []
    if args.ledger_dir:
        record_ids = lens.record_profile(profile, model,
                                         directory=args.ledger_dir)
    summary = {
        "ok": True, "cmd": "profile",
        "structure_hash": profile["structure_hash"],
        "kernel": profile["kernel"], "k": profile["k"],
        "dtypes": {fd: {"full_ms": round(entry["full_ms"], 6),
                        "coverage": round(entry["coverage"], 4)}
                   for fd, entry in profile["dtypes"].items()},
        "records": len(record_ids),
    }
    if args.out:
        summary["profile"] = args.out
    if args.fit:
        summary["model"] = args.fit
    print(json.dumps(summary, sort_keys=True))
    return 0


def cmd_fit(args) -> int:
    from arrow_matrix_tpu.obs import lens

    profile = _load_json(args.profile)
    dtypes = (tuple(d for d in args.dtypes.split(",") if d)
              if args.dtypes else None)
    model = lens.fit_from_profile(profile, dtypes=dtypes)
    if args.out:
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json
        atomic_write_json(args.out, model.to_dict(), indent=2,
                          sort_keys=True)
    print(json.dumps({"ok": True, "cmd": "fit",
                      "structure_hash": model.structure_hash,
                      "families": sorted(model.coeffs),
                      **({"model": args.out} if args.out else {})},
                     sort_keys=True))
    return 0


def cmd_predict(args) -> int:
    import numpy as np

    from arrow_matrix_tpu.obs.costmodel import predict_iter_ms
    from arrow_matrix_tpu.tune.fingerprint import structure_fingerprint

    model = _load_model(args.model)
    levels, width = _levels(args)
    fp = structure_fingerprint(levels, width, np.float32)
    fd = None if args.dtype == "f32" else args.dtype
    ms = predict_iter_ms(fp, args.k, model, kernel=args.kernel,
                         feature_dtype=fd, ring=args.ring)
    print(json.dumps({"ok": True, "cmd": "predict",
                      "predicted_ms": round(float(ms), 6),
                      "kernel": args.kernel, "k": args.k,
                      "dtype": args.dtype}, sort_keys=True))
    return 0


def cmd_explain(args) -> int:
    from arrow_matrix_tpu.obs import lens

    profile = _load_json(args.profile)
    model = _load_model(args.model) if args.model else None
    gap = lens.explain_gap(profile, base=args.base_dtype,
                           other=args.other_dtype, model=model)
    if gap.get("note"):
        print(gap["note"])
    print(json.dumps({"ok": True, "cmd": "explain",
                      "gap_ms": round(gap["gap_ms"], 6),
                      "dominant": gap["dominant"],
                      "dominant_segment": gap["dominant_segment"],
                      "per_level": {lbl: round(v, 6) for lbl, v
                                    in gap["per_level"].items()}},
                     sort_keys=True))
    return 0


def cmd_check(args) -> int:
    from arrow_matrix_tpu.obs import lens

    profile = _load_json(args.profile)
    model = _load_model(args.model) if args.model else None
    kwargs = {}
    if args.coverage_tol is not None:
        kwargs["coverage_tol"] = args.coverage_tol
    problems = lens.check_profile(profile, model, **kwargs)
    for p in problems:
        print(f"lens check: {p}", file=sys.stderr)
    print(json.dumps({"ok": not problems, "cmd": "check",
                      "problems": problems}, sort_keys=True))
    return 1 if problems else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return {"profile": cmd_profile, "fit": cmd_fit,
            "predict": cmd_predict, "explain": cmd_explain,
            "check": cmd_check}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
