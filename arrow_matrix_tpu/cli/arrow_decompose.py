"""``arrow_decompose`` — offline arrow decomposition CLI.

Counterpart of the reference's decomposition entry point
(reference scripts/decomposition_main.py:109-208): load a graph, run
``arrow_decomposition``, save the npy-triplet artifact.  Flags mirror
the reference's (``:121-137``); ``--format`` is inferred from the file
extension here instead of being a separate flag.
"""

from __future__ import annotations

import argparse
import os
import pickle
import time

import numpy as np

from arrow_matrix_tpu.cli.common import load_sparse_matrix, str2bool


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Arrow decomposition of sparse graphs.")
    parser.add_argument("--width", type=int, default=5_000_000,
                        help="Arrow width (block size).")
    parser.add_argument("--dataset_dir", type=str, default=".",
                        help="Directory containing the graph files.")
    parser.add_argument("--dataset_name", nargs="+", type=str, required=True,
                        help="Graph file names (extension included; "
                             ".npz/.mtx/.mat).")
    parser.add_argument("--levels", type=int, default=10,
                        help="Maximum number of decomposition levels "
                             "(the reference hardcodes 10, "
                             "decomposition_main.py:184).")
    parser.add_argument("--block_diagonal", type=str2bool, nargs="?",
                        default=True,
                        help="Block-diagonal (vs banded) edge criterion.")
    parser.add_argument("--directed", type=str2bool, nargs="?", default=False,
                        help="Accepted for reference flag parity; the "
                             "decomposer handles asymmetric inputs "
                             "automatically (structural symmetrization "
                             "for linearization only).")
    parser.add_argument("--seed", type=int, default=0,
                        help="Linearization RNG seed.")
    parser.add_argument("--visualize", type=str2bool, nargs="?",
                        default=False,
                        help="Save a spy plot of each level "
                             "(decomposition_main.py:83-106).")
    parser.add_argument("--save_input_graph", type=str2bool, nargs="?",
                        default=False,
                        help="Pickle the parsed input graph next to the "
                             "artifact to skip re-parsing "
                             "(decomposition_main.py:157-162).")
    parser.add_argument("--out_dir", type=str, default=None,
                        help="Output directory (default: dataset_dir).")
    parser.add_argument("--band_detect", type=str2bool, nargs="?",
                        default=True,
                        help="Detect banded/bandable inputs (identity "
                             "or RCM order) and emit ONE level with "
                             "zero routing; false restores the plain "
                             "recursion (e.g. to regenerate legacy "
                             "multi-level artifacts).")
    parser.add_argument("--backend", type=str, default="auto",
                        choices=["auto", "native", "numpy"],
                        help="Linearization backend: native C++ kernels "
                             "(the reference's fast Julia decomposer "
                             "role) or the scipy/csgraph implementation. "
                             "Backends use different RNG streams: pin "
                             "one for seed-reproducible results across "
                             "machines.")
    return parser


def decompose_one(path: str, args: argparse.Namespace) -> None:
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition

    base_name = os.path.splitext(os.path.basename(path))[0]
    out_dir = args.out_dir or args.dataset_dir
    os.makedirs(out_dir, exist_ok=True)
    base = os.path.join(out_dir, base_name)

    # The cache is only honored when --save_input_graph opted into it,
    # and only while it is at least as new as the source file (a stale
    # pickle must never silently replace an updated input graph; pickle
    # is also an arbitrary-code-execution format, so loading one the
    # user never asked to create is not acceptable).
    cache = base + ".pickle"
    # Strict >: a source rewrite landing within the filesystem's
    # timestamp granularity of the cache write must invalidate (same
    # tie-break direction as the native-library staleness check).
    cache_fresh = (args.save_input_graph and os.path.exists(cache)
                   and (not os.path.exists(path)
                        or os.path.getmtime(cache) > os.path.getmtime(path)))
    if cache_fresh:
        print(f"loading cached graph {cache}")
        with open(cache, "rb") as f:
            a = pickle.load(f)
    else:
        print(f"loading {path}")
        a = load_sparse_matrix(path)
        if args.save_input_graph:
            with open(cache, "wb") as f:
                pickle.dump(a, f)

    print(f"decomposing n={a.shape[0]} nnz={a.nnz} width={args.width} "
          f"levels<={args.levels} block_diagonal={args.block_diagonal}")
    tic = time.perf_counter()
    # Directed graphs need no special flag: the decomposer symmetrizes
    # the structural pattern internally for linearization (the Julia
    # reference's `symmetric` pre-step, ArrowDecomposition.jl:119-124)
    # while the level matrices keep the asymmetric values.
    levels = arrow_decomposition(
        a, arrow_width=args.width, max_levels=args.levels,
        block_diagonal=args.block_diagonal, seed=args.seed,
        backend=args.backend, band_detect=args.band_detect)
    print(f"decomposed into {len(levels)} levels in "
          f"{time.perf_counter() - tic:.1f}s; achieved widths "
          f"{[l.arrow_width for l in levels]}")

    save_decomposition(levels, base, block_diagonal=args.block_diagonal)
    print(f"saved artifact under {base}_B_{levels[0].arrow_width}_*")

    if args.visualize:
        visualize(levels, base)


def visualize(levels, base: str) -> None:
    """Spy-plot each level (reference
    visualize_banded_decomposition, decomposition_main.py:83-106)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, len(levels),
                             figsize=(4 * len(levels), 4), squeeze=False)
    for ax, lvl in zip(axes[0], levels):
        ax.spy(lvl.matrix, markersize=0.1)
        ax.set_title(f"width {lvl.arrow_width}")
    fig.savefig(base + "_decomposition.png", dpi=150, bbox_inches="tight")
    plt.close(fig)
    print(f"wrote {base}_decomposition.png")


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    for name in args.dataset_name:
        decompose_one(os.path.join(args.dataset_dir, name), args)


if __name__ == "__main__":
    main()
