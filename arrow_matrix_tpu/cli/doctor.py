"""``amt_doctor`` — environment diagnosis for the framework.

Packages the operational knowledge the other entry points depend on
into one read-only command: which JAX backend is reachable (with a
bounded subprocess probe — a wedged TPU tunnel hangs ``jax.devices()``
indefinitely, the failure mode every CLI here defends against), how
many devices a virtual CPU pool would give, whether the native C++
decomposer builds, whether cross-process collectives are available,
and the state of the benchmark caches.

Prints one human-readable report and exits 0 when the core checks
pass (accelerator reachability is reported but NOT required — the
framework's CPU paths are first-class).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def _check(label: str, ok, detail: str = "") -> bool:
    mark = {True: "ok  ", False: "FAIL", None: "warn"}[ok]
    print(f"[{mark}] {label}" + (f": {detail}" if detail else ""),
          flush=True)
    return ok is not False


def probe_accelerator(timeout_s: float) -> tuple[bool, str]:
    """Bounded real-data round-trip on the DEFAULT backend (the shared
    probe contract, utils.platform.probe_default_backend)."""
    from arrow_matrix_tpu.utils.platform import probe_default_backend

    platform, kind, err = probe_default_backend(timeout_s=timeout_s,
                                                retries=1)
    if err is not None:
        return False, (f"{err} — wedged tunnel / hung PJRT plugin? "
                       f"(CLIs degrade to CPU; see --device cpu)")
    return True, f"{platform} {kind}"


def probe_cpu_pool(n: int) -> tuple[bool, str]:
    code = (f"import sys; sys.argv=[]; "
            f"from arrow_matrix_tpu.utils.platform import "
            f"force_cpu_devices; force_cpu_devices({n}); import jax; "
            f"print('POOL', len(jax.devices()), "
            f"jax.devices()[0].platform)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=120)
    except subprocess.TimeoutExpired:
        return False, "no response in 120s"
    if proc.returncode != 0:
        return False, proc.stderr.strip()[-120:] or f"rc={proc.returncode}"
    # Last-line anchoring: a site plugin may print a banner first.
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("POOL")]
    got = lines[-1].split()[1:] if lines else []
    return got == [str(n), "cpu"], (f"{got[0]} virtual cpu devices"
                                    if got else "no probe output")


def probe_gloo() -> tuple[bool | None, str]:
    try:
        import jax

        impl = jax.config.jax_cpu_collectives_implementation
        return True, (f"cpu collectives impl available "
                      f"(current: {impl or 'default'})")
    except (ImportError, AttributeError) as e:
        return None, (f"cpu-collectives knob unavailable ({e}); "
                      f"multi-process CPU runs may not work")


def probe_tunnel_infra() -> tuple[bool | None, str]:
    """Relay-leg diagnosis for the axon tunnel (the round-4 root-cause
    method, ROUND4.md): TCP-connect the relay port and the session/
    stateless ports its redirects target.  A relay that accepts but
    serves nothing (with the session ports closed) is the half-dead
    infra wedge — unrecoverable client-side."""
    import socket

    relay = int(os.environ.get("AMT_AXON_RELAY_PORT", "2024"))
    state = {}
    for port in (relay, 8082, 8083):
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=3):
                state[port] = "open"
        except OSError:
            state[port] = "closed"
    detail = ", ".join(f"{p}:{s}" for p, s in state.items())
    if state[relay] == "closed":
        return None, f"relay port closed ({detail}) — no tunnel here"
    if state[8082] == "closed" and state[8083] == "closed":
        return None, (f"relay accepts but session ports are dead "
                      f"({detail}) — the half-dead-relay wedge; "
                      f"recovery is infra-side")
    return True, detail


def report_holders_and_registry() -> None:
    from arrow_matrix_tpu.utils.platform import (
        find_stale_plugin_holders,
        read_preemptible,
    )

    holders = find_stale_plugin_holders()
    _check("tunnel claim holders", True if not holders else None,
           f"{holders} hold relay connections" if holders
           else "none (no other process claims the chip)")
    reg = read_preemptible()
    _check("preemptible host jobs", True,
           f"{reg} registered" if reg else "none registered")


def probe_lint() -> tuple[bool, str]:
    """Run graft-lint (analysis/) over the installed package — a core
    check: a finding means a hot-path hazard (host sync, recompile,
    sharding mismatch) shipped past the gate."""
    try:
        import arrow_matrix_tpu
        from arrow_matrix_tpu.analysis import lint_paths

        pkg = os.path.dirname(os.path.abspath(arrow_matrix_tpu.__file__))
        findings, waived = lint_paths([pkg])
        if findings:
            worst = findings[0]
            return False, (f"{len(findings)} finding(s), e.g. "
                           f"{worst.format()[:100]}")
        return True, (f"clean ({len(waived)} waived) — "
                      f"run `python -m arrow_matrix_tpu.analysis` "
                      f"for details")
    except Exception as e:  # the doctor must never crash on a probe
        return False, f"{type(e).__name__}: {str(e)[:100]}"


def probe_prove() -> tuple[bool, str]:
    """graft-prove health: the H1-H3 checkers must trip on a planted
    surprise all-gather (in-process selftest, host-only), and the
    checked-in HLO contract manifest — when the working tree carries
    one — must record every contract proven.  The full prover
    (`python -m arrow_matrix_tpu.analysis prove`) compiles on a
    virtual mesh and is the lint_gate/--prove and tier-1 job, not a
    doctor probe."""
    try:
        from arrow_matrix_tpu.analysis import prove

        if not prove.selftest():
            return False, ("selftest failed: a planted surprise "
                           "all-gather did not trip H1-H3")
        mpath = prove.DEFAULT_MANIFEST
        if os.path.isfile(mpath):
            import json

            with open(mpath, encoding="utf-8") as fh:
                manifest = json.load(fh)
            if not manifest.get("ok"):
                return False, f"{mpath} records violated contracts"
            detail = (f"gate trips on planted surprises; {mpath}: "
                      f"{len(manifest.get('entries', ()))} entries ok")
        else:
            detail = ("gate trips on planted surprises; no checked-in "
                      "manifest here — run `python -m "
                      "arrow_matrix_tpu.analysis prove`")
        return True, detail
    except Exception as e:  # the doctor must never crash on a probe
        return False, f"{type(e).__name__}: {str(e)[:100]}"


def probe_sync() -> tuple[bool, str]:
    """graft-sync health: the RC1-RC5 analyzer must trip on its
    broken twins and the runtime witness must raise on an inverted
    acquisition order (in-process selftest, host-only); then one
    serve round trip runs in a bounded subprocess with
    AMT_LOCK_WITNESS=1 so every lock the request path takes is
    order-checked live.  The full static proof over the package is
    the lint_gate/--sync and tier-1 job, not a doctor probe."""
    try:
        from arrow_matrix_tpu.analysis import sync as graft_sync

        ok, lines = graft_sync.selftest()
        if not ok:
            bad = [ln for ln in lines if "fail" in ln.lower()]
            return False, ("selftest failed: "
                           + (bad[0] if bad else lines[-1]))[:140]
    except Exception as e:  # the doctor must never crash on a probe
        return False, f"{type(e).__name__}: {str(e)[:100]}"
    code = ("import sys, os, tempfile; sys.argv=[]; "
            "from arrow_matrix_tpu.utils.platform import "
            "force_cpu_devices; force_cpu_devices(1); "
            "from arrow_matrix_tpu import sync; "
            "assert sync.witness_registry() is not None, "
            "'witness did not arm from AMT_LOCK_WITNESS=1'; "
            "from arrow_matrix_tpu.serve import smoke_serve; "
            "d = tempfile.mkdtemp(prefix='sync_probe_'); "
            "s = smoke_serve(d, n=64, width=16, k=2, tenants=1, "
            "requests=1, iterations=1); "
            "reg = sync.witness_registry(); snap = reg.snapshot(); "
            "ok = (s['completed'] == 1 and s['failed'] == 0 and "
            "snap['acquisitions'] > 0 and not snap['violations']); "
            "print('SYNC ok ' + str(snap['acquisitions']) if ok "
            "else 'SYNC FAIL: ' + repr(snap))")
    env = dict(os.environ)
    env["AMT_LOCK_WITNESS"] = "1"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240, env=env)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("SYNC")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if not lines[-1].startswith("SYNC ok"):
        return False, lines[-1][:120]
    acq = lines[-1].rsplit(" ", 1)[-1]
    return True, (f"twins trip, witness-on serve round-trips "
                  f"({acq} order-checked acquisitions, 0 violations)")


def probe_kcert() -> tuple[bool, str]:
    """graft-kcert health: the KC1-KC5 certifier must trip on its
    broken selftest twins (in-process, host-only — no jax import);
    then ONE certified kernel runs a full interpret-mode round trip
    in a bounded subprocess (certify_entry replays the DMA-ring
    schedule, enumerates the grid, and executes the numeric witness:
    stream == vectorized bit-identity vs the f32 golden).  The full
    two-kernel manifest check is kernel_gate/--kernels, not a doctor
    probe."""
    try:
        from arrow_matrix_tpu.analysis import kernels as graft_kcert

        ok, lines = graft_kcert.selftest()
        if not ok:
            bad = [ln for ln in lines if "fail" in ln.lower()]
            return False, ("selftest failed: "
                           + (bad[0] if bad else lines[-1]))[:140]
    except Exception as e:  # the doctor must never crash on a probe
        return False, f"{type(e).__name__}: {str(e)[:100]}"
    code = ("import sys; sys.argv=[]; "
            "from arrow_matrix_tpu.utils.platform import "
            "force_cpu_devices; force_cpu_devices(1); "
            "from arrow_matrix_tpu.ops.kernel_contract import "
            "builtin_kernels; "
            "from arrow_matrix_tpu.analysis.kernels import "
            "certify_entry; "
            "e = [x for x in builtin_kernels() "
            "if x.name == 'sell_tier_spmm_packed'][0]; "
            "rec = certify_entry(e); "
            "print('KCERT ok ' + str(rec['points']) if rec['ok'] "
            "else 'KCERT FAIL: ' + '; '.join(rec['findings'])[:200])")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("KCERT")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if not lines[-1].startswith("KCERT ok"):
        return False, lines[-1][:120]
    pts = lines[-1].rsplit(" ", 1)[-1]
    return True, (f"twins trip, certified interpret round trip "
                  f"({pts} grid/BlockSpec points, witness passed)")


def probe_obs() -> tuple[bool, str]:
    """graft-scope round-trip: the obs layer imports and a minimal
    smoke trace (one algorithm, 2 devices) produces a valid run
    directory — trace JSON, metrics.jsonl, summary.json.  Bounded
    subprocess: the probe must not inherit this process's backend
    state, and a wedged build must not hang the doctor."""
    code = ("import sys, tempfile; sys.argv=[]; "
            "from arrow_matrix_tpu.utils.platform import "
            "force_cpu_devices; force_cpu_devices(2); "
            "from arrow_matrix_tpu.obs.smoke import run_smoke, "
            "validate_run_dir; d = tempfile.mkdtemp(prefix='obs_probe_'); "
            "run_smoke(d, n=64, width=16, k=2, n_dev=2, iters=1, "
            "algorithms=('spmm_1d',)); p = validate_run_dir(d, "
            "algorithms=('spmm_1d',)); "
            "print('OBS ok' if not p else 'OBS FAIL: ' + p[0])")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("OBS")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "OBS ok":
        return False, lines[-1][:120]
    return True, ("smoke trace round-trips — run "
                  "`python -m arrow_matrix_tpu.obs smoke <dir>` for "
                  "the full five-algorithm run")


def probe_serve() -> tuple[bool, str]:
    """graft-serve round-trip: the serving runtime starts, admits and
    completes one request on the host-CPU backend, and shuts down
    cleanly with a valid SLO summary.  Bounded subprocess for the same
    reasons as the OBS probe: no backend-state inheritance, and a
    wedged build must not hang the doctor."""
    code = ("import sys, tempfile; sys.argv=[]; "
            "from arrow_matrix_tpu.utils.platform import "
            "force_cpu_devices; force_cpu_devices(1); "
            "from arrow_matrix_tpu.serve import smoke_serve; "
            "d = tempfile.mkdtemp(prefix='serve_probe_'); "
            "s = smoke_serve(d, n=64, width=16, k=2, tenants=1, "
            "requests=1, iterations=1); "
            "lat = s['latency_ms']; "
            "ok = (s['completed'] == 1 and s['failed'] == 0 and "
            "lat['p50'] is not None and lat['p99'] is not None and "
            "s['hbm']['budget_bytes'] > 0); "
            "print('SERVE ok' if ok else 'SERVE FAIL: ' + repr(s))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("SERVE")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "SERVE ok":
        return False, lines[-1][:120]
    return True, ("one-request serve round-trips — run `graft_serve` "
                  "for the full multi-tenant load")


def probe_pulse() -> tuple[bool, str]:
    """graft-pulse round-trip: serve a two-request trace with a
    PulseMonitor attached, start the stdlib scrape endpoint on an
    ephemeral port, scrape /metrics and /pulse.json once, and validate
    both against the pulse schema.  Bounded subprocess, as for the OBS
    and SERVE probes."""
    code = (
        "import sys, json, urllib.request; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "from arrow_matrix_tpu.obs import pulse; "
        "from arrow_matrix_tpu.serve import ArrowServer, ExecConfig, "
        "ba_executor_factory, run_trace, synthetic_trace; "
        "fac, n = ba_executor_factory(64, 16, 3, fmt='fold'); "
        "mon = pulse.PulseMonitor(window_s=0.05, "
        "watchdog=pulse.SloWatchdog()); "
        "srv = ArrowServer(fac, ExecConfig(), name='pulse-probe'); "
        "srv.attach_pulse(mon); "
        "run_trace(srv, synthetic_trace(n, tenants=1, requests=2, "
        "k=2, iterations=1, seed=3)); mon.close(); "
        "ep = pulse.PulseEndpoint(mon); ep.start(); "
        "text = urllib.request.urlopen(ep.url + '/metrics', "
        "timeout=10).read().decode(); "
        "snap = json.loads(urllib.request.urlopen(ep.url + "
        "'/pulse.json', timeout=10).read().decode()); "
        "p = pulse.validate_exposition(text) + "
        "pulse.validate_ring(snap); ep.stop(); "
        "p += [] if snap['totals']['completed'] == 2 else "
        "['completed != 2']; "
        "print('PULSE ok' if not p else 'PULSE FAIL: ' + p[0])")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("PULSE")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "PULSE ok":
        return False, lines[-1][:120]
    return True, ("endpoint scrape + ring schema round-trip — run "
                  "`graft_serve --pulse` for the live series")


def probe_classes() -> tuple[bool, str]:
    """graft-classes round-trip: probe a bf16 error curve on a tiny BA
    structure, derive the certificate, and serve one approx request
    beside one exact request against it — the approx ticket must be
    served approx with a certified bound and a smaller admission price
    than the exact ticket at the same k.  Bounded subprocess, as for
    the SERVE probe."""
    code = (
        "import sys, dataclasses; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "from arrow_matrix_tpu.classes import certificate_from_record; "
        "from arrow_matrix_tpu.ledger.probe import "
        "error_curves_for_source; "
        "from arrow_matrix_tpu.serve import ArrowServer, ExecConfig, "
        "ba_executor_factory, run_trace, synthetic_trace; "
        "src = {'kind': 'ba', 'n': 64, 'm': 3, 'width': 16, "
        "'seed': 3}; "
        "recs = error_curves_for_source(src, k=2, iterations=2, "
        "seed=3, dtypes=('bf16',)); "
        "cert = certificate_from_record(recs[0]); "
        "fac, n = ba_executor_factory(64, 16, 3, fmt='fold'); "
        "srv = ArrowServer(fac, ExecConfig(), name='class-probe', "
        "certificates=[cert]); "
        "trace = [dataclasses.replace(r, traffic_class=c) for r, c "
        "in zip(synthetic_trace(n, tenants=1, requests=2, k=2, "
        "iterations=2, seed=3), ('approx', 'exact'))]; "
        "a, e = run_trace(srv, trace); "
        "ok = (cert is not None and cert.covers(2) and "
        "a.status == 'completed' and a.served_class == 'approx' and "
        "a.certified_bound is not None and "
        "a.predicted_bytes < e.predicted_bytes and "
        "e.status == 'completed' and e.served_class == 'exact'); "
        "print('CLASS ok' if ok else 'CLASS FAIL: ' + "
        "repr((a.summary(), e.summary())))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("CLASS")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "CLASS ok":
        return False, lines[-1][:120]
    return True, ("bf16 certificate + approx round trip, priced "
                  "below exact — run `graft_ledger probe` for full "
                  "error curves")


def probe_tune() -> tuple[bool, str]:
    """graft-tune round-trip: one tiny smoke search races its
    subprocess children and persists a plan, and an immediate second
    search of the unchanged structure is a pure cache hit with ZERO
    children spawned — the acceptance property tools/tune_gate.py
    enforces.  Bounded subprocess, as for the SERVE and PULSE probes
    (force_cpu_devices sets env vars, so the tune children inherit
    the CPU pinning)."""
    code = ("import sys, tempfile; sys.argv=[]; "
            "from arrow_matrix_tpu.utils.platform import "
            "force_cpu_devices; force_cpu_devices(1); "
            "from arrow_matrix_tpu.tune import smoke_tune; "
            "d = tempfile.mkdtemp(prefix='tune_probe_'); "
            "r1 = smoke_tune(d); r2 = smoke_tune(d); "
            "ok = (r1['ok'] and not r1['cache_hit'] and "
            "r1['children_spawned'] > 0 and r2['ok'] and "
            "r2['cache_hit'] and r2['children_spawned'] == 0); "
            "print('TUNE ok' if ok else 'TUNE FAIL: ' + "
            "repr({'r1': {kk: r1.get(kk) for kk in ('ok', 'cache_hit', "
            "'children_spawned', 'error')}, 'r2': {kk: r2.get(kk) "
            "for kk in ('ok', 'cache_hit', 'children_spawned')}}))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("TUNE")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "TUNE ok":
        return False, lines[-1][:120]
    return True, ("smoke search + pure cache hit round-trips — run "
                  "`graft_tune search` for a real structure")


def probe_ledger() -> tuple[bool, str]:
    """graft-ledger round-trip: append a record to a throwaway store
    and validate schema + hash chain; then, when the committed fixture
    store is present (tests/fixtures/ledger), run the drift gate
    against its baseline (must be green) AND verify a planted 10×
    regression trips it (the gate must not be green merely because it
    checks nothing).  Bounded subprocess, as for the other probes."""
    code = (
        "import sys, os, tempfile, json; sys.argv=[]; "
        "d = tempfile.mkdtemp(prefix='ledger_probe_'); "
        "from arrow_matrix_tpu.ledger import Ledger, "
        "canonical_record_id, schema_problems; "
        "from arrow_matrix_tpu.ledger import gate; "
        "lg = Ledger(d); "
        "r = lg.record('probe', 'doctor_probe_ms', 1.0, unit='ms', "
        "host_load=0.0, git_rev=None); "
        "p = schema_problems(r) + lg.validate(); "
        "fix = os.path.join('tests', 'fixtures', 'ledger'); "
        "bp = os.path.join(fix, 'baseline.json'); "
        "note = 'no committed fixture store — in-memory checks only'; "
        "fr = []; "
        "\n"
        "if os.path.isfile(bp):\n"
        "    flg = Ledger(fix); fr = flg.read_all()\n"
        "    base = gate.load_baseline(bp)\n"
        "    f, _ = gate.check_records(fr, base)\n"
        "    p += flg.validate() + f\n"
        "    banded = [x for x in fr if x.get('unit') in ('ms', 's') "
        "and isinstance(x.get('value'), (int, float))]\n"
        "    if banded:\n"
        "        bad = json.loads(json.dumps(banded[0]))\n"
        "        bad['value'] = bad['value'] * 10\n"
        "        bad['record_id'] = canonical_record_id(bad)\n"
        "        f2, _ = gate.check_records([bad], base)\n"
        "        if not f2:\n"
        "            p.append('planted 10x regression did not trip')\n"
        "    note = 'gate green on committed fixture; planted "
        "regression trips'\n"
        "print('LEDGER ok: ' + note if not p "
        "else 'LEDGER FAIL: ' + str(p[0]))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("LEDGER")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if not lines[-1].startswith("LEDGER ok"):
        return False, lines[-1][:120]
    return True, lines[-1][len("LEDGER ok: "):][:120]


def probe_fleet() -> tuple[bool, str]:
    """graft-fleet round-trip: spawn a 2-worker process fleet, route
    one request to each worker, SIGKILL one, and require the router to
    requeue a request aimed at the dead worker onto the survivor — the
    kill-one-worker-of-N contract in miniature (tools/fleet_gate.py
    runs the full 3-worker mid-batch version).  Bounded subprocess, as
    for the other probes."""
    code = (
        "import sys, tempfile; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "import numpy as np; "
        "from arrow_matrix_tpu.fleet.router import FleetRouter; "
        "from arrow_matrix_tpu.serve.request import Request; "
        "d = tempfile.mkdtemp(prefix='fleet_probe_'); "
        "r = FleetRouter(spawn=2, vertices=64, width=16, seed=3, "
        "run_dir=d); p = []; "
        "\n"
        "try:\n"
        "    x = np.ones((r.n_rows, 2), dtype=np.float32)\n"
        "    wids = sorted(r.workers)\n"
        "    ten = {}\n"
        "    i = 0\n"
        "    while len(ten) < 2 and i < 256:\n"
        "        ten.setdefault(r.ring.lookup(f't{i}'), f't{i}')\n"
        "        i += 1\n"
        "    t1 = r.submit(Request('p0', ten[wids[0]], x, 1))\n"
        "    t2 = r.submit(Request('p1', ten[wids[1]], x, 1))\n"
        "    r.drain(timeout_s=120)\n"
        "    if not (t1.status == t2.status == 'completed'):\n"
        "        p.append('one-request-per-worker warmup failed: '\n"
        "                 + repr((t1.status, t2.status)))\n"
        "    victim = wids[0]\n"
        "    r.kill_worker(victim)\n"
        "    t3 = r.submit(Request('p2', ten[victim], x, 1))\n"
        "    r.drain(timeout_s=120)\n"
        "    if t3.status != 'completed':\n"
        "        p.append('requeued request did not complete: '\n"
        "                 + repr((t3.status, t3.reason, t3.error)))\n"
        "    elif getattr(t3, 'requeues', 0) < 1:\n"
        "        p.append('dead-worker request was not requeued')\n"
        "    elif getattr(t3, 'worker_id', None) == victim:\n"
        "        p.append('request credited to the dead worker')\n"
        "finally:\n"
        "    r.shutdown()\n"
        "print('FLEET ok' if not p else 'FLEET FAIL: ' + str(p[0]))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("FLEET")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "FLEET ok":
        return False, lines[-1][:120]
    return True, ("2-worker fleet survives a kill with requeue — run "
                  "`graft_fleet` / tools/fleet_gate.py for the full "
                  "matrix")


def probe_host() -> tuple[bool, str]:
    """graft-host round-trip: spawn a 2-worker fleet split into two
    host fault domains, aim a checkpointing request at the host-1
    domain, wait for its first COMPLETE checkpoint, SIGKILL the whole
    domain, and require the host-0 survivor to requeue AND resume the
    request from the shared checkpoint rather than recompute — the
    kill-a-host contract in miniature (tools/fleet_gate.py runs the
    full 2x2 mid-batch version with bit-identity and wire-ledger
    checks).  Bounded subprocess, as for the other probes."""
    code = (
        "import os, sys, tempfile, time; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "import numpy as np; "
        "from arrow_matrix_tpu.fleet.router import FleetRouter; "
        "from arrow_matrix_tpu.serve.request import Request; "
        "d = tempfile.mkdtemp(prefix='host_probe_'); "
        "ck = os.path.join(d, 'ck'); "
        "r = FleetRouter(spawn=2, hosts=2, vertices=64, width=16, "
        "seed=3, run_dir=d, checkpoint_dir=ck, checkpoint_every=1); "
        "p = []; "
        "\n"
        "try:\n"
        "    hm = r.host_map()\n"
        "    if sorted(hm) != ['host-0', 'host-1']:\n"
        "        p.append('bad host map: ' + repr(hm))\n"
        "    doomed = set(hm.get('host-1') or ())\n"
        "    x = np.ones((r.n_rows, 2), dtype=np.float32)\n"
        "    ten = None\n"
        "    i = 0\n"
        "    while ten is None and i < 256:\n"
        "        if r.ring.lookup('t%d' % i) in doomed:\n"
        "            ten = 't%d' % i\n"
        "        i += 1\n"
        "    t = r.submit(Request('h0', ten, x, 32))\n"
        "    deadline = time.monotonic() + 60\n"
        "    while time.monotonic() < deadline:\n"
        "        if os.path.exists(os.path.join(ck, 'ck_h0')):\n"
        "            break\n"
        "        time.sleep(0.005)\n"
        "    else:\n"
        "        p.append('no checkpoint appeared before the kill')\n"
        "    r.kill_host('host-1')\n"
        "    r.drain(timeout_s=120)\n"
        "    if t.status != 'completed':\n"
        "        p.append('request lost with the host: '\n"
        "                 + repr((t.status, t.reason, t.error)))\n"
        "    elif getattr(t, 'requeues', 0) < 1:\n"
        "        p.append('dead-domain request was not requeued')\n"
        "    elif getattr(t, 'worker_id', None) in doomed:\n"
        "        p.append('request credited to the dead domain')\n"
        "    logs = ''\n"
        "    for h in r.workers.values():\n"
        "        if h.worker_id in doomed:\n"
        "            continue\n"
        "        try:\n"
        "            logs += open(h.log_path).read()\n"
        "        except OSError:\n"
        "            pass\n"
        "    if not p and 'resumed request' not in logs:\n"
        "        p.append('survivor recomputed instead of resuming')\n"
        "    if not p and r.live_hosts() != ['host-0']:\n"
        "        p.append('dead domain not buried: '\n"
        "                 + repr(r.live_hosts()))\n"
        "finally:\n"
        "    r.shutdown()\n"
        "print('HOST ok' if not p else 'HOST FAIL: ' + str(p[0]))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("HOST")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "HOST ok":
        return False, lines[-1][:120]
    return True, ("kill-a-host domain survived with resume — run "
                  "tools/fleet_gate.py for quorum + bit-identity")


def probe_reshard() -> tuple[bool, str]:
    """graft-reshard round-trip: seed one mid-flight checkpoint on a
    2-device layout, grow the server onto 4 devices (the checkpoint
    replayed through a staged redistribution plan), and require the
    request to resume from the migrated checkpoint and complete — the
    kill-mid-migration contract in miniature, minus the kill
    (tools/reshard_gate.py runs the full armed version).  Bounded
    subprocess, as for the other probes."""
    code = (
        "import os, sys, tempfile; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(4); "
        "import jax; import numpy as np; "
        "from arrow_matrix_tpu.parallel.mesh import make_mesh; "
        "from arrow_matrix_tpu.serve.loadgen import "
        "ba_executor_factory, synthetic_trace; "
        "from arrow_matrix_tpu.serve.scheduler import "
        "ArrowServer, ExecConfig; "
        "from arrow_matrix_tpu.utils.checkpoint import save_state; "
        "\n"
        "d = tempfile.mkdtemp(prefix='reshard_probe_')\n"
        "devs = jax.devices()\n"
        "m2 = make_mesh((2,), ('blocks',), devices=np.asarray(devs[:2]))\n"
        "m4 = make_mesh((4,), ('blocks',), devices=np.asarray(devs))\n"
        "fac2, n_rows = ba_executor_factory(96, 16, 3, fmt='auto', "
        "mesh=m2)\n"
        "fac4, _ = ba_executor_factory(96, 16, 3, fmt='auto', mesh=m4)\n"
        "req = synthetic_trace(n_rows, tenants=1, requests=1, k=2, "
        "iterations=2, seed=7)[0]\n"
        "ex2 = fac2(ExecConfig())\n"
        "x = ex2.step(ex2.set_features(req.x))\n"
        "save_state(os.path.join(d, 'ck_' + req.request_id), "
        "np.asarray(x), 1, layout='serve/' + req.request_id "
        "+ '/k2/it2')\n"
        "srv = ArrowServer(fac2, ExecConfig(), name='probe', "
        "checkpoint_dir=d, checkpoint_every=1, max_batch_k=0, "
        "grow_factory=fac4, reshard_budget_bytes=1024)\n"
        "p = []\n"
        "if not srv.grow(reason='probe'):\n"
        "    p.append('grow refused')\n"
        "elif srv.checkpoints_resharded != 1:\n"
        "    p.append('expected 1 resharded checkpoint, got '\n"
        "             + str(srv.checkpoints_resharded))\n"
        "t = srv.submit(req)\n"
        "srv.drain()\n"
        "if t.result is None:\n"
        "    p.append('migrated request did not complete: '\n"
        "             + repr((t.status, t.error)))\n"
        "elif t.resumed_step != 1:\n"
        "    p.append('request recomputed instead of resuming the '\n"
        "             'migrated checkpoint (resumed_step='\n"
        "             + repr(t.resumed_step) + ')')\n"
        "print('RESHARD ok' if not p else 'RESHARD FAIL: ' + str(p[0]))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("RESHARD")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "RESHARD ok":
        return False, lines[-1][:120]
    return True, ("2-dev -> 4-dev grow migrated a live checkpoint "
                  "through a staged plan and resumed it — "
                  "tools/reshard_gate.py runs the armed version")


def probe_xray() -> tuple[bool, str]:
    """graft-xray round-trip: spawn a 2-worker process fleet, route
    one request to each worker, merge the run dir into ONE fleet
    trace, and require closed span trees (each request id on the
    router track AND a worker track), a measured clock offset per
    worker that is sane for one host, and zero truncated tracks —
    the tracing loop in miniature (the SIGKILL-recovery half is
    tools/chaos_gate.py:scenario_xray_kill).  Bounded subprocess, as
    for the other probes."""
    code = (
        "import sys, tempfile; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "import numpy as np; "
        "from arrow_matrix_tpu.fleet.router import FleetRouter; "
        "from arrow_matrix_tpu.obs import xray; "
        "from arrow_matrix_tpu.serve.request import Request; "
        "d = tempfile.mkdtemp(prefix='xray_probe_'); "
        "r = FleetRouter(spawn=2, vertices=64, width=16, seed=3, "
        "run_dir=d); p = []; "
        "\n"
        "try:\n"
        "    x = np.ones((r.n_rows, 2), dtype=np.float32)\n"
        "    wids = sorted(r.workers)\n"
        "    ten = {}\n"
        "    i = 0\n"
        "    while len(ten) < 2 and i < 256:\n"
        "        ten.setdefault(r.ring.lookup(f't{i}'), f't{i}')\n"
        "        i += 1\n"
        "    ts = [r.submit(Request(f'p{j}', ten[w], x, 1))\n"
        "          for j, w in enumerate(wids)]\n"
        "    r.drain(timeout_s=120)\n"
        "    if not all(t.status == 'completed' for t in ts):\n"
        "        p.append('fleet warmup failed: '\n"
        "                 + repr([t.status for t in ts]))\n"
        "    report = r.fleet_summary()\n"
        "    xray.save_router_trace(r.tracer, d)\n"
        "finally:\n"
        "    r.shutdown()\n"
        "doc = xray.merge_run_dir(d, report=report)\n"
        "info = doc['xray']\n"
        "if len(info['processes']) != 3:\n"
        "    p.append('expected 3 tracks, got '\n"
        "             + repr([q['process'] for q in "
        "info['processes']]))\n"
        "if info['truncated']:\n"
        "    p.append('graceful run left truncated tracks: '\n"
        "             + repr(info['truncated']))\n"
        "offs = report.get('clock_offsets_ns') or {}\n"
        "for w in wids:\n"
        "    rec = offs.get(w)\n"
        "    if not isinstance(rec, dict):\n"
        "        p.append('no clock offset for ' + w)\n"
        "    elif abs(rec.get('offset_ns', 0)) > 1e9:\n"
        "        p.append('implausible same-host offset: ' + repr(rec))\n"
        "pid_of = {q['process']: q['pid'] for q in info['processes']}\n"
        "evs = [e for e in doc['traceEvents'] if e.get('ph') == 'X']\n"
        "for t in ts:\n"
        "    rid = t.request.request_id\n"
        "    pids = {e['pid'] for e in evs if rid in\n"
        "            str(e['args'].get('request_id', '')).split('+')}\n"
        "    if pid_of['router'] not in pids or len(pids) < 2:\n"
        "        p.append(rid + ' span tree not closed across the '\n"
        "                 'wire (pids=' + repr(sorted(pids)) + ')')\n"
        "        break\n"
        "print('XRAY ok' if not p else 'XRAY FAIL: ' + str(p[0]))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("XRAY")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if lines[-1] != "XRAY ok":
        return False, lines[-1][:120]
    return True, ("2-worker fleet merged into one closed-span trace "
                  "with sane clock offsets — run `graft_xray report` "
                  "on any fleet run dir")


def probe_lens() -> tuple[bool, str]:
    """graft-lens round trip: profile a small BA fold level-by-level
    with the prefix-difference harness, fit the structure-conditioned
    cost model from the static counters, and predict the iteration
    back — the calibration loop in miniature.  At this smoke scale
    the tight bands the tier-1 gate enforces on the committed
    ba_256_3 point do not hold (tier times are microseconds), so the
    probe checks the round trip is structurally sound and the
    prediction lands in a loose sanity band.  Bounded subprocess, as
    for the other probes."""
    code = (
        "import sys; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "from arrow_matrix_tpu.obs import lens; "
        "from arrow_matrix_tpu.obs.costmodel import CostModel; "
        "from arrow_matrix_tpu.tune.search import "
        "load_levels_from_source; "
        "p = []; "
        "\n"
        "levels, width = load_levels_from_source(\n"
        "    {'kind': 'ba', 'n': 96, 'm': 3, 'width': 16,\n"
        "     'seed': 5, 'max_levels': 6})\n"
        "prof = lens.profile_fold(levels, width, 8, kernel='xla',\n"
        "                         feature_dtypes=('f32',), iters=20)\n"
        "ent = prof['dtypes'].get('f32') or {}\n"
        "if not ent.get('full_ms', 0.0) > 0.0:\n"
        "    p.append('no positive full-step time measured')\n"
        "tiers = ent.get('tiers') or []\n"
        "if not tiers:\n"
        "    p.append('profile attributed no tiers')\n"
        "for t in tiers:\n"
        "    for key in ('family', 'nnz', 'rows', 'streamed_bytes'):\n"
        "        if key not in t:\n"
        "            p.append('tier missing counter ' + key)\n"
        "            break\n"
        "model = lens.fit_from_profile(prof)\n"
        "if not p and not model.coeffs:\n"
        "    p.append('fit produced no per-family coefficients')\n"
        "if not p:\n"
        "    pred = lens.predict_profile_iter_ms(prof, model, 'f32')\n"
        "    full = ent['full_ms']\n"
        "    if not pred > 0.0:\n"
        "        p.append('non-positive prediction ' + repr(pred))\n"
        "    elif not 0.02 <= pred / full <= 50.0:\n"
        "        p.append('prediction insane: ' + repr(pred)\n"
        "                 + ' ms vs measured ' + repr(full) + ' ms')\n"
        "    m2 = CostModel.from_dict(model.to_dict())\n"
        "    if m2.to_dict() != model.to_dict():\n"
        "        p.append('cost model dict round trip not lossless')\n"
        "print('LENS ok' if not p else 'LENS FAIL: ' + str(p[0]))")
    # At this micro scale a host-load spike can push every tier under
    # the resolution floor (fit has no coefficients) — retry once so a
    # transient spike doesn't read as a broken calibration loop; a
    # genuinely broken fit fails both attempts.
    verdict = ""
    for _ in range(2):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=240)
        except subprocess.TimeoutExpired:
            return False, "no response in 240s"
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("LENS")]
        if proc.returncode != 0 or not lines:
            return False, (proc.stderr.strip()[-120:]
                           or f"rc={proc.returncode}, no probe output")
        verdict = lines[-1]
        if verdict == "LENS ok":
            return True, ("per-level profile -> cost-model fit -> "
                          "prediction round trip is sane — "
                          "tools/lens_gate.py checks the committed "
                          "calibration")
    return False, verdict[:120]


def probe_synth() -> tuple[bool, str]:
    """graft-synth round trip: fingerprint a tiny BA ladder,
    synthesize the per-level schedule, certify it KC1-KC5 in
    interpret mode, persist the generated program to a throwaway
    store, and re-register + re-certify it from the store record —
    the structure-JIT loop in miniature (the raced, committed version
    is `graft_tune search --synth`; tools/kernel_gate.py checks the
    committed store).  Bounded subprocess, as for the other probes."""
    code = (
        "import sys, tempfile, os; sys.argv=[]; "
        "from arrow_matrix_tpu.utils.platform import "
        "force_cpu_devices; force_cpu_devices(1); "
        "import numpy as np; "
        "from arrow_matrix_tpu.analysis.kernels import "
        "certify_candidate_opts, certify_entry; "
        "from arrow_matrix_tpu.ops.kernel_contract import "
        "unregister_kernel; "
        "from arrow_matrix_tpu.tune import synth; "
        "from arrow_matrix_tpu.tune.fingerprint import "
        "structure_fingerprint, fingerprint_hash; "
        "from arrow_matrix_tpu.tune.search import "
        "load_levels_from_source; "
        "p = []; "
        "\n"
        "levels, width = load_levels_from_source(\n"
        "    {'kind': 'ba', 'n': 96, 'm': 3, 'width': 16,\n"
        "     'seed': 5, 'max_levels': 6})\n"
        "fp = structure_fingerprint(levels, width, np.float32)\n"
        "sched = synth.synthesize_schedule(fp)\n"
        "if not sched:\n"
        "    p.append('synthesized an empty schedule for a live ladder')\n"
        "why = certify_candidate_opts({'schedule': sched}, 16,\n"
        "                             interpret=True)\n"
        "if why is not None:\n"
        "    p.append('schedule did not certify: ' + why)\n"
        "store = os.path.join(tempfile.mkdtemp(prefix='synth_probe_'),\n"
        "                     'store.json')\n"
        "name = synth.persist_program(fp, fingerprint_hash(fp), 16,\n"
        "                             sched, path=store)\n"
        "try:\n"
        "    if name not in synth.register_persisted_programs(store):\n"
        "        p.append('store round trip lost program ' + name)\n"
        "    prog = synth.load_store(store)['programs'][name]\n"
        "    rec = certify_entry(synth.entry_from_program(name, prog))\n"
        "    if not rec['ok']:\n"
        "        p.append('stored program failed certification: '\n"
        "                 + '; '.join(rec['findings'])[:140])\n"
        "finally:\n"
        "    unregister_kernel(name)\n"
        "print('SYNTH ok ' + str(len(sched)) if not p\n"
        "      else 'SYNTH FAIL: ' + str(p[0]))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=240)
    except subprocess.TimeoutExpired:
        return False, "no response in 240s"
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("SYNTH")]
    if proc.returncode != 0 or not lines:
        return False, (proc.stderr.strip()[-120:]
                       or f"rc={proc.returncode}, no probe output")
    if not lines[-1].startswith("SYNTH ok"):
        return False, lines[-1][:120]
    tiers = lines[-1].rsplit(" ", 1)[-1]
    return True, (f"{tiers}-tier schedule synthesized, certified, and "
                  f"store round-tripped — `graft_tune search --synth` "
                  f"races it for real")


def probe_native() -> tuple[bool | None, str]:
    try:
        from arrow_matrix_tpu.decomposition import native

        if not native.available():
            err = native.load_error()
            return None, ("C++ decomposer unavailable"
                          + (f" ({err})" if err else "")
                          + " — the numpy backend will be used")
        return True, "C++ decomposer built and loadable"
    except Exception as e:
        return None, f"{type(e).__name__}: {str(e)[:100]}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--probe-timeout", type=float, default=90.0,
                    help="seconds to wait for the accelerator probe")
    ap.add_argument("--devices", type=int, default=4,
                    help="virtual CPU pool size to verify")
    args = ap.parse_args(argv)

    ok = True
    print("arrow-matrix-tpu doctor\n")

    import importlib

    for mod in ("jax", "flax", "optax", "scipy", "numpy"):
        try:
            m = importlib.import_module(mod)
            _check(f"import {mod}", True,
                   getattr(m, "__version__", "?"))
        except ImportError as e:
            ok &= _check(f"import {mod}", False, str(e)[:100])

    acc_ok, detail = probe_accelerator(args.probe_timeout)
    _check("accelerator (default backend, bounded probe)",
           True if acc_ok else None, detail)

    t, detail = probe_tunnel_infra()
    _check("tunnel relay/session ports", t, detail)
    report_holders_and_registry()

    good, detail = probe_cpu_pool(args.devices)
    ok &= _check(f"virtual CPU pool ({args.devices} devices)", good,
                 detail)

    g, detail = probe_gloo()
    _check("multi-process collectives", g, detail)

    n, detail = probe_native()
    _check("native decomposer", n, detail)

    lint_ok, detail = probe_lint()
    ok &= _check("graft-lint (static analysis, R1-R9)", lint_ok, detail)

    prove_ok, detail = probe_prove()
    ok &= _check("graft-prove (HLO collective contracts, H1-H7)",
                 prove_ok, detail)

    sync_ok, detail = probe_sync()
    ok &= _check("graft-sync (lock discipline RC1-RC5 + witness)",
                 sync_ok, detail)

    kcert_ok, detail = probe_kcert()
    ok &= _check("graft-kcert (Pallas kernel certifier KC1-KC5)",
                 kcert_ok, detail)

    obs_ok, detail = probe_obs()
    ok &= _check("graft-scope (obs smoke trace)", obs_ok, detail)

    serve_ok, detail = probe_serve()
    ok &= _check("graft-serve (one-request round trip)", serve_ok,
                 detail)

    pulse_ok, detail = probe_pulse()
    ok &= _check("graft-pulse (endpoint scrape + schema)", pulse_ok,
                 detail)

    class_ok, detail = probe_classes()
    ok &= _check("graft-classes (certificate + approx round trip)",
                 class_ok, detail)

    tune_ok, detail = probe_tune()
    ok &= _check("graft-tune (smoke search + cache hit)", tune_ok,
                 detail)

    ledger_ok, detail = probe_ledger()
    ok &= _check("graft-ledger (record + chain + drift gate)",
                 ledger_ok, detail)

    fleet_ok, detail = probe_fleet()
    ok &= _check("graft-fleet (kill one of 2 workers + requeue)",
                 fleet_ok, detail)

    host_ok, detail = probe_host()
    ok &= _check("graft-host (kill a host domain + resume)",
                 host_ok, detail)

    reshard_ok, detail = probe_reshard()
    ok &= _check("graft-reshard (grow-migration round trip)",
                 reshard_ok, detail)

    xray_ok, detail = probe_xray()
    ok &= _check("graft-xray (merged fleet trace + clock offsets)",
                 xray_ok, detail)

    lens_ok, detail = probe_lens()
    ok &= _check("graft-lens (profile -> fit -> predict round trip)",
                 lens_ok, detail)

    synth_ok, detail = probe_synth()
    ok &= _check("graft-synth (schedule synth + certify + store)",
                 synth_ok, detail)

    cache = "bench_cache"
    if os.path.isdir(cache):
        done = [f for f in os.listdir(cache) if f.endswith(".complete")]
        _check("bench decomposition caches", True if done else None,
               f"{len(done)} cached" if done
               else "none (first bench run decomposes from scratch)")
    else:
        _check("bench decomposition caches", None,
               "no bench_cache/ (first bench run decomposes from "
               "scratch)")

    print()
    print("core checks passed" if ok else "CORE CHECKS FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
