"""``graft_ledger`` — the operator surface of the graft-ledger store.

Subcommands:

* ``report`` — summarize the store per (kind, metric, structure,
  platform) key: count, median, MAD, newest value, host-load context.
  The provenance command PERFORMANCE.md tables cite.
* ``diff`` — compare the newest record of every key against the
  committed baseline (the same math as the gate, presented as a table
  instead of an exit code).
* ``curve`` — print error-vs-iteration curves (``kind=error_curve``)
  as aligned columns, one row per iteration.
* ``export`` — regenerate a legacy ``BENCH_r*.json`` round document
  from the store (``--round N``), so the bench trajectory continues in
  the old vocabulary without a hand-written file.
* ``ingest`` — load committed history INTO the store: legacy
  ``BENCH_r*.json`` rounds and/or a tune plan-cache directory.
* ``probe`` — run the ErrorProbe (error-vs-iteration vs the f32
  golden) on a structure and append the curves.
* ``check`` / ``rebaseline`` — delegate to the drift gate
  (``tools/ledger_gate.py`` engine).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_ledger", description=__doc__.splitlines()[0])
    p.add_argument("--ledger-dir", default=None,
                   help="store directory (default: AMT_LEDGER_DIR or "
                        "bench_results/ledger)")
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("report", help="per-key summary of the store")
    r.add_argument("--kind", default=None)
    r.add_argument("--metric", default=None)
    r.add_argument("--structure", default=None,
                   help="filter by structure hash")
    r.add_argument("--json", action="store_true")

    d = sub.add_parser("diff", help="newest records vs the baseline")
    d.add_argument("--baseline", default=None)

    c = sub.add_parser("curve", help="print error-vs-iteration curves")
    c.add_argument("--structure", default=None)
    c.add_argument("--dtype", default=None,
                   help="f32 / bf16 / int8 (default: all)")

    e = sub.add_parser("export", help="regenerate a legacy "
                                      "BENCH_r*.json round from the "
                                      "store")
    e.add_argument("--round", type=int, required=True)
    e.add_argument("--out", default=None,
                   help="output path (default BENCH_r0<N>.json)")
    e.add_argument("--upto", default=None, metavar="RECORD_ID",
                   help="pin the export to the chain prefix ending at "
                        "this record id (default: an existing round "
                        "file's recorded parsed.ledger.head, else the "
                        "whole store)")

    i = sub.add_parser("ingest", help="load committed history into "
                                      "the store")
    i.add_argument("--bench", nargs="*", default=None,
                   help="legacy BENCH_r*.json files")
    i.add_argument("--plans", default=None,
                   help="tune plan-cache directory")

    pr = sub.add_parser("probe", help="append error-vs-iteration "
                                      "curves for a structure")
    pr.add_argument("--ba", type=str, default=None,
                    help="Barabasi-Albert source: N,WIDTH,SEED")
    pr.add_argument("--ba_m", type=int, default=3)
    pr.add_argument("--max_levels", type=int, default=10)
    pr.add_argument("--base", type=str, default=None,
                    help="committed graphio artifact directory")
    pr.add_argument("--width", type=int, default=None)
    pr.add_argument("--k", type=int, default=4)
    pr.add_argument("--iterations", type=int, default=8)
    pr.add_argument("--seed", type=int, default=3)
    pr.add_argument("--dtypes", type=str, default="f32,bf16",
                    help="comma list of f32/bf16/int8")

    g = sub.add_parser("check", help="drift gate (nonzero exit on "
                                     "regression/schema drift)")
    g.add_argument("--baseline", default=None)

    b = sub.add_parser("rebaseline", help="rebuild the baseline from "
                                          "the store")
    b.add_argument("--baseline", default=None)
    return p


def _cmd_report(args) -> int:
    from arrow_matrix_tpu.ledger import Ledger
    from arrow_matrix_tpu.ledger.gate import baseline_key, build_baseline

    lg = Ledger(args.ledger_dir)
    recs = lg.query(kind=args.kind, metric=args.metric,
                    structure_hash=args.structure)
    if not recs:
        print(f"graft_ledger: no records in {lg.path}",
              file=sys.stderr)
        return 1
    base = build_baseline(recs)
    newest = {}
    for rec in recs:
        newest[baseline_key(rec)] = rec
    if args.json:
        print(json.dumps({"store": lg.path, "records": len(recs),
                          "baseline": base}, indent=2,
                         sort_keys=True))
        return 0
    print(f"# {lg.path}: {len(recs)} records")
    print(f"{'key':<58} {'n':>3} {'median':>12} {'mad':>10} "
          f"{'newest':>12} {'unit':>6}")
    for key in sorted(set(list(base['metrics']) + list(base['curves']))):
        rec = newest.get(key)
        entry = base["metrics"].get(key)
        if entry is not None:
            print(f"{key:<58} {entry['count']:>3} "
                  f"{entry['median']:>12.4g} {entry['mad']:>10.4g} "
                  f"{(rec or {}).get('value') or float('nan'):>12.4g} "
                  f"{entry.get('unit') or '-':>6}")
        else:
            curve = base["curves"][key]["rel_frobenius"]
            tail = curve[-1] if curve else float("nan")
            print(f"{key:<58} {len(curve):>3}pt {'curve':>12} "
                  f"{'-':>10} {tail:>12.4g} {'rel':>6}")
    return 0


def _cmd_diff(args) -> int:
    from arrow_matrix_tpu.ledger import Ledger
    from arrow_matrix_tpu.ledger.gate import (
        band_upper,
        baseline_key,
        baseline_path,
        load_baseline,
        normalized_value,
    )

    lg = Ledger(args.ledger_dir)
    bpath = args.baseline or baseline_path(args.ledger_dir)
    baseline = load_baseline(bpath)
    newest = {}
    for rec in lg.read_all():
        newest[baseline_key(rec)] = rec
    print(f"# newest records in {lg.path} vs baseline {bpath}")
    print(f"{'key':<58} {'newest':>12} {'median':>12} {'band':>12} "
          f"{'delta%':>8}")
    rc = 0
    for key, entry in sorted(baseline.get("metrics", {}).items()):
        rec = newest.get(key)
        if rec is None:
            print(f"{key:<58} {'absent':>12}")
            continue
        nv = normalized_value(rec)
        med = entry["median"]
        upper = band_upper(entry, baseline.get("band_k", 4.0),
                           baseline.get("rel_floor", 0.05))
        delta = (100.0 * (nv - med) / med) if med and nv is not None \
            else float("nan")
        mark = ""
        if nv is not None and nv > upper and \
                (entry.get("unit") in ("ms", "s")):
            mark = "  REGRESSED"
            rc = 1
        print(f"{key:<58} {nv if nv is not None else float('nan'):>12.4g} "
              f"{med:>12.4g} {upper:>12.4g} {delta:>8.2f}{mark}")
    return rc


def _cmd_curve(args) -> int:
    from arrow_matrix_tpu.ledger import Ledger

    lg = Ledger(args.ledger_dir)
    recs = lg.query(kind="error_curve",
                    structure_hash=args.structure)
    if args.dtype:
        recs = [r for r in recs
                if r.get("knobs", {}).get("dtype") == args.dtype]
    if not recs:
        print("graft_ledger: no error_curve records match",
              file=sys.stderr)
        return 1
    for rec in recs:
        knobs = rec.get("knobs", {})
        print(f"# {rec.get('metric')} structure="
              f"{rec.get('structure_hash')} k={knobs.get('k')} "
              f"seed={knobs.get('seed')} "
              f"emulated={knobs.get('emulated')} "
              f"record={rec.get('record_id')}")
        payload = rec.get("payload", {})
        fro = payload.get("frobenius", [])
        rel = payload.get("rel_frobenius", [])
        mab = payload.get("max_abs", [])
        print(f"{'iter':>4} {'frobenius':>12} {'rel_frob':>12} "
              f"{'max_abs':>12}")
        for j in range(len(rel)):
            print(f"{j:>4} "
                  f"{fro[j] if j < len(fro) else float('nan'):>12.4e} "
                  f"{rel[j]:>12.4e} "
                  f"{mab[j] if j < len(mab) else float('nan'):>12.4e}")
    return 0


def _cmd_export(args) -> int:
    from arrow_matrix_tpu.ledger import Ledger
    from arrow_matrix_tpu.ledger.export import export_legacy_round

    out = args.out or f"BENCH_r{args.round:02d}.json"
    doc = export_legacy_round(Ledger(args.ledger_dir), args.round, out,
                              head=args.upto)
    print(f"graft_ledger: wrote {out} (metric "
          f"{doc['parsed'].get('metric')!r}, "
          f"{len(doc['parsed'].get('tuned', []))} tuned entries, "
          f"{len(doc['parsed'].get('error_curves', []))} curves)")
    return 0


def _cmd_ingest(args) -> int:
    from arrow_matrix_tpu.ledger import Ledger
    from arrow_matrix_tpu.ledger.export import (
        ingest_legacy_bench,
        ingest_tune_plans,
    )

    lg = Ledger(args.ledger_dir)
    total = 0
    if args.bench:
        count, notes = ingest_legacy_bench(lg, args.bench)
        total += count
        for note in notes:
            print(f"  note {note}")
        print(f"graft_ledger: ingested {count} legacy bench rounds")
    if args.plans:
        count, notes = ingest_tune_plans(lg, args.plans)
        total += count
        for note in notes:
            print(f"  note {note}")
        print(f"graft_ledger: ingested {count} tune plan winners")
    if not total and not args.bench and not args.plans:
        print("graft_ledger ingest: nothing to do (pass --bench "
              "and/or --plans)", file=sys.stderr)
        return 1
    return 0


def _probe_source(args) -> dict:
    if args.ba and args.base:
        raise SystemExit("graft_ledger probe: --ba and --base are "
                         "exclusive")
    if args.ba:
        try:
            n, width, seed = (int(v) for v in args.ba.split(","))
        except ValueError:
            raise SystemExit("graft_ledger probe: --ba wants "
                             "N,WIDTH,SEED")
        return {"kind": "ba", "n": n, "m": args.ba_m, "width": width,
                "seed": seed, "max_levels": args.max_levels}
    if args.base:
        src = {"kind": "dir", "base": args.base}
        if args.width:
            src["width"] = args.width
        return src
    raise SystemExit("graft_ledger probe: need --ba N,WIDTH,SEED or "
                     "--base DIR")


def _cmd_probe(args) -> int:
    from arrow_matrix_tpu.ledger import Ledger
    from arrow_matrix_tpu.ledger.probe import error_curves_for_source

    dtypes = tuple(s.strip() for s in args.dtypes.split(",")
                   if s.strip())
    recs = error_curves_for_source(
        _probe_source(args), k=args.k, iterations=args.iterations,
        seed=args.seed, dtypes=dtypes, ledger=Ledger(args.ledger_dir))
    for rec in recs:
        print(f"{rec['metric']}: structure="
              f"{rec['structure_hash']} final rel_frobenius="
              f"{rec['value']:.4e} -> {rec['record_id']}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        return _cmd_report(args)
    if args.cmd == "diff":
        return _cmd_diff(args)
    if args.cmd == "curve":
        return _cmd_curve(args)
    if args.cmd == "export":
        return _cmd_export(args)
    if args.cmd == "ingest":
        return _cmd_ingest(args)
    if args.cmd == "probe":
        return _cmd_probe(args)
    from arrow_matrix_tpu.ledger import gate as gate_mod

    argv2: List[str] = []
    if args.ledger_dir:
        argv2 += ["--ledger-dir", args.ledger_dir]
    if getattr(args, "baseline", None):
        argv2 += ["--baseline", args.baseline]
    argv2.append("--rebaseline" if args.cmd == "rebaseline"
                 else "--check")
    return gate_mod.main(argv2)


if __name__ == "__main__":
    sys.exit(main())
