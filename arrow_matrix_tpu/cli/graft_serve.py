"""``graft_serve`` — run the always-on multi-tenant SpMM server over a
deterministic synthetic load.

Builds a Barabasi-Albert arrow decomposition (the resident operator),
starts :class:`~arrow_matrix_tpu.serve.ArrowServer` with admission
control against the HBM budget, and drives it with the deterministic
load generator (serve/loadgen.py): no wall-clock randomness, so two
runs of the same flags produce bit-identical per-request results —
the property tools/serve_gate.py's kill scenario compares across a
SIGKILL + checkpoint resume.

Prints the SLO report (requests/s, p50/p99 latency, shed/rejected
census, HBM occupancy) and writes ``serve_summary.json`` +
``metrics.jsonl`` + the flight recorder under ``--obs_dir``.  With
``--pulse``, attaches the graft-pulse telemetry layer (obs/pulse.py):
a request-correlated Perfetto trace (``serve_trace.json``), the
windowed SLO time series ring (``pulse_ring.json`` +
``pulse_metrics.prom``), an optional live scrape endpoint
(``--pulse_port``), and the SLO-burn watchdog feeding the degradation
ladder.  Exits non-zero only when a request FAILED (shed/rejected are
explicit, policy-level outcomes, not server failures).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    from arrow_matrix_tpu.cli.common import (
        add_device_args,
        add_heal_args,
    )

    p = argparse.ArgumentParser(
        prog="graft_serve", description=__doc__.splitlines()[0])
    p.add_argument("--vertices", type=int, default=256)
    p.add_argument("--width", type=int, default=32,
                   help="arrow width of the resident decomposition")
    p.add_argument("--features", type=int, default=4,
                   help="feature width k of every synthetic request")
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--iterations", type=int, default=3,
                   help="SpMM iterations per request")
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--fmt", type=str, default="fold",
                   choices=["fold", "ell"],
                   help="resident executor format: 'fold' is the "
                        "single-chip SELL fold (full degradation "
                        "ladder), 'ell' shards level blocks over a "
                        "--devices mesh")
    p.add_argument("--kernel", type=str, default="xla",
                   choices=["xla", "pallas_sell"],
                   help="base rung kernel (fold only); faults degrade "
                        "pallas_sell -> xla")
    p.add_argument("--repl", type=int, default=1,
                   help="base rung 2.5D column replication (fold)")
    p.add_argument("--overlap_slabs", type=int, default=1,
                   help="base rung overlap sub-slabs")
    p.add_argument("--queue", type=int, default=16,
                   help="bounded queue capacity; overflow sheds "
                        "explicitly")
    p.add_argument("--max_batch_k", type=int, default=0,
                   help="dynamic batching: concatenate compatible "
                        "queued requests along the feature axis up to "
                        "this combined width (0 disables)")
    p.add_argument("--hbm_budget_mb", type=float, default=0.0,
                   help="HBM budget for admission control in MiB "
                        "(0 = the platform/AMT_HBM_GB budget)")
    p.add_argument("--degrade_after", type=int, default=2,
                   help="recovered faults per tenant before its rung "
                        "degrades")
    p.add_argument("--deadline", type=float, default=0.0,
                   help="per-request queueing deadline seconds "
                        "(0 = none); expired requests are shed "
                        "explicitly at dequeue")
    p.add_argument("--obs_dir", type=str, default=None,
                   help="run directory for serve_summary.json, "
                        "metrics.jsonl, and the flight recorder")
    p.add_argument("--pulse", action="store_true",
                   help="attach the graft-pulse live telemetry layer: "
                        "windowed SLO time series + burn watchdog, "
                        "request-correlated Perfetto trace "
                        "(serve_trace.json) and pulse_ring.json/"
                        "pulse_metrics.prom under --obs_dir")
    p.add_argument("--pulse_window", type=float, default=0.5,
                   help="pulse sliding-window width in seconds")
    p.add_argument("--pulse_port", type=int, default=-1,
                   help="serve /metrics + /pulse.json on this port "
                        "for the run's duration (0 = ephemeral, "
                        "-1 = no endpoint)")
    p.add_argument("--slo_p99_ms", type=float, default=0.0,
                   help="p99 latency SLO target in ms for the burn "
                        "watchdog (0 = no latency rule)")
    p.add_argument("--results_out", type=str, default=None,
                   help="write completed request results to this .npz "
                        "(one array per request id) — the replay "
                        "artifact serve_gate compares bit-for-bit")
    add_device_args(p)
    add_heal_args(p, checkpoint_every_default=2)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from arrow_matrix_tpu.cli.common import setup_platform

    setup_platform(args)

    import numpy as np

    from arrow_matrix_tpu.faults import RetryPolicy
    from arrow_matrix_tpu.obs import MetricsRegistry, flight
    from arrow_matrix_tpu.serve import (
        ArrowServer,
        ExecConfig,
        ba_executor_factory,
        run_trace,
        slo_summary,
        synthetic_trace,
        write_serve_artifacts,
    )

    registry = MetricsRegistry(run_dir=args.obs_dir)
    if args.obs_dir:
        import os

        os.makedirs(args.obs_dir, exist_ok=True)
        flight.install(os.path.join(args.obs_dir, "flight.json"))

    mesh = None
    if args.fmt == "ell":
        import jax

        from arrow_matrix_tpu.parallel import make_mesh

        n_dev = len(jax.devices())
        mesh = make_mesh((n_dev,), ("blocks",))
    factory, n_rows = ba_executor_factory(
        args.vertices, args.width, args.seed, fmt=args.fmt, mesh=mesh)
    base_cfg = ExecConfig(kernel=args.kernel, repl=args.repl,
                          overlap_slabs=args.overlap_slabs)
    policy = RetryPolicy.from_args(args)
    budget = (int(args.hbm_budget_mb * 2**20)
              if args.hbm_budget_mb > 0 else None)
    monitor, endpoint, tracer = None, None, None
    if args.pulse:
        import os

        from arrow_matrix_tpu.obs import Tracer, pulse as pulse_mod

        tracer = Tracer("graft-serve", registry=registry)
        ring = (os.path.join(args.obs_dir, "pulse_ring.json")
                if args.obs_dir else None)
        monitor = pulse_mod.PulseMonitor(
            window_s=args.pulse_window, ring_path=ring,
            name="graft-serve",
            watchdog=pulse_mod.SloWatchdog(pulse_mod.default_rules(
                target_p99_ms=(args.slo_p99_ms
                               if args.slo_p99_ms > 0 else None))))
    server = ArrowServer(
        factory, base_cfg, hbm_budget_bytes=budget,
        queue_capacity=args.queue, policy=policy,
        checkpoint_dir=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        max_batch_k=args.max_batch_k,
        degrade_after=args.degrade_after,
        registry=registry, tracer=tracer, name="graft-serve",
        verbose=True)
    if monitor is not None:
        server.attach_pulse(monitor)
        if args.pulse_port >= 0:
            from arrow_matrix_tpu.obs import PulseEndpoint

            endpoint = PulseEndpoint(monitor,
                                     port=args.pulse_port).start()
            print(f"graft-serve: pulse endpoint at {endpoint.url}"
                  f"/metrics", flush=True)
    trace = synthetic_trace(
        n_rows, tenants=args.tenants, requests=args.requests,
        k=args.features, iterations=args.iterations, seed=args.seed,
        deadline_s=args.deadline if args.deadline > 0 else None)
    t0 = time.perf_counter()
    tickets = run_trace(server, trace)
    wall = time.perf_counter() - t0
    if monitor is not None:
        monitor.close()
    summary = slo_summary(server, tickets, wall, pulse=monitor)

    lat = summary["latency_ms"]
    print(f"graft-serve: {summary['requests']} requests over "
          f"{args.tenants} tenants — {summary['completed']} completed,"
          f" {summary['shed']} shed, {summary['rejected']} rejected, "
          f"{summary['failed']} failed in {wall:.2f}s "
          f"({(summary['requests_per_s'] or 0):.2f} req/s)")
    if lat["count"]:
        print(f"graft-serve: latency p50={lat['p50']:.1f}ms "
              f"p90={lat['p90']:.1f}ms p99={lat['p99']:.1f}ms")
    hbm = summary["hbm"]
    print(f"graft-serve: hbm peak {hbm['peak_in_use_bytes']} / "
          f"{hbm['budget_bytes']} B "
          f"(peak occupancy {hbm['peak_occupancy']:.2e}; resident "
          f"operator {hbm['resident_bytes']} B)")
    if summary["faults_seen"]:
        print(f"graft-serve: {summary['faults_seen']} fault(s) seen, "
              f"{summary['recoveries']} recover(ies), "
              f"{summary['checkpoint_corruptions']} checkpoint "
              f"corruption(s) discarded")

    if monitor is not None:
        pt = summary["pulse"]
        burns = [e for e in pt["burn_events"]
                 if e["event"] == "slo_burn"]
        print(f"graft-serve: pulse — {len(pt['windows'])} windows of "
              f"{pt['window_s']}s, {len(burns)} SLO burn(s)"
              + (": " + ", ".join(sorted({b['rule'] for b in burns}))
                 if burns else ""), flush=True)
    if args.results_out:
        done = {t.request.request_id: t.result for t in tickets
                if t.result is not None}
        if monitor is not None:
            # Embed the windowed series in the replay artifact for
            # offline diffing.  Only with --pulse: serve_gate compares
            # fault vs fault-free artifacts file-by-file, and the
            # window series is timing-shaped, not replay-identical.
            done["_pulse_windows"] = np.frombuffer(
                json.dumps(summary["pulse"]["windows"]).encode(),
                dtype=np.uint8)
        np.savez(args.results_out, **done)
        print(f"graft-serve: wrote {len(done)} result(s) to "
              f"{args.results_out}")
    if args.obs_dir:
        import os

        if tracer is not None:
            tp = tracer.save(os.path.join(args.obs_dir,
                                          "serve_trace.json"))
            print(f"graft-serve: wrote request-correlated trace "
                  f"{tp}")
        if monitor is not None:
            with open(os.path.join(args.obs_dir,
                                   "pulse_metrics.prom"), "w",
                      encoding="utf-8") as fh:
                fh.write(monitor.exposition_text())
        path = write_serve_artifacts(args.obs_dir, summary,
                                     registry=registry)
        rec = flight.get_recorder()
        if rec is not None:
            rec.seal("graft-serve run complete")
            flight.set_recorder(None)
        print(f"graft-serve: wrote {path}")
    if endpoint is not None:
        endpoint.stop()
    if summary["failed"]:
        print(f"graft-serve: {summary['failed']} request(s) FAILED",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
