"""Command-line entry points.

Counterparts of the reference's console scripts (reference setup.py:17-24):

  arrow_decompose   offline decomposition        (scripts/decomposition_main.py)
  spmm_arrow        arrow SpMM benchmark         (scripts/spmm_arrow_main.py)
  spmm_15d          1.5D baseline benchmark      (scripts/spmm_15d_main.py)
  spmm_petsc        1D PETSc-style benchmark     (scripts/spmm_petsc_main.py)

Each is runnable as ``python -m arrow_matrix_tpu.cli.<name>`` or via the
installed console script.  One deliberate difference from the reference:
there is no ``mpiexec`` — every command is a single SPMD process driving
all local devices through one `jax.sharding.Mesh`; ``--devices N``
requests an N-device *virtual CPU* mesh for testing multi-chip layouts
without hardware (the analog of ``mpiexec --oversubscribe``).
"""
