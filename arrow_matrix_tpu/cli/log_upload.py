"""``log_upload`` — deferred upload of offline benchmark logs.

Counterpart of the reference's wandb upload tool (reference
scripts/wb_log_main.py + arrow/common/wb_logging.py:135-160): scan a log
directory for runs written by the benchmark CLIs, stream each to wandb,
and mark it with a ``.logged`` indicator file.  Without wandb installed
it lists the pending runs (file logs remain the source of truth).
"""

from __future__ import annotations

import argparse
import os


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Upload offline run logs to W&B.")
    parser.add_argument("-f", "--path", type=str, default="./logs",
                        help="Directory containing run logs.")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.path):
        raise SystemExit(f"{args.path} is not a directory")

    from arrow_matrix_tpu.utils.logging import log_local_runs

    handled = log_local_runs(args.path)
    print(f"{len(handled)} run(s) handled")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
