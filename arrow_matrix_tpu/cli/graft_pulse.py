"""``graft_pulse`` — watch / snapshot / check live serving telemetry.

The operator-side companion to obs/pulse.py.  A *source* is either a
pulse ring artifact on disk (``pulse_ring.json``, or the run directory
that contains one) or a live :class:`~arrow_matrix_tpu.obs.pulse
.PulseEndpoint` URL (``http://host:port`` — ``/pulse.json`` is
appended when missing):

  * ``snapshot <source>`` — one human-readable view: totals, the last
    closed windows, active burns;
  * ``watch <source>`` — poll the source and print one line per newly
    closed window (req/s, p50/p99, occupancy, sheds, burns) until
    ``--count`` windows or Ctrl-C;
  * ``check <source> [--metrics <path-or-url>]`` — validate the ring
    document (and optionally a Prometheus exposition payload) against
    the graft-pulse schema; exit non-zero on any problem — the same
    validators tools/obs_gate.py and ``amt_doctor probe_pulse`` use;
  * ``merge <source...>`` — pool N rings (one per graft-fleet worker)
    into one merged document via the lossless Histogram.merge: the
    merged quantiles are EXACT nearest-rank over the union of raw
    samples, and each source ring's pooled windows are asserted equal
    to its own streamed totals (exit non-zero on any mismatch).

Pure stdlib + obs/pulse.py: no jax import, so it runs anywhere the
artifacts land.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import Optional

from arrow_matrix_tpu.obs import pulse


def _resolve(source: str) -> str:
    if source.startswith(("http://", "https://")):
        return (source if source.endswith("/pulse.json")
                else source.rstrip("/") + "/pulse.json")
    if os.path.isdir(source):
        return os.path.join(source, "pulse_ring.json")
    return source


def _load(source: str) -> dict:
    src = _resolve(source)
    if src.startswith(("http://", "https://")):
        with urllib.request.urlopen(src, timeout=10) as resp:
            return json.loads(resp.read().decode())
    return pulse.load_ring(src)


def _read_text(source: str) -> str:
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=10) as resp:
            return resp.read().decode()
    with open(source, encoding="utf-8") as fh:
        return fh.read()


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.1f}"


def _window_line(w: dict, base: float = 0.0) -> str:
    lat = w["latency_ms"]
    occ = w["hbm"]["occupancy"]
    extra = ""
    if w["shed"] or w["rejected"]:
        extra += f" shed={w['shed']} rej={w['rejected']}"
    if w["faults_seen"]:
        extra += f" faults={w['faults_seen']}"
    if w["degraded"]:
        extra += f" degraded={w['degraded']}"
    if w["slo_burns"]:
        extra += f" BURNS={w['slo_burns']}"
    return (f"w{w['window']:>4} +{w['start_s'] - base:.1f}s "
            f"{(w['requests_per_s'] or 0.0):7.2f} req/s "
            f"p50={_fmt_ms(lat['p50'])}ms "
            f"p99={_fmt_ms(lat['p99'])}ms "
            f"occ={'-' if occ is None else format(occ, '.2e')}"
            f"{extra}")


def cmd_snapshot(args) -> int:
    doc = _load(args.source)
    t = doc["totals"]
    lat = t["latency_ms"]
    print(f"pulse: {doc['meta'].get('name', '?')} pid="
          f"{doc['meta'].get('pid')} window={doc['window_s']}s "
          f"windows={len(doc['windows'])} "
          f"(+{doc.get('dropped_windows', 0)} dropped) "
          f"sealed={doc.get('closed') or 'LIVE'}")
    print(f"totals: {t['completed']} completed / {t['failed']} failed "
          f"/ {t['shed']} shed / {t['rejected']} rejected; "
          f"{(t['requests_per_s'] or 0.0):.2f} req/s; "
          f"p50={_fmt_ms(lat['p50'])}ms p99={_fmt_ms(lat['p99'])}ms; "
          f"{t['faults_seen']} fault(s), {t['degraded']} "
          f"degradation(s)")
    for tenant, rec in (t.get("per_tenant") or {}).items():
        tl = rec["latency_ms"]
        print(f"  {tenant}: {rec['completed']} completed "
              f"p99={_fmt_ms(tl['p99'])}ms shed={rec['shed']} "
              f"rejected={rec['rejected']}")
    base = doc["windows"][0]["start_s"] if doc["windows"] else 0.0
    for w in doc["windows"][-args.last:]:
        print("  " + _window_line(w, base))
    burning = doc.get("burning") or []
    if burning:
        print(f"BURNING now: {', '.join(burning)}")
    for e in doc.get("burn_events", []):
        print(f"  [{e['event']}] {e['rule']} window={e['window']}"
              + (f" value={e['value']:.3g} > {e['threshold']:.3g}"
                 if e["event"] == "slo_burn" else ""))
    return 0


def cmd_watch(args) -> int:
    printed = -1
    seen = 0
    base = None
    try:
        while True:
            try:
                doc = _load(args.source)
            except (OSError, json.JSONDecodeError) as e:
                print(f"graft_pulse: source unreadable ({e}); "
                      f"retrying", file=sys.stderr)
                time.sleep(args.interval)
                continue
            for w in doc["windows"]:
                if w["window"] > printed:
                    if base is None:
                        base = w["start_s"]
                    print(_window_line(w, base), flush=True)
                    printed = w["window"]
                    seen += 1
                    if args.count and seen >= args.count:
                        return 0
            if doc.get("closed"):
                print(f"graft_pulse: source sealed "
                      f"({doc['closed']})")
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_check(args) -> int:
    problems = []
    try:
        doc = _load(args.source)
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"ring unreadable: {e}")
        doc = None
    if doc is not None:
        problems += [f"ring: {p}" for p in pulse.validate_ring(doc)]
    if args.metrics:
        try:
            text = _read_text(args.metrics)
        except OSError as e:
            problems.append(f"exposition unreadable: {e}")
        else:
            problems += [f"exposition: {p}"
                         for p in pulse.validate_exposition(text)]
    for p in problems:
        print(f"graft_pulse check: PROBLEM: {p}")
    if problems:
        return 1
    n = len(doc["windows"]) if doc else 0
    print(f"graft_pulse check: OK ({n} windows"
          + (", exposition valid" if args.metrics else "") + ")")
    return 0


def cmd_merge(args) -> int:
    docs = []
    problems = []
    for source in args.sources:
        try:
            docs.append(_load(source))
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"{source}: unreadable ({e})")
    merged = pulse.merge_rings(docs)
    problems += merged["problems"]
    merged["problems"] = problems
    if args.out:
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json

        atomic_write_json(args.out, merged, indent=2, sort_keys=True)
    if args.json:
        print(json.dumps(merged, indent=2, sort_keys=True))
    else:
        t = merged["totals"]
        lat = t["latency_ms"]
        print(f"pulse merge: {merged['rings']} ring(s), "
              f"{lat['count']} pooled samples")
        for r in merged["per_ring"]:
            print(f"  {r['name']}: {r['windows']} windows "
                  f"(+{r['dropped_windows']} dropped), "
                  f"{r['pooled_samples']} samples")
        print(f"totals: {t['completed']} completed / {t['failed']} "
              f"failed / {t['shed']} shed / {t['rejected']} rejected; "
              f"p50={_fmt_ms(lat['p50'])}ms "
              f"p90={_fmt_ms(lat['p90'])}ms "
              f"p99={_fmt_ms(lat['p99'])}ms (exact pooled quantiles)")
    for p in problems:
        print(f"graft_pulse merge: PROBLEM: {p}")
    return 1 if problems else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_pulse", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("snapshot",
                        help="print one view of a pulse source")
    sp.add_argument("source", help="pulse_ring.json / run dir / "
                                   "endpoint URL")
    sp.add_argument("--last", type=int, default=5,
                    help="closed windows to show")
    sp.set_defaults(fn=cmd_snapshot)

    wp = sub.add_parser("watch",
                        help="print one line per newly closed window")
    wp.add_argument("source")
    wp.add_argument("--interval", type=float, default=1.0)
    wp.add_argument("--count", type=int, default=0,
                    help="stop after this many windows (0 = until "
                         "sealed / Ctrl-C)")
    wp.set_defaults(fn=cmd_watch)

    cp = sub.add_parser("check",
                        help="validate ring (+ exposition) schema")
    cp.add_argument("source")
    cp.add_argument("--metrics", type=str, default=None,
                    help="also validate this exposition text "
                         "(pulse_metrics.prom path or /metrics URL)")
    cp.set_defaults(fn=cmd_check)

    mp = sub.add_parser(
        "merge",
        help="pool N pulse rings (fleet workers) into one exact "
             "merged document; asserts pooled == streamed per ring")
    mp.add_argument("sources", nargs="+",
                    help="pulse_ring.json paths / run dirs / "
                         "endpoint URLs")
    mp.add_argument("--out", default=None,
                    help="write the merged document here")
    mp.add_argument("--json", action="store_true",
                    help="print the merged document as JSON")
    mp.set_defaults(fn=cmd_merge)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
