"""``graft_tune`` — structure-specialized kernel autotuning with a
persistent plan cache (the graft-tune subsystem).

Three subcommands close the tune lifecycle:

* ``search`` — fingerprint a structure (``--ba n,width,seed`` or a
  committed ``--base`` graphio directory), race the pruned candidate
  space in subprocess-isolated children, persist the winner as a
  versioned TunePlan under ``bench_cache/tune_plans/<hash>.json``.
  A second search of an unchanged structure is a pure cache hit —
  zero children spawned.
* ``show`` — print a cached plan file (or list every cached hash).
* ``check`` — replay the plan cache's promises (bit-identity vs the
  golden fold path, ≤5% regression vs default, hash integrity, cache
  purity); same engine as ``tools/tune_gate.py``; exits nonzero on
  any broken promise.

Consumption is ``plan="auto"`` on ``MultiLevelArrow`` /
``SellMultiLevel`` (loud ``TunePlanMiss`` fallback on a cache miss)
and ``tune_plan=`` on the serve scheduler.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _source_from_args(args) -> dict:
    if args.ba and args.base:
        raise SystemExit("graft_tune: --ba and --base are exclusive")
    if args.ba:
        try:
            n, width, seed = (int(v) for v in args.ba.split(","))
        except ValueError:
            raise SystemExit("graft_tune: --ba wants N,WIDTH,SEED "
                             "(e.g. --ba 4096,128,7)")
        return {"kind": "ba", "n": n, "m": args.ba_m, "width": width,
                "seed": seed, "max_levels": args.max_levels}
    if args.base:
        src = {"kind": "dir", "base": args.base}
        if args.width:
            src["width"] = args.width
        return src
    raise SystemExit("graft_tune search: need --ba N,WIDTH,SEED or "
                     "--base DIR")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_tune", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("search", help="race candidates, cache the "
                                      "winning plan")
    s.add_argument("--ba", type=str, default=None,
                   help="Barabasi-Albert source: N,WIDTH,SEED")
    s.add_argument("--ba_m", type=int, default=3,
                   help="BA attachment parameter m")
    s.add_argument("--max_levels", type=int, default=10)
    s.add_argument("--base", type=str, default=None,
                   help="committed graphio artifact directory "
                        "(e.g. bench_cache/ba_16384_8_w512_s7_L12)")
    s.add_argument("--width", type=int, default=None,
                   help="decomposition width inside --base (default: "
                        "autodetect)")
    s.add_argument("--k", type=int, action="append", default=None,
                   help="feature width(s) to tune (repeatable; "
                        "default 16 128)")
    s.add_argument("--iters", type=int, default=3)
    s.add_argument("--timeout", type=float, default=240.0,
                   help="per-candidate child timeout seconds")
    s.add_argument("--plan-dir", type=str, default=None)
    s.add_argument("--refresh", action="store_true",
                   help="re-search even on a cache hit")
    s.add_argument("--allow-int8", action="store_true",
                   help="include the opt-in int8 carriage candidate")
    s.add_argument("--synth", action="store_true",
                   help="graft-synth: derive per-level schedules from "
                        "the degree ladder and race them alongside "
                        "the fixed menu")
    s.add_argument("--traffic-class", choices=("exact", "approx"),
                   default="exact",
                   help="winner gate: exact = f32 bit-identity "
                        "(default); approx = class tolerance with a "
                        "probed error-curve certificate")
    s.add_argument("--restrict", type=str, action="append",
                   default=None,
                   help="race only these candidate names (repeatable)")
    s.add_argument("--json", action="store_true",
                   help="print the full report(s) as JSON")
    s.add_argument("--quiet", action="store_true")

    w = sub.add_parser("show", help="print cached plan file(s)")
    w.add_argument("hash", nargs="?", default=None,
                   help="structure hash (omit to list the cache)")
    w.add_argument("--plan-dir", type=str, default=None)

    c = sub.add_parser("check", help="gate the plan cache "
                                     "(tools/tune_gate.py engine)")
    c.add_argument("--plan-dir", type=str, default=None)
    c.add_argument("--hash", action="append", default=None)
    c.add_argument("--iters", type=int, default=3)
    c.add_argument("--repeats", type=int, default=3)
    c.add_argument("--rel-tol", type=float, default=0.05)
    c.add_argument("--abs-tol-ms", type=float, default=0.25)
    c.add_argument("--refresh", action="store_true")
    c.add_argument("--no-timing", action="store_true")
    c.add_argument("--quiet", action="store_true")
    return p


def _cmd_search(args) -> int:
    from arrow_matrix_tpu.tune.search import search

    source = _source_from_args(args)
    ks: List[int] = args.k or [16, 128]
    reports = []
    rc = 0
    for k in ks:
        plan, report = search(source, k, iters=args.iters,
                              timeout_s=args.timeout,
                              plan_dir=args.plan_dir,
                              refresh=args.refresh,
                              allow_int8=args.allow_int8,
                              restrict=args.restrict,
                              traffic_class=args.traffic_class,
                              synth=args.synth,
                              quiet=args.quiet)
        reports.append(report)
        if plan is None:
            rc = 1
            continue
        if not args.json:
            tag = ("cache-hit" if report.get("cache_hit")
                   else f"searched {report.get('children_spawned')} "
                        f"children")
            print(f"k={k}: {plan.candidate!r} "
                  f"{plan.measured_ms} ms (margin {plan.margin}, "
                  f"{tag}) -> {report.get('plan_path', 'cache')}")
    if args.json:
        print(json.dumps(reports if len(reports) > 1 else reports[0],
                         indent=2, sort_keys=True))
    return rc


def _cmd_show(args) -> int:
    from arrow_matrix_tpu.tune.gate import gate_sources
    from arrow_matrix_tpu.tune.plan import load_plan_file, plan_dir

    if args.hash is None:
        sources = gate_sources(args.plan_dir)
        if not sources:
            print(f"graft_tune: no plans in "
                  f"{plan_dir(args.plan_dir)!r}", file=sys.stderr)
            return 1
        for h, src in sources.items():
            record = load_plan_file(h, args.plan_dir) or {}
            ks = sorted((record.get("plans") or {}),
                        key=lambda s: int(s))
            winners = {s: (record["plans"][s].get("candidate"))
                       for s in ks}
            print(f"{h}  k={','.join(ks)}  winners={winners}  "
                  f"source={src}")
        return 0
    record = load_plan_file(args.hash, args.plan_dir)
    if record is None:
        print(f"graft_tune: no plan file for {args.hash!r} in "
              f"{plan_dir(args.plan_dir)!r}", file=sys.stderr)
        return 1
    print(json.dumps(record, indent=2, sort_keys=True))
    return 0


def _cmd_check(args) -> int:
    from arrow_matrix_tpu.tune.gate import run_gate

    return run_gate(directory=args.plan_dir, hashes=args.hash,
                    iters=args.iters, repeats=args.repeats,
                    rel_tol=args.rel_tol, abs_tol_ms=args.abs_tol_ms,
                    refresh=args.refresh, timing=not args.no_timing,
                    quiet=args.quiet)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "search":
        return _cmd_search(args)
    if args.cmd == "show":
        return _cmd_show(args)
    return _cmd_check(args)


if __name__ == "__main__":
    sys.exit(main())
