"""``graft_lint`` — static-analysis CLI (doctor-style sibling).

Thin wrapper exposing the analysis engines as a console entry point
alongside ``amt_doctor``: lints the installed package (or explicit
paths) with the R1-R7 rule set and can run the trace-time recompile
audit.  The real implementation lives in ``arrow_matrix_tpu.analysis``;
this module exists so ``python -m arrow_matrix_tpu.cli.graft_lint``
and the pyproject console script reach it the same way the other CLIs
are reached (cli/__init__.py).
"""

from __future__ import annotations

from arrow_matrix_tpu.analysis.__main__ import main


if __name__ == "__main__":
    raise SystemExit(main())
