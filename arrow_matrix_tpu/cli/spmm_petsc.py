"""``spmm_petsc`` — 1-D row-partition (PETSc-style) baseline benchmark.

Counterpart of the reference's PETSc baseline entry point
(reference scripts/spmm_petsc_main.py + arrow/baseline/spmm_petsc.py:
398-495).  The reference loads pre-partitioned per-rank slice files
(``{name}.part.{P}.slice.{r}.npz``); here there is one SPMD process, so
``--file`` takes the whole matrix (or a ``.part.`` slice-scheme prefix,
reassembled) and the partition is computed at load.  ``--dryrun`` builds
the exchange tables and exits without benchmarking
(spmm_petsc_main.py:40).
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import time

import numpy as np
from scipy import sparse

from arrow_matrix_tpu.cli.common import (
    add_device_args,
    add_distributed_args,
    add_heal_args,
    load_sparse_matrix,
    make_supervisor,
    normalize_scale,
    random_adjacency,
    setup_platform,
    str2bool,
)


#: The reference's slice-file naming scheme (spmm_petsc.py:82-102) —
#: ONE copy shared by the per-slice fast path and the reassembly
#: fallback, so both always agree on what matches.
SLICE_RE = re.compile(r"(.*)\.part\.(\d+)\.slice\.(\d+)\.npz$")


def load_slices_or_matrix(path: str) -> sparse.csr_matrix:
    """Accept either one matrix file or any slice of the reference's
    ``{name}.part.{P}.slice.{r}.npz`` scheme (all slices are then
    reassembled; the partition itself is recomputed)."""
    m = SLICE_RE.match(path)
    if not m:
        return load_sparse_matrix(path)
    base, p = m.group(1), int(m.group(2))
    paths = sorted(
        glob.glob(f"{base}.part.{p}.slice.*.npz"),
        key=lambda s: int(re.search(r"slice\.(\d+)\.npz$", s).group(1)))
    if len(paths) != p:
        raise SystemExit(f"found {len(paths)} of {p} slice files for {base}")
    return sparse.vstack([sparse.load_npz(f) for f in paths]).tocsr()


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="SpMM PETSc benchmark.")
    parser.add_argument("-s", "--seed", type=int, default=42)
    parser.add_argument("-f", "--file", type=str, default=None,
                        help="Matrix file, or one slice of the "
                             "reference's .part.P.slice.r.npz scheme.")
    parser.add_argument("-v", "--vertices", type=int, default=100_000,
                        help="Vertices of the random matrix (no --file).")
    parser.add_argument("-e", "--edges", type=int, default=1_000_000)
    parser.add_argument("-c", "--columns", type=int, default=32)
    parser.add_argument("-z", "--iterations", type=int, default=3)
    parser.add_argument("--validate", type=str2bool, nargs="?", default=True)
    parser.add_argument("--dryrun", type=str2bool, nargs="?", default=False,
                        help="Build the exchange tables, print their "
                             "stats, skip the benchmark.")
    parser.add_argument("-m", "--memory", type=float, default=0.5,
                        help="Fraction of currently-FREE device memory "
                             "(net of this layout's own blocks) "
                             "budgeted for kernel intermediates; "
                             "drives the ELL slot-chunk auto-tiling "
                             "(the reference's --memory OOM-model GPU "
                             "tiling, spmm_petsc.py:323-395).  <= 0 "
                             "disables chunking.")
    parser.add_argument("--carry", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Carry X across iterations (X := A @ X "
                             "propagation; the 1-D row partition "
                             "preserves the blocked layout, so the "
                             "result feeds the next step directly) "
                             "instead of timing the same input.")
    add_heal_args(parser)
    parser.add_argument("--logdir", type=str, default="./logs")
    parser.add_argument("--comm_report", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Account the per-iteration collective "
                             "bytes of the compiled step from its HLO "
                             "(compare against spmm_arrow's modes — "
                             "the reference paper's headline metric).")
    parser.add_argument("--mem_report", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Report the compiled step's per-device "
                             "memory breakdown against the format-"
                             "metadata prediction, plus the per-shard "
                             "load-imbalance report.")
    add_device_args(parser)
    add_distributed_args(parser)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.checkpoint and not args.carry:
        # Pure flag error: fail before any build/compile work.
        raise SystemExit("--checkpoint requires --carry (there is no "
                         "iteration state to resume when X is the "
                         "same input every iteration)")
    setup_platform(args)

    import jax

    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.spmm_1d import MatrixSlice1D
    from arrow_matrix_tpu.utils import logging as wb
    from arrow_matrix_tpu.utils.graphs import random_dense

    n_dev = len(jax.devices())
    mesh = make_mesh((n_dev,), ("slices",))

    # Per-slice ingest (the reference's IO-parallel loading: each rank
    # reads only its own slice file, spmm_petsc.py:421-440) whenever
    # the slice count matches the device count; otherwise the slices
    # are reassembled into one host view (the partition is recomputed).
    slice_paths = None
    owned_slabs: dict = {}
    if args.file:
        m = SLICE_RE.match(args.file)
        if m and int(m.group(2)) == n_dev:
            base, p = m.group(1), int(m.group(2))
            slice_paths = [f"{base}.part.{p}.slice.{r}.npz"
                           for r in range(p)]
            missing = [q for q in slice_paths if not os.path.exists(q)]
            if missing:
                raise SystemExit(f"missing slice files: {missing[:3]}")
        name = os.path.basename(args.file)
    else:
        name = f"random_{args.vertices}_{args.edges}"

    if slice_paths is not None:
        from arrow_matrix_tpu.parallel.spmm_1d import (
            _exchange_sum,
            _owned_slice_ids,
            _primary_slice_ids,
        )

        mine = sorted(_owned_slice_ids(mesh, "slices"))
        primary = _primary_slice_ids(mesh, "slices")
        owned_slabs = {
            d: sparse.load_npz(slice_paths[d]).tocsr().astype(np.float32)
            for d in mine}
        # Global normalize_scale from per-slice row sums (each process
        # reads only its own slices; one host-side max exchange with
        # one contributor per slice).
        scales = np.zeros(n_dev)
        for d, s in owned_slabs.items():
            if s.nnz and d in primary:
                scales[d] = float(abs(s).sum(axis=1).max())
        scale = max(float(np.max(_exchange_sum(scales))), 1.0)
        for d in mine:
            owned_slabs[d] = (owned_slabs[d] / scale).tocsr()
        a = [(lambda d=d: owned_slabs[d]) if d in owned_slabs
             else slice_paths[d] for d in range(n_dev)]
    elif args.file:
        a = normalize_scale(load_slices_or_matrix(args.file))
    else:
        a = normalize_scale(
            random_adjacency(args.vertices, args.edges, args.seed))

    wb.init("PETSc_TPU_v1", name, config=vars(args))

    with wb.segment("build_time"):
        dist = MatrixSlice1D(
            a, mesh,
            chunk="auto" if args.memory > 0 else None,
            memory_fraction=args.memory if args.memory > 0 else 0.5)
    print(f"{n_dev} slices of <= {dist.l_rows} rows; exchange slot "
          f"{dist.slot} rows/pair")
    if args.dryrun:
        wb.finish(args.logdir)
        return 0

    x_host = random_dense(dist.n, args.columns, seed=args.seed)
    x = dist.set_features(x_host)

    if args.validate:
        got = dist.gather_result(dist.spmm(x))
        if slice_paths is not None:
            # Per-slice golden: each process validates the rows of the
            # slices it loaded (the global matrix never exists here).
            err_n = err_d = 0.0
            ok = True
            for d, slab in owned_slabs.items():
                lo, hi = dist.slices[d]
                want_d = np.asarray(slab @ x_host)
                err_n += float(np.linalg.norm(got[lo:hi] - want_d) ** 2)
                err_d += float(np.linalg.norm(want_d) ** 2)
                # Elementwise gate per owned slab: the reassembled path
                # checks np.allclose, and a single bad row can hide
                # inside a small Frobenius ratio — both --validate
                # paths must enforce the same strictness (ADVICE r3).
                ok &= bool(np.allclose(got[lo:hi], want_d,
                                       rtol=1e-4, atol=1e-4))
            err = (err_n / max(err_d, 1e-30)) ** 0.5
            ok = ok and bool(err < 1e-4)
            scope = (f"rows of slices {sorted(owned_slabs)}"
                     if jax.process_count() > 1 else "all rows")
            print(f"validation ({scope}): allclose={ok} "
                  f"rel frobenius err={err:.3e}")
        else:
            want = np.asarray(a @ x_host)
            err = np.linalg.norm(got - want) / max(np.linalg.norm(want),
                                                   1e-30)
            ok = np.allclose(got, want, rtol=1e-4, atol=1e-4)
            print(f"validation: allclose={ok} rel frobenius err={err:.3e}")
        wb.log({"frobenius_err": float(err)})
        if not ok:
            wb.finish(args.logdir)
            return 1

    y = dist.spmm(x)  # compile + warmup
    jax.block_until_ready(y)
    if args.comm_report:
        from arrow_matrix_tpu import obs
        from arrow_matrix_tpu.utils import commstats

        rep = obs.account_collectives(
            "spmm_1d", dist._step, dist.l_cols, dist.l_data,
            dist.nl_cols, dist.nl_data, dist.send_idx, x,
            ideal_bytes=obs.ideal_bytes_for(dist, args.columns))
        print(f"per-iteration collective bytes ({rep['source']} HLO):")
        print(commstats.format_stats(rep["collectives"]))
        if rep["ratio"] is not None:
            print(f"measured vs paper-model ideal: "
                  f"{rep['measured_bytes']} / {rep['ideal_bytes']} "
                  f"bytes = {rep['ratio']:.2f}x")
    if args.mem_report:
        from arrow_matrix_tpu import obs

        mem = obs.account_memory(
            "spmm_1d", dist._step, dist.l_cols, dist.l_data,
            dist.nl_cols, dist.nl_data, dist.send_idx, x,
            predicted_bytes=obs.predicted_bytes_for(dist, args.columns))
        print(obs.format_memory_report(mem))
        imb = obs.account_imbalance("spmm_1d", dist)
        if imb is not None:
            print(obs.format_imbalance_report(imb))
    sup = make_supervisor(args, "spmm_petsc", carry=args.carry,
                          layout="petsc/1d_sliced")
    start_it = 0
    if args.carry and args.checkpoint:
        state = sup.resume(like=x)
        if state is not None:
            x, start_it = state
            print(f"resumed from {args.checkpoint} at iteration "
                  f"{start_it}")

    def body(xb, it):
        wb.set_iteration_data({"iteration": it})
        tic = time.perf_counter()
        yb = dist.spmm(xb)
        jax.block_until_ready(yb)
        wb.log({"spmm_time": time.perf_counter() - tic})
        # 1-D row partition preserves the blocked layout: the result
        # is directly the next carried state.
        return yb

    _, ok = sup.run(body, x, start_it, args.iterations)

    s = wb.get_log().summarize().get("spmm_time")
    if s:
        print(f"spmm_time mean {s['mean'] * 1e3:.3f} ms over "
              f"{s['count']} iterations (min {s['min'] * 1e3:.3f})")
    out = wb.finish(args.logdir)
    if out:
        print(f"log written to {out}.json")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
