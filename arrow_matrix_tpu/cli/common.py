"""Shared CLI plumbing: flag parsing helpers, platform selection, and
matrix loading.

The reference's per-entry-point argparse + ``str2bool`` + device-string
convention (reference arrow/common/utils.py:9-17, scripts/*_main.py) —
plus the one genuinely TPU-specific concern: the JAX platform must be
pinned *before* the first backend initialization.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import numpy as np
from scipy import sparse


def str2bool(v) -> bool:
    """Reference-compatible boolean flag parser (utils.py:9-17)."""
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError("Boolean value expected.")


def add_device_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "-i", "--device", type=str, default="auto",
        choices=["auto", "cpu", "tpu"],
        help="Compute platform (the reference's cpu/gpu gate, "
             "spmm_arrow_main.py:18; 'auto' uses the default backend).")
    parser.add_argument(
        "--devices", type=int, default=0,
        help="Force an N-device virtual CPU platform (multi-chip layouts "
             "without hardware; the analog of mpiexec --oversubscribe). "
             "Implies --device cpu.")


def add_distributed_args(parser: argparse.ArgumentParser) -> None:
    """Multi-process launch flags (the mpiexec-rank analog: one OS
    process per host, `jax.distributed` joins them into one runtime).

    Launch N processes with the same --coordinator/--num-processes and
    distinct --process-id 0..N-1; on TPU pods the three are
    auto-detected and none is needed.
    """
    parser.add_argument(
        "--coordinator", type=str, default=None,
        help="host:port of process 0's coordination service; enables "
             "multi-process execution (jax.distributed.initialize).")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)


def setup_platform(args: argparse.Namespace) -> None:
    """Pin the JAX platform per --device/--devices, and join the
    multi-process runtime when --coordinator is given (must run before
    anything initializes a JAX backend)."""
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    coordinator = getattr(args, "coordinator", None)
    cpu = args.device == "cpu" or args.devices > 0
    if coordinator is not None:
        from arrow_matrix_tpu.parallel.mesh import initialize_multihost

        if cpu:
            # Pin + gloo even without an explicit count (--device cpu
            # alone must behave like the single-process path).
            import jax

            force_cpu_devices(args.devices if args.devices > 0 else None)
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        elif args.device == "tpu":
            # Same platform pin as the single-process path: with
            # multiple registered PJRT plugins the default priority
            # may initialize the wrong backend.
            os.environ.setdefault("JAX_PLATFORMS", "tpu")
        initialize_multihost(coordinator, args.num_processes,
                             args.process_id)
        return
    if cpu:
        force_cpu_devices(args.devices if args.devices > 0 else None)
    elif args.device == "tpu":
        os.environ.setdefault("JAX_PLATFORMS", "tpu")


def add_heal_args(parser: argparse.ArgumentParser,
                  checkpoint_every_default: int = 10) -> None:
    """graft-heal run-loop flags, shared by all three SpMM CLIs: the
    supervised iteration loop (watchdog / bounded retry / finite-check)
    plus iteration-state checkpointing (``utils/checkpoint.py``)."""
    g = parser.add_argument_group(
        "graft-heal", "supervised run loop: watchdog, bounded retry, "
                      "checkpoint resume (see faults/)")
    g.add_argument("--checkpoint", type=str, default=None,
                   help="Directory/base for iteration-state checkpoints "
                        "(requires --carry): X and the iteration "
                        "counter are saved every --checkpoint_every "
                        "iterations (orbax when available — sharded "
                        "arrays persist per-shard without a host "
                        "gather) and the run resumes from the "
                        "checkpoint when one exists.  Beyond reference "
                        "parity: the reference's only resume point is "
                        "the decomposition artifact.")
    g.add_argument("--checkpoint_every", type=int,
                   default=checkpoint_every_default)
    g.add_argument("--watchdog", type=float, default=0.0,
                   help="Per-iteration watchdog seconds (0 disables): "
                        "an iteration exceeding the budget is treated "
                        "as a fault — retried from its entry state, or "
                        "escalated to process-level recovery when it "
                        "never drains.")
    g.add_argument("--max_retries", type=int, default=2,
                   help="Consecutive faulted attempts of one iteration "
                        "before the run fails (each retry backs off "
                        "exponentially and rolls back to the last "
                        "checkpoint when one exists).")
    g.add_argument("--retry_jitter", type=float, default=0.0,
                   help="±fraction of deterministic, seedable jitter "
                        "on each backoff delay (faults/policy.py): 0 "
                        "keeps the bare exponential schedule; serving "
                        "deployments use ~0.2 so retries across "
                        "tenants don't synchronize.")
    g.add_argument("--finite_check", type=str2bool, nargs="?",
                   default=True, const=True,
                   help="Jitted all-finite check on the carried X each "
                        "iteration; NaN/Inf rolls back to the last "
                        "checkpoint instead of silently poisoning "
                        "every subsequent iteration (carry mode only).")


def make_supervisor(args: argparse.Namespace, name: str, *,
                    carry: bool, layout: Optional[str] = None,
                    registry=None, canonicalize=None):
    """Build the graft-heal Supervisor for a CLI run from its flags
    (one recipe so all three CLIs agree on flag semantics).

    ``canonicalize`` is the executor's checkpoint canonicalizer — for
    2.5D replicated runs (graft-repl) pass its ``merge_carries`` so
    saves persist the merged carriage instead of replica 0's partial
    slab view.
    """
    from arrow_matrix_tpu.faults import RetryPolicy, Supervisor

    return Supervisor(
        name, carry=carry,
        policy=RetryPolicy.from_args(args),
        checkpoint_path=getattr(args, "checkpoint", None),
        checkpoint_every=getattr(args, "checkpoint_every", 0),
        finite_check=bool(getattr(args, "finite_check", True)) and carry,
        layout=layout, registry=registry, canonicalize=canonicalize)


def load_sparse_matrix(path: str, dtype=np.float32) -> sparse.csr_matrix:
    """Load a sparse matrix from .npz (scipy), .mtx (matrix market), or
    .mat (matlab; the reference's primary input format,
    decomposition_main.py:18-34) — dispatch on extension."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npz":
        m = sparse.load_npz(path)
    elif ext in (".mtx", ".mm"):
        from scipy.io import mmread

        m = mmread(path)
    elif ext == ".mat":
        m = _load_matlab(path)
    else:
        raise ValueError(f"unsupported matrix format {ext!r} "
                         f"(expected .npz, .mtx, or .mat)")
    m = sparse.csr_matrix(m).astype(dtype)
    m.sum_duplicates()
    m.sort_indices()
    return m


def _load_matlab(path: str) -> sparse.spmatrix:
    from scipy.io import loadmat

    try:
        contents = loadmat(path)
    except NotImplementedError:
        # v7.3 files are HDF5 (the reference reads them with mat73,
        # decomposition_main.py:18-34; mat73 is not in this image).
        return _load_matlab_hdf5(path)
    for v in contents.values():
        if sparse.issparse(v):
            return v
    raise ValueError(f"no sparse matrix found in {path}")


def _load_matlab_hdf5(path: str) -> sparse.spmatrix:
    """MATLAB v7.3 (HDF5) sparse loader via h5py.

    MATLAB stores a sparse matrix as an HDF5 group with CSC component
    datasets ``data``/``ir``/``jc`` and the row count in the group's
    ``MATLAB_sparse`` attribute.  The SuiteSparse collection (the
    reference's primary datasets) keeps the matrix at ``Problem/A``;
    that location is probed first, then any sparse-tagged group.
    """
    try:
        import h5py
    except ImportError:
        raise ValueError(
            f"{path} is a MATLAB v7.3 (HDF5) file and h5py is not "
            f"available; convert it to .npz or .mtx first")

    def as_csc(node):
        jc = np.asarray(node["jc"], dtype=np.int64)
        ir = np.asarray(node["ir"], dtype=np.int64)
        data = (np.asarray(node["data"]) if "data" in node
                else np.ones(ir.size, dtype=np.float32))
        n_rows = int(node.attrs["MATLAB_sparse"])
        n_cols = jc.size - 1
        return sparse.csc_matrix((data, ir, jc), shape=(n_rows, n_cols))

    with h5py.File(path, "r") as f:
        if "Problem" in f and "A" in f["Problem"] \
                and "MATLAB_sparse" in f["Problem"]["A"].attrs:
            return as_csc(f["Problem"]["A"])
        found = []

        def visit(name, node):
            if isinstance(node, h5py.Group) and "MATLAB_sparse" in node.attrs:
                found.append(name)

        f.visititems(visit)
        if found:
            return as_csc(f[found[0]])
    raise ValueError(f"no MATLAB sparse matrix found in HDF5 file {path}")


def random_adjacency(vertices: int, edges: int, seed: int,
                     dtype=np.float32) -> sparse.csr_matrix:
    """Random graph with ~edges nonzeros (the reference's random dataset
    path, spmm_15d_main.py:100-110 via utils.generate_sparse_matrix)."""
    from arrow_matrix_tpu.utils.graphs import random_csr

    nnz_per_row = max(1, edges // max(vertices, 1))
    return random_csr(vertices, vertices, nnz_per_row, seed=seed).astype(dtype)


def normalize_scale(a: sparse.csr_matrix) -> sparse.csr_matrix:
    """Scale so iterated SpMM stays bounded (benchmark loops reuse the
    output as the next input)."""
    s = max(abs(a).sum(axis=1).max(), 1.0)
    return (a / s).tocsr().astype(a.dtype)
