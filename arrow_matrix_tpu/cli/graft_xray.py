"""``graft_xray`` — operator surface of the graft-xray fleet tracer.

Subcommands:

* ``merge`` — stitch a fleet run dir's per-process trace docs
  (``router_xray.json`` + each worker's ``xray_trace.json``, falling
  back to flight-ring recovery with ``truncated`` markers for workers
  that died mid-run) into ONE clock-offset-aligned Perfetto trace,
  ``fleet_xray.json`` — open it in ui.perfetto.dev.
* ``report`` — per-traffic-class critical-path decomposition of a
  merged trace: queue / admission / serialize / wire / worker_queue /
  compute / checkpoint / response mean ms per class.  The analyzer
  that localizes WHERE a byte-cheaper class spends the time it saves
  (BENCH_r07's bf16).  ``--ledger-dir`` appends the per-class segment
  means as ``kind="xray"`` records so the drift gate bands them.
* ``diff`` — per-class, per-segment regression check of one report
  JSON against a baseline report JSON; exits nonzero on regression.

Prints ONE JSON line as its last stdout line (CLI contract).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_xray", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("merge", help="merge a fleet run dir into one "
                                     "Perfetto trace")
    m.add_argument("run_dir")
    m.add_argument("--out", default=None,
                   help="output path (default "
                        "<run_dir>/fleet_xray.json)")

    r = sub.add_parser("report", help="per-class critical-path "
                                      "decomposition")
    r.add_argument("run_dir",
                   help="fleet run dir (uses fleet_xray.json when "
                        "present, else merges on the fly)")
    r.add_argument("--out", default=None,
                   help="write the report JSON here too")
    r.add_argument("--ledger-dir", default=None,
                   help="append per-class segment means as "
                        "kind='xray' ledger records")
    r.add_argument("--lens", default=None, metavar="PROFILE",
                   help="graft-lens profile JSON (graft_lens profile "
                        "--out): subdivide each class's compute "
                        "segment by per-level attribution (exact "
                        "class uses the f32 fractions, approx the "
                        "bf16 ones when profiled)")
    r.add_argument("--json", action="store_true",
                   help="skip the table, JSON line only")

    d = sub.add_parser("diff", help="report vs baseline report")
    d.add_argument("baseline", help="baseline report JSON "
                                    "(graft_xray report --out)")
    d.add_argument("new", help="new report JSON")
    d.add_argument("--rel-threshold", type=float, default=0.10)
    d.add_argument("--abs-floor-ms", type=float, default=1.0)
    return p


def _load_trace(run_dir: str):
    import os

    from arrow_matrix_tpu.obs import xray

    path = os.path.join(run_dir, "fleet_xray.json")
    if os.path.exists(path):
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    return xray.merge_run_dir(run_dir)


def _load_classes(run_dir: str) -> dict:
    """request_id -> served_class from the run's fleet report (the
    honest class label — a certificate-miss fallback reclassifies)."""
    import os

    try:
        with open(os.path.join(run_dir, "fleet_report.json"),
                  encoding="utf-8") as fh:
            report = json.load(fh)
    except (OSError, ValueError):
        return {}
    return {t["request_id"]: t["served_class"]
            for t in report.get("tickets", [])
            if t.get("served_class")}


def cmd_merge(args) -> int:
    import os

    from arrow_matrix_tpu.obs import xray

    trace = xray.merge_run_dir(args.run_dir)
    if args.out:
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json
        atomic_write_json(args.out, trace)
        path = args.out
    else:
        path = xray.save_fleet_trace(trace, args.run_dir)
    info = dict(trace["xray"])
    info.update({"ok": True, "cmd": "merge", "trace": path,
                 "events": len(trace["traceEvents"])})
    info.pop("offsets_ns", None)
    print(json.dumps(info, sort_keys=True))
    return 0


def cmd_report(args) -> int:
    from arrow_matrix_tpu.obs import xray

    trace = _load_trace(args.run_dir)
    cp = xray.critical_path(trace, classes=_load_classes(args.run_dir))
    if getattr(args, "lens", None):
        from arrow_matrix_tpu.obs import lens as lens_mod
        with open(args.lens, encoding="utf-8") as fh:
            profile = json.load(fh)
        dtypes = profile.get("dtypes", {})
        fractions = {}
        if "f32" in dtypes:
            fractions["exact"] = lens_mod.attribution_fractions(
                profile, "f32")
        # Approx traffic rides the bf16 carriage when it was profiled;
        # otherwise the f32 attribution is the best available shape.
        approx_fd = "bf16" if "bf16" in dtypes else "f32"
        if approx_fd in dtypes:
            fractions["approx"] = lens_mod.attribution_fractions(
                profile, approx_fd)
        cp = xray.subdivide_compute(cp, fractions)
    if not args.json:
        for line in xray.format_report(cp):
            print(line)
    if args.out:
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json
        atomic_write_json(args.out, cp, indent=2, sort_keys=True)
    if args.ledger_dir:
        from arrow_matrix_tpu.ledger import store
        for cls in sorted(cp["per_class"]):
            agg = cp["per_class"][cls]
            for name, ms in agg["segments_mean_ms"].items():
                store.record(
                    "xray", f"seg_{name}_{cls}", round(float(ms), 4),
                    directory=args.ledger_dir, unit="ms",
                    knobs={"traffic_class": cls, "segment": name,
                           "count": agg["count"]})
            store.record(
                "xray", f"iter_ms_{cls}",
                round(float(agg["mean_ms"]), 4),
                directory=args.ledger_dir, unit="ms",
                knobs={"traffic_class": cls, "count": agg["count"]})
    summary = {"ok": True, "cmd": "report",
               "requests": len(cp["requests"]),
               "per_class": {cls: {"count": agg["count"],
                                   "mean_ms": round(agg["mean_ms"], 3)}
                             for cls, agg in cp["per_class"].items()},
               "truncated_requests": sorted(
                   rid for rid, rec in cp["requests"].items()
                   if rec["truncated"])}
    print(json.dumps(summary, sort_keys=True))
    return 0


def cmd_diff(args) -> int:
    from arrow_matrix_tpu.obs import xray

    with open(args.baseline, encoding="utf-8") as fh:
        base = json.load(fh)
    with open(args.new, encoding="utf-8") as fh:
        new = json.load(fh)
    d = xray.diff_reports(base, new,
                          rel_threshold=args.rel_threshold,
                          abs_floor_ms=args.abs_floor_ms)
    for line in d["regressions"]:
        print(f"REGRESSION {line}", file=sys.stderr)
    print(json.dumps({"ok": not d["regressions"], "cmd": "diff",
                      "regressions": d["regressions"]},
                     sort_keys=True))
    return 1 if d["regressions"] else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return {"merge": cmd_merge, "report": cmd_report,
            "diff": cmd_diff}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
