"""``graft_fleet`` — run a multi-process ArrowServer fleet end to end.

Spawns N worker processes (each a full ArrowServer: supervisor,
admission, checkpoint-resume, pulse ring, run-dir ledger), routes a
deterministic synthetic trace through the
:class:`~arrow_matrix_tpu.fleet.router.FleetRouter`, and writes the
merged fleet artifacts into ``--run_dir``:

  * ``fleet_report.json`` — the merged SLO report; ``latency_ms`` is
    the EXACT pooled-quantile summary over every worker's raw
    samples, ``host_load`` records the 1-minute loadavg the run saw;
  * ``pulse_merged.json`` — the workers' pulse rings pooled via
    ``graft_pulse merge`` semantics (:func:`~arrow_matrix_tpu.obs
    .pulse.merge_rings`);
  * ``ledger/ledger.jsonl`` — every worker's run-dir ledger folded
    into one chained fleet history (kind ``fleet``);
  * ``<worker-id>/`` — each worker's own ring, ledger, summary, log.

Chaos knobs: ``--fault_worker``/``--fault_plan`` arm EXACTLY ONE
worker's environment with an ``AMT_FAULT_PLAN`` (e.g. a ``kill`` plan
on ``*.step`` — the worker SIGKILLs itself mid-batch
deterministically), which is how tools/fleet_gate.py runs the
kill-one-worker-of-N survival scenario.  The last stdout line is the
JSON verdict (the gate/doctor handshake).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from arrow_matrix_tpu.serve import request as rq


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_fleet", description=__doc__.splitlines()[0])
    p.add_argument("--run_dir", required=True)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--vertices", type=int, default=128)
    p.add_argument("--width", type=int, default=16)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--fmt", default="fold")
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--iterations", type=int, default=4)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--trace_seed", type=int, default=5)
    p.add_argument("--queue", type=int, default=64)
    p.add_argument("--hbm_budget_mb", type=float, default=0.0)
    p.add_argument("--placement", choices=("ring", "pack"),
                   default="ring")
    p.add_argument("--window_s", type=float, default=0.25)
    p.add_argument("--submit_timeout_s", type=float, default=300.0)
    p.add_argument("--results_npz", default=None,
                   help="also save completed results (request id -> "
                        "array) for bit-identity comparisons")
    p.add_argument("--fault_worker", default=None,
                   help="worker id whose environment gets "
                        "--fault_plan (chaos scenarios)")
    p.add_argument("--fault_plan", default=None,
                   help="AMT_FAULT_PLAN JSON (or a path to it) for "
                        "--fault_worker only")
    p.add_argument("--verbose", action="store_true")
    return p


def run_fleet(args) -> dict:
    from arrow_matrix_tpu.fleet.router import FleetRouter
    from arrow_matrix_tpu.ledger.store import _default_host_load
    from arrow_matrix_tpu.obs import pulse as pulse_mod
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace
    from arrow_matrix_tpu.utils.artifacts import atomic_write_json

    os.makedirs(args.run_dir, exist_ok=True)
    worker_env = None
    if args.fault_worker:
        plan = args.fault_plan or ""
        if os.path.exists(plan):
            with open(plan, encoding="utf-8") as fh:
                plan = fh.read()
        worker_env = {args.fault_worker: {"AMT_FAULT_PLAN": plan}}
    router = FleetRouter(
        spawn=args.workers, vertices=args.vertices, width=args.width,
        seed=args.seed, fmt=args.fmt, queue_capacity=args.queue,
        hbm_budget_mb=args.hbm_budget_mb,
        checkpoint_dir=os.path.join(args.run_dir, "checkpoints"),
        run_dir=args.run_dir, window_s=args.window_s,
        placement=args.placement, worker_env=worker_env,
        submit_timeout_s=args.submit_timeout_s,
        verbose=args.verbose)
    try:
        trace = synthetic_trace(
            router.n_rows, tenants=args.tenants,
            requests=args.requests, k=args.k,
            iterations=args.iterations, seed=args.trace_seed)
        if args.placement == "pack":
            router.plan_packing({r.tenant: r.k for r in trace})
        tickets = [router.submit(r) for r in trace]
        router.drain(timeout_s=args.submit_timeout_s)
        report = router.fleet_summary()
    finally:
        router.shutdown()
    report["host_load"] = _default_host_load()
    report["tickets"] = [
        {"request_id": t.request.request_id,
         "tenant": t.request.tenant, "status": t.status,
         "reason": t.reason,
         "worker_id": getattr(t, "worker_id", None),
         "requeues": getattr(t, "requeues", 0)}
        for t in tickets]
    folded = router.fold_ledgers()
    report["ledger_records_folded"] = folded

    ring_docs = []
    for wid in sorted(router.workers):
        handle = router.workers[wid]
        if not handle.obs_dir:
            continue
        ring_path = os.path.join(handle.obs_dir, "pulse_ring.json")
        if os.path.exists(ring_path):
            ring_docs.append(pulse_mod.load_ring(ring_path))
    merged_pulse = pulse_mod.merge_rings(ring_docs)
    atomic_write_json(os.path.join(args.run_dir,
                                   "pulse_merged.json"),
                      merged_pulse, indent=2, sort_keys=True)
    report["pulse_merged"] = {
        "rings": merged_pulse["rings"],
        "totals": merged_pulse["totals"],
        "problems": merged_pulse["problems"],
    }
    if args.results_npz:
        import numpy as np

        np.savez(args.results_npz,
                 **{t.request.request_id: t.result for t in tickets
                    if t.status == rq.COMPLETED
                    and t.result is not None})
        report["results_npz"] = args.results_npz
    atomic_write_json(os.path.join(args.run_dir,
                                   "fleet_report.json"),
                      report, indent=2, sort_keys=True)
    return report


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    report = run_fleet(args)
    verdict = {
        "fleet": report["fleet"],
        "workers": report["num_workers"],
        "dead_workers": report["dead_workers"],
        "requests": report["requests"],
        "completed": report["completed"],
        "failed": report["failed"],
        "shed": report["shed"],
        "rejected": report["rejected"],
        "shed_reasons": report["shed_reasons"],
        "requeues": report["requeues"],
        "requests_per_s": report["requests_per_s"],
        "latency_ms": {f: report["latency_ms"].get(f)
                       for f in ("count", "p50", "p90", "p99")},
        "host_load": report["host_load"],
        "pulse_problems": report["pulse_merged"]["problems"],
        "run_dir": args.run_dir,
    }
    print(json.dumps(verdict, sort_keys=True), flush=True)
    lost = (report["requests"] - report["completed"]
            - report["failed"] - report["shed"] - report["rejected"])
    return 0 if lost == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
