"""``graft_fleet`` — run a multi-process ArrowServer fleet end to end.

Spawns N worker processes (each a full ArrowServer: supervisor,
admission, checkpoint-resume, pulse ring, run-dir ledger), routes a
deterministic synthetic trace through the
:class:`~arrow_matrix_tpu.fleet.router.FleetRouter`, and writes the
merged fleet artifacts into ``--run_dir``:

  * ``fleet_report.json`` — the merged SLO report; ``latency_ms`` is
    the EXACT pooled-quantile summary over every worker's raw
    samples, ``host_load`` records the 1-minute loadavg the run saw;
  * ``pulse_merged.json`` — the workers' pulse rings pooled via
    ``graft_pulse merge`` semantics (:func:`~arrow_matrix_tpu.obs
    .pulse.merge_rings`);
  * ``ledger/ledger.jsonl`` — every worker's run-dir ledger folded
    into one chained fleet history (kind ``fleet``);
  * ``<worker-id>/`` — each worker's own ring, ledger, summary, log.

Chaos knobs: ``--fault_worker``/``--fault_plan`` arm EXACTLY ONE
worker's environment with an ``AMT_FAULT_PLAN`` (e.g. a ``kill`` plan
on ``*.step`` — the worker SIGKILLs itself mid-batch
deterministically), which is how tools/fleet_gate.py runs the
kill-one-worker-of-N survival scenario.  The last stdout line is the
JSON verdict (the gate/doctor handshake).

graft-host: ``--hosts H`` groups the workers into H host fault
domains (contiguous blocks, spawn env ``AMT_HOST_ID``); the router
resolves the wire per domain (same host -> shm descriptors, cross
host -> raw framing; ``--transport`` overrides).  ``--fault_host``
arms EVERY worker of one domain with the same fault plan — the
kill-a-host rung: a whole domain SIGKILLs mid-batch and the
survivors must absorb its work with zero accepted-request loss.
``--measure_wire`` additionally benchmarks all three transports over
a local socketpair and records ``serialize_ms_per_mb_<transport>``
in the run ledger, the banded evidence that the shm path stays
cheaper than base64.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from arrow_matrix_tpu.serve import request as rq


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_fleet", description=__doc__.splitlines()[0])
    p.add_argument("--run_dir", required=True)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--vertices", type=int, default=128)
    p.add_argument("--width", type=int, default=16)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--fmt", default="fold")
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--iterations", type=int, default=4)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--trace_seed", type=int, default=5)
    p.add_argument("--queue", type=int, default=64)
    p.add_argument("--hbm_budget_mb", type=float, default=0.0)
    p.add_argument("--placement", choices=("ring", "pack"),
                   default="ring")
    p.add_argument("--window_s", type=float, default=0.25)
    p.add_argument("--submit_timeout_s", type=float, default=300.0)
    p.add_argument("--results_npz", default=None,
                   help="also save completed results (request id -> "
                        "array) for bit-identity comparisons")
    p.add_argument("--hosts", type=int, default=1,
                   help="host fault domains to split the workers "
                        "into (graft-host; contiguous blocks)")
    p.add_argument("--transport", default="auto",
                   choices=("auto", "json", "raw", "shm"),
                   help="wire transport override (auto: same-host "
                        "shm, cross-host raw)")
    p.add_argument("--fault_worker", default=None,
                   help="worker id whose environment gets "
                        "--fault_plan (chaos scenarios)")
    p.add_argument("--fault_host", default=None,
                   help="host domain id (e.g. host-1) whose EVERY "
                        "worker gets --fault_plan — the kill-a-host "
                        "rung")
    p.add_argument("--fault_plan", default=None,
                   help="AMT_FAULT_PLAN JSON (or a path to it) for "
                        "--fault_worker / --fault_host only")
    p.add_argument("--kill_host", default=None,
                   help="router-side kill-a-host rung: once the batch "
                        "is mid-flight, SIGKILL every worker of this "
                        "domain AT ONCE and heartbeat-probe the "
                        "victims to a dead verdict")
    p.add_argument("--measure_wire", action="store_true",
                   help="benchmark json/raw/shm over a socketpair "
                        "and record serialize_ms_per_mb_<transport> "
                        "in the run ledger")
    p.add_argument("--verbose", action="store_true")
    return p


def run_fleet(args) -> dict:
    from arrow_matrix_tpu.fleet.router import FleetRouter
    from arrow_matrix_tpu.ledger import store as ledger_store
    from arrow_matrix_tpu.ledger.store import _default_host_load
    from arrow_matrix_tpu.obs import pulse as pulse_mod
    from arrow_matrix_tpu.obs import xray as xray_mod
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace
    from arrow_matrix_tpu.utils.artifacts import atomic_write_json

    os.makedirs(args.run_dir, exist_ok=True)
    if args.fault_worker and getattr(args, "fault_host", None):
        raise SystemExit("pass --fault_worker or --fault_host, "
                         "not both")
    worker_env = None
    if args.fault_worker or getattr(args, "fault_host", None):
        plan = args.fault_plan or ""
        if os.path.exists(plan):
            with open(plan, encoding="utf-8") as fh:
                plan = fh.read()
        if args.fault_worker:
            worker_env = {args.fault_worker: {"AMT_FAULT_PLAN": plan}}
        else:
            # Arm the WHOLE domain: worker i of n lives in
            # host-{i*hosts//n} (the router's contiguous-block split).
            n, hosts = args.workers, max(1, int(args.hosts))
            victims = [f"worker-{i}" for i in range(n)
                       if f"host-{i * min(hosts, n) // n}"
                       == args.fault_host]
            if not victims:
                raise SystemExit(f"--fault_host {args.fault_host!r} "
                                 f"matches no worker (workers={n}, "
                                 f"hosts={hosts})")
            worker_env = {wid: {"AMT_FAULT_PLAN": plan}
                          for wid in victims}
    router = FleetRouter(
        spawn=args.workers, vertices=args.vertices, width=args.width,
        seed=args.seed, fmt=args.fmt, queue_capacity=args.queue,
        hbm_budget_mb=args.hbm_budget_mb,
        checkpoint_dir=os.path.join(args.run_dir, "checkpoints"),
        run_dir=args.run_dir, window_s=args.window_s,
        placement=args.placement,
        hosts=getattr(args, "hosts", 1),
        transport=getattr(args, "transport", "auto"),
        worker_env=worker_env,
        submit_timeout_s=args.submit_timeout_s,
        verbose=args.verbose)
    try:
        trace = synthetic_trace(
            router.n_rows, tenants=args.tenants,
            requests=args.requests, k=args.k,
            iterations=args.iterations, seed=args.trace_seed)
        if args.placement == "pack":
            router.plan_packing({r.tenant: r.k for r in trace})
        tickets = [router.submit(r) for r in trace]
        killed_hosts = []
        if getattr(args, "kill_host", None):
            # Mid-batch on purpose, and timed so the survivors can
            # RESUME rather than recompute: wait until some request
            # dispatched to the doomed domain is still in flight AND
            # has a checkpoint on the shared dir (per-request
            # ``ck_<request_id>`` keys), then take the whole domain
            # down in one sweep.  The deaths are then probed to a
            # verdict through the REAL heartbeat ladder — the same
            # wire discovery a dispatch failure triggers — so the
            # burial is deterministic for the gate without
            # short-circuiting health.
            domain = set(router.host_map().get(args.kill_host) or [])
            ck_dir = os.path.join(args.run_dir, "checkpoints")

            def _resumable_in_flight():
                for t in tickets:
                    if t.status in rq.TERMINAL:
                        continue
                    if getattr(t, "worker_id", None) not in domain:
                        continue
                    # The FINAL checkpoint path only (orbax writes a
                    # *-tmp-* then renames atomically): its existence
                    # means a COMPLETE save a survivor can resume;
                    # matching the tmp file would fire the kill
                    # mid-write, before anything is resumable.
                    if os.path.exists(os.path.join(
                            ck_dir, f"ck_{t.request.request_id}")):
                        return True
                return False

            deadline = time.monotonic() + args.submit_timeout_s
            while time.monotonic() < deadline:
                if _resumable_in_flight():
                    break
                if all(t.status in rq.TERMINAL for t in tickets):
                    break   # batch outran the kill; fire anyway
                time.sleep(0.005)
            victims = router.kill_host(args.kill_host)
            killed_hosts.append(args.kill_host)
            for wid in victims:
                router._on_worker_failure(
                    wid, f"host domain {args.kill_host} killed")
        router.drain(timeout_s=args.submit_timeout_s)
        report = router.fleet_summary()
        # The router's own trace doc goes to disk while the router is
        # still alive; workers write theirs on graceful close (during
        # shutdown), and a SIGKILLed worker leaves its flight ring —
        # merge_run_dir below stitches whichever survived.
        xray_mod.save_router_trace(router.tracer, args.run_dir)
    finally:
        router.shutdown()
    report["host_load"] = _default_host_load()
    report["killed_hosts"] = killed_hosts
    report["tickets"] = [
        {"request_id": t.request.request_id,
         "tenant": t.request.tenant, "status": t.status,
         "reason": t.reason,
         "worker_id": getattr(t, "worker_id", None),
         "requeues": getattr(t, "requeues", 0),
         "served_class": getattr(t, "served_class", None),
         "trace_id": (t.trace or {}).get("trace_id")}
        for t in tickets]
    folded = router.fold_ledgers()
    report["ledger_records_folded"] = folded

    # graft-xray: ONE merged fleet trace (router + every worker track,
    # clock-offset aligned, dead workers recovered truncated), the
    # per-class critical-path report over it, and the wire cost totals
    # as banded first-class ledger metrics.
    trace_doc = xray_mod.merge_run_dir(args.run_dir, report=report)
    trace_path = xray_mod.save_fleet_trace(trace_doc, args.run_dir)
    classes = {t["request_id"]: t["served_class"]
               for t in report["tickets"] if t["served_class"]}
    cp = xray_mod.critical_path(trace_doc, classes=classes)
    atomic_write_json(os.path.join(args.run_dir, "xray_report.json"),
                      cp, indent=2, sort_keys=True)
    report["xray"] = {
        "trace": trace_path,
        "processes": trace_doc["xray"]["processes"],
        "truncated": trace_doc["xray"]["truncated"],
        "per_class": {cls: {"count": agg["count"],
                            "mean_ms": agg.get("mean_ms"),
                            "segments_mean_ms":
                                agg.get("segments_mean_ms")}
                      for cls, agg in cp["per_class"].items()},
    }
    tot = report.get("wire", {}).get("totals") or {}
    shape_tag = (f"fleet_w{args.workers}_n{args.vertices}"
                 f"_r{args.requests}_k{args.k}")
    for metric, value, unit in (
            ("wire_bytes",
             tot.get("bytes_out", 0) + tot.get("bytes_in", 0), "B"),
            ("wire_ms", tot.get("wire_ms"), "ms"),
            ("serialize_ms", tot.get("serialize_ms"), "ms")):
        ledger_store.record(
            "fleet", metric, value,
            directory=os.path.join(args.run_dir, "ledger"),
            unit=unit, structure_hash=shape_tag,
            knobs={"fleet": report["fleet"],
                   "workers": args.workers,
                   "requests": args.requests,
                   "frames": tot.get("frames"),
                   "hosts": getattr(args, "hosts", 1),
                   "payload_bytes": tot.get("payload_bytes"),
                   "shm_bytes": tot.get("shm_bytes")})
    if getattr(args, "measure_wire", False):
        # Same payload, three wires, one socketpair: the banded proof
        # that shm descriptor passing stays cheaper than the base64
        # envelope (and how close it gets to raw framing).
        from arrow_matrix_tpu.fleet import wire as wire_mod
        measured = wire_mod.measure_transports()
        report["wire_measured"] = measured
        for transport in ("base64", "raw", "shm"):
            ledger_store.record(
                "fleet", f"serialize_ms_per_mb_{transport}",
                round(float(
                    measured[transport]["serialize_ms_per_mb"]), 4),
                directory=os.path.join(args.run_dir, "ledger"),
                unit="ms", structure_hash="wire_1mb",
                knobs={"transport": transport,
                       "frame_bytes":
                           measured[transport]["frame_bytes"]})

    ring_docs = []
    for wid in sorted(router.workers):
        handle = router.workers[wid]
        if not handle.obs_dir:
            continue
        ring_path = os.path.join(handle.obs_dir, "pulse_ring.json")
        if os.path.exists(ring_path):
            ring_docs.append(pulse_mod.load_ring(ring_path))
    merged_pulse = pulse_mod.merge_rings(ring_docs)
    atomic_write_json(os.path.join(args.run_dir,
                                   "pulse_merged.json"),
                      merged_pulse, indent=2, sort_keys=True)
    report["pulse_merged"] = {
        "rings": merged_pulse["rings"],
        "totals": merged_pulse["totals"],
        "problems": merged_pulse["problems"],
    }
    if args.results_npz:
        import numpy as np

        np.savez(args.results_npz,
                 **{t.request.request_id: t.result for t in tickets
                    if t.status == rq.COMPLETED
                    and t.result is not None})
        report["results_npz"] = args.results_npz
    atomic_write_json(os.path.join(args.run_dir,
                                   "fleet_report.json"),
                      report, indent=2, sort_keys=True)
    return report


def build_migrate_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graft_fleet migrate",
        description="Rebalance one tenant between fleet workers via "
                    "staged checkpoint handoff on the shared "
                    "checkpoint dir (FleetRouter.migrate).")
    p.add_argument("--run_dir", required=True)
    p.add_argument("--tenant", default="tenant1",
                   help="synthetic_trace tenant id to rebalance "
                        "(tenant1 owns phase-1 requests at the "
                        "default trace_seed, so its checkpoints are "
                        "on the shared dir when the handoff runs)")
    p.add_argument("--to_worker", default=None,
                   help="destination worker id (default: the ring's "
                        "next live candidate)")
    p.add_argument("--dry-run", dest="dry_run", action="store_true",
                   help="print the staged handoff plan and per-stage "
                        "bytes without rewriting checkpoints or "
                        "moving the tenant")
    p.add_argument("--scratch_budget_kb", type=float, default=64.0,
                   help="per-endpoint per-stage handoff scratch "
                        "budget")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--vertices", type=int, default=128)
    p.add_argument("--width", type=int, default=16)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--fmt", default="fold")
    p.add_argument("--tenants", type=int, default=2)
    p.add_argument("--requests", type=int, default=4,
                   help="requests per phase (pre- and post-migration)")
    p.add_argument("--iterations", type=int, default=4)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--trace_seed", type=int, default=5)
    p.add_argument("--submit_timeout_s", type=float, default=300.0)
    p.add_argument("--verbose", action="store_true")
    return p


def run_migrate(args) -> dict:
    """The tenant-rebalance path end to end: phase 1 routes requests
    (writing per-request checkpoints onto the shared dir), the router
    migrates the tenant — staged handoff plans over those checkpoints,
    then a placement pin — and phase 2 proves every subsequent request
    of that tenant lands on the destination worker.  ``--dry-run``
    stops after printing the plans: nothing is rewritten or repinned.
    """
    from arrow_matrix_tpu.fleet.router import FleetRouter
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace
    from arrow_matrix_tpu.utils.artifacts import atomic_write_json

    os.makedirs(args.run_dir, exist_ok=True)
    router = FleetRouter(
        spawn=args.workers, vertices=args.vertices, width=args.width,
        seed=args.seed, fmt=args.fmt,
        checkpoint_dir=os.path.join(args.run_dir, "checkpoints"),
        run_dir=args.run_dir,
        submit_timeout_s=args.submit_timeout_s,
        verbose=args.verbose)
    try:
        trace = synthetic_trace(
            router.n_rows, tenants=args.tenants,
            requests=2 * args.requests, k=args.k,
            iterations=args.iterations, seed=args.trace_seed)
        phase1, phase2 = trace[:args.requests], trace[args.requests:]
        t1 = [router.submit(r) for r in phase1]
        router.drain(timeout_s=args.submit_timeout_s)

        migration = router.migrate(
            args.tenant, args.to_worker,
            scratch_budget_bytes=int(args.scratch_budget_kb * 1024),
            dry_run=args.dry_run)
        for h in migration["checkpoints"]:
            print(h["plan"], flush=True)
        if not migration["checkpoints"]:
            print(f"[graft-fleet] tenant {args.tenant} has no "
                  f"checkpoints on the shared dir (phase 1 routed "
                  f"none of its requests?)", flush=True)

        t2 = []
        if not args.dry_run:
            t2 = [router.submit(r) for r in phase2]
            router.drain(timeout_s=args.submit_timeout_s)
        tickets = t1 + t2
        summary = router.fleet_summary()
    finally:
        router.shutdown()

    post = [t for t in t2 if t.request.tenant == args.tenant]
    on_dst = [t for t in post
              if getattr(t, "worker_id", None)
              == migration["to_worker"]]
    report = {
        "migration": migration,
        "phase1_completed": sum(t.status == rq.COMPLETED for t in t1),
        "phase2_completed": sum(t.status == rq.COMPLETED for t in t2),
        "post_migration_tenant_requests": len(post),
        "post_migration_on_destination": len(on_dst),
        "requests": len(tickets),
        "completed": summary["completed"],
        "failed": summary["failed"],
        "shed": summary["shed"],
        "rejected": summary["rejected"],
        "migrations": summary["migrations"],
        "tenant_pins": summary["tenant_pins"],
        "run_dir": args.run_dir,
    }
    atomic_write_json(os.path.join(args.run_dir,
                                   "migrate_report.json"),
                      report, indent=2, sort_keys=True)
    return report


def main_migrate(argv=None) -> int:
    args = build_migrate_parser().parse_args(argv)
    report = run_migrate(args)
    verdict = {key: report[key] for key in
               ("phase1_completed", "phase2_completed",
                "post_migration_tenant_requests",
                "post_migration_on_destination", "requests",
                "completed", "failed", "shed", "rejected",
                "migrations", "tenant_pins", "run_dir")}
    verdict["migration"] = {
        key: report["migration"][key] for key in
        ("tenant", "from_worker", "to_worker", "dry_run",
         "total_stages", "moved_bytes", "scratch_budget_bytes")}
    verdict["migration"]["checkpoints"] = [
        {key: h[key] for key in
         ("checkpoint", "rows", "k", "n_stages", "stage_bytes",
          "moved_bytes")}
        for h in report["migration"]["checkpoints"]]
    print(json.dumps(verdict, sort_keys=True), flush=True)
    lost = (report["requests"] - report["completed"]
            - report["failed"] - report["shed"] - report["rejected"])
    strayed = (report["post_migration_tenant_requests"]
               - report["post_migration_on_destination"])
    if report["migration"]["dry_run"]:
        strayed = 0
    return 0 if (lost == 0 and strayed == 0) else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "migrate":
        return main_migrate(argv[1:])
    args = build_parser().parse_args(argv)
    report = run_fleet(args)
    verdict = {
        "fleet": report["fleet"],
        "workers": report["num_workers"],
        "dead_workers": report["dead_workers"],
        "hosts": report.get("hosts"),
        "live_hosts": report.get("live_hosts"),
        "killed_hosts": report.get("killed_hosts"),
        "transports": report.get("transports"),
        "wire_shm_bytes": (report.get("wire", {}).get("totals")
                           or {}).get("shm_bytes"),
        "wire_measured": {
            t: {"serialize_ms_per_mb":
                    round(float(m["serialize_ms_per_mb"]), 4)}
            for t, m in (report.get("wire_measured") or {}).items()},
        "requests": report["requests"],
        "completed": report["completed"],
        "failed": report["failed"],
        "shed": report["shed"],
        "rejected": report["rejected"],
        "shed_reasons": report["shed_reasons"],
        "requeues": report["requeues"],
        "requests_per_s": report["requests_per_s"],
        "latency_ms": {f: report["latency_ms"].get(f)
                       for f in ("count", "p50", "p90", "p99")},
        "host_load": report["host_load"],
        "pulse_problems": report["pulse_merged"]["problems"],
        "run_dir": args.run_dir,
    }
    print(json.dumps(verdict, sort_keys=True), flush=True)
    lost = (report["requests"] - report["completed"]
            - report["failed"] - report["shed"] - report["rejected"])
    return 0 if lost == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
