"""``spmm_15d`` — 1.5D A-stationary baseline benchmark.

Counterpart of the reference's 1.5D entry point
(reference scripts/spmm_15d_main.py:20-276): random or file matrix,
auto replication factor, optional result validation against ``A @ X``
on the host, timed iteration loop.  (The reference's benchmark loop
as written raises NameError — SURVEY.md §7 known bugs — so the timing
protocol here follows its ``--validate`` path's working kernel calls.)
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from arrow_matrix_tpu.cli.common import (
    add_device_args,
    add_distributed_args,
    add_heal_args,
    load_sparse_matrix,
    make_supervisor,
    normalize_scale,
    random_adjacency,
    setup_platform,
    str2bool,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="SpMM 1.5D benchmark.")
    parser.add_argument("-d", "--dataset", nargs="?",
                        choices=["random", "file"], default="random")
    parser.add_argument("-s", "--seed", type=int, default=42)
    parser.add_argument("-v", "--vertices", type=int, default=100_000)
    parser.add_argument("-e", "--edges", type=int, default=1_000_000)
    parser.add_argument("-f", "--file", type=str, default=None,
                        help="Sparse matrix file (.npz/.mtx/.mat), or "
                             "with --memmap the BASE of an npy CSR "
                             "triplet (BASE_indptr.npy, BASE_indices"
                             ".npy, optional BASE_data.npy).")
    parser.add_argument("--memmap", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Memory-map --file as an npy CSR triplet "
                             "and build slab-by-slab, never holding "
                             "the matrix in RAM (the reference's "
                             "generate_15d_decomposition_new ingest, "
                             "spmm_15d.py:158-309).  Skips the "
                             "iterate-boundedness normalization (the "
                             "reference does not normalize either); "
                             "--validate computes the golden by "
                             "streaming slabs.")
    parser.add_argument("-c", "--columns", type=int, default=128,
                        help="Feature columns of X.")
    parser.add_argument("-r", "--replication", type=int, default=0,
                        help="Replication factor c; 0 = largest valid "
                             "power of two (spmm_15d_main.py:87-96).")
    parser.add_argument("--repl", type=str, default=None,
                        choices=["auto", "1", "2", "4"],
                        help="graft-repl spelling of -r/--replication "
                             "(one flag name across the SpMM CLIs): an "
                             "explicit c, or 'auto' for the largest "
                             "structurally valid factor whose ×c "
                             "replicated-operator footprint the HBM "
                             "budget certifies (obs/comm planner; "
                             "AMT_HBM_GB overrides the budget).  A "
                             "budget that rejects every c>1 degrades "
                             "LOUDLY to c=1.  Unlike -r 0's purely "
                             "structural pick, 'auto' never plans an "
                             "OOM.")
    parser.add_argument("--validate", type=str2bool, nargs="?", default=True)
    parser.add_argument("-m", "--memory", type=float, default=0.5,
                        help="Fraction of currently-free device memory "
                             "budgeted for kernel intermediates "
                             "(slot-chunk auto-tiling; the reference's "
                             "--gpu-tiling analog, spmm_15d.py:371-449)."
                             "  <= 0 disables chunking.")
    parser.add_argument("-z", "--iterations", type=int, default=10)
    parser.add_argument("--carry", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Carry X across iterations (X := A @ X "
                             "propagation; the blocked result is "
                             "gathered and re-distributed each "
                             "iteration — the 1.5D output layout "
                             "differs from its input layout) instead "
                             "of timing the same input repeatedly.")
    add_heal_args(parser)
    parser.add_argument("--logdir", type=str, default="./logs")
    parser.add_argument("--comm_report", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Account the per-iteration collective "
                             "bytes of the compiled step from its HLO "
                             "(compare against spmm_arrow's modes — "
                             "the reference paper's headline metric).")
    parser.add_argument("--mem_report", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Report the compiled step's per-device "
                             "memory breakdown against the format-"
                             "metadata prediction, plus the per-shard "
                             "load-imbalance report.")
    add_device_args(parser)
    add_distributed_args(parser)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.checkpoint and not args.carry:
        # Pure flag error: fail before any build/compile work.
        raise SystemExit("--checkpoint requires --carry (there is no "
                         "iteration state to resume when X is the "
                         "same input every iteration)")
    setup_platform(args)

    import jax

    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.spmm_15d import SpMM15D, largest_replication
    from arrow_matrix_tpu.utils import logging as wb
    from arrow_matrix_tpu.utils.graphs import random_dense

    if args.memmap:
        if not args.file:
            raise SystemExit("--memmap requires --file BASE (npy "
                             "triplet: BASE_indptr.npy, ...)")
        import os

        def _mm(suffix, required=True):
            p = f"{args.file}_{suffix}.npy"
            if not os.path.exists(p):
                if required:
                    raise SystemExit(f"missing triplet member {p}")
                return None
            return np.load(p, mmap_mode="r")

        a = (_mm("data", required=False), _mm("indices"), _mm("indptr"))
        name = os.path.basename(args.file)
    elif args.dataset == "file" or args.file:
        if not args.file:
            raise SystemExit("--dataset file requires --file")
        a = normalize_scale(load_sparse_matrix(args.file))
        import os

        name = os.path.basename(args.file)
    else:
        a = normalize_scale(
            random_adjacency(args.vertices, args.edges, args.seed))
        name = f"random_{args.vertices}_{args.edges}"

    n_dev = len(jax.devices())
    if args.repl is not None:
        if args.replication:
            raise SystemExit("--repl and -r/--replication set the same "
                             "factor; give one")
        if args.repl == "auto":
            # HBM-certified structural maximum: the 1.5D scheme
            # replicates this device's A shard ×c, so the planner is
            # the same base×c-fits-budget certificate as spmm_arrow's
            # 2.5D mode (memview.largest_fitting_repl), filtered by
            # the reference's c^2 | n_dev divisibility rule.
            import sys

            from arrow_matrix_tpu.obs.comm import hbm_budget_bytes
            from arrow_matrix_tpu.obs.memview import largest_fitting_repl

            nnz = int(a.nnz) if hasattr(a, "nnz") else int(a[1].size)
            rows = int(a.shape[0]) if hasattr(a, "shape") \
                else int(a[2].size - 1)
            base_est = (nnz * 8 // max(n_dev, 1)
                        + 2 * (-(-rows // max(n_dev, 1)))
                        * args.columns * 4)
            budget = hbm_budget_bytes()
            structural = [cc for cc in (1, 2, 4, 8)
                          if cc <= largest_replication(n_dev)
                          and n_dev % (cc * cc) == 0]
            c_fit = largest_fitting_repl(base_est, budget, structural)
            if c_fit == 1 and max(structural) > 1:
                print(f"[graft-repl] auto replication DEGRADED to "
                      f"c=1: base footprint ~{base_est} B x c exceeds "
                      f"the HBM budget {budget / 2**30:.2f} GiB for "
                      f"every structural c {structural[1:]} (set "
                      f"AMT_HBM_GB to raise)", file=sys.stderr)
            else:
                print(f"--repl auto plan: c={c_fit} (structural "
                      f"candidates {structural}, base ~{base_est} B "
                      f"per device, budget "
                      f"{budget / 2**30:.2f} GiB)")
            args.replication = c_fit
        else:
            args.replication = int(args.repl)
    c = args.replication or largest_replication(n_dev)
    if n_dev % (c * c) != 0:
        raise SystemExit(
            f"device count {n_dev} not divisible by c^2 = {c * c} "
            f"(reference divisibility rule, spmm_15d.py:34-40)")
    mesh = make_mesh((n_dev // c, c), ("rows", "repl"))
    print(f"grid {n_dev // c} x {c} on {n_dev} "
          f"{jax.devices()[0].platform} device(s)")

    wb.init(f"15D_TPU_c_{c}", name, config=vars(args))
    with wb.segment("build_time"):
        dist = SpMM15D(
            a, mesh,
            chunk="auto" if args.memory > 0 else None,
            memory_fraction=args.memory if args.memory > 0 else 0.5)

    n = dist.shape[1]
    x_host = random_dense(n, args.columns, seed=args.seed)
    x = dist.set_features(x_host)

    if args.validate:
        from arrow_matrix_tpu.utils import numerics

        got = dist.gather_result(dist.spmm(x))
        if args.memmap:
            # Streaming golden: the global matrix never exists in RAM.
            from arrow_matrix_tpu.parallel.spmm_15d import _slab_source

            _, _, slab_of = _slab_source(a, np.float32)
            want = np.empty_like(x_host)
            nnz = 0
            step_rows = max(dist.l_ni, 1)
            for lo in range(0, n, step_rows):
                slab = slab_of(lo, min(n, lo + step_rows))
                want[lo:lo + slab.shape[0]] = slab @ x_host
                nnz += int(slab.nnz)
        else:
            want = np.asarray(a @ x_host)
            nnz = a.nnz
        err = numerics.relative_error(got, want)
        tol = numerics.relative_tolerance(nnz / max(n, 1), iters=1)
        ok = bool(np.isfinite(err) and err <= tol)
        print(f"validation: ok={ok} rel frobenius err={err:.3e} "
              f"(gate {tol:.1e}; spmm_15d_main.py:195-197 protocol, "
              f"tolerance per utils/numerics.py)")
        wb.log({"frobenius_err": float(err)})
        if not ok:
            wb.finish(args.logdir)
            return 1

    y = dist.spmm(x)  # compile + warmup
    jax.block_until_ready(y)
    if args.comm_report:
        from arrow_matrix_tpu import obs
        from arrow_matrix_tpu.utils import commstats

        # repl is recorded for the obs schema; reduce_bytes stays 0 —
        # the 1.5D scheme's reduction is the per-step all-reduce
        # already inside the measured bytes, not a deferred merge.
        rep = obs.account_collectives(
            "spmm_15d", dist._step, dist.a_cols, dist.a_data, x,
            ideal_bytes=obs.ideal_bytes_for(dist, args.columns),
            repl=c, reduce_bytes=obs.reduce_bytes_for(dist, args.columns))
        print(f"per-iteration collective bytes ({rep['source']} HLO):")
        print(commstats.format_stats(rep["collectives"]))
        if rep["ratio"] is not None:
            print(f"measured vs paper-model ideal: "
                  f"{rep['measured_bytes']} / {rep['ideal_bytes']} "
                  f"bytes = {rep['ratio']:.2f}x")
    if args.mem_report:
        from arrow_matrix_tpu import obs

        mem = obs.account_memory(
            "spmm_15d", dist._step, dist.a_cols, dist.a_data, x,
            predicted_bytes=obs.predicted_bytes_for(dist, args.columns))
        print(obs.format_memory_report(mem))
        imb = obs.account_imbalance("spmm_15d", dist)
        if imb is not None:
            print(obs.format_imbalance_report(imb))
    if args.carry and dist.shape[0] != dist.shape[1]:
        raise SystemExit(f"--carry needs a square matrix (X := A @ X); "
                         f"have {dist.shape}")
    sup = make_supervisor(args, "spmm_15d", carry=args.carry,
                          layout=f"15d/c{c}/blocked_input")
    start_it = 0
    if args.carry and args.checkpoint:
        state = sup.resume(like=x)
        if state is not None:
            x, start_it = state
            print(f"resumed from {args.checkpoint} at iteration "
                  f"{start_it}")

    def body(xb, it):
        wb.set_iteration_data({"iteration": it})
        tic = time.perf_counter()
        yb = dist.spmm(xb)
        jax.block_until_ready(yb)
        wb.log({"spmm_time": time.perf_counter() - tic})
        if not args.carry:
            return yb
        # The 1.5D output layout (p/c, c, l_ni, k) differs from the
        # input layout — re-distribute outside the timed window (the
        # reference benchmark never carries; checkpoint/resume needs a
        # stable input-layout state).
        return dist.set_features(dist.gather_result(yb))

    _, ok = sup.run(body, x, start_it, args.iterations)

    s = wb.get_log().summarize().get("spmm_time")
    if s:
        print(f"spmm_time mean {s['mean'] * 1e3:.3f} ms over "
              f"{s['count']} iterations (min {s['min'] * 1e3:.3f})")
    out = wb.finish(args.logdir)
    if out:
        print(f"log written to {out}.json")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
