"""``spmm_arrow`` — the distributed arrow SpMM benchmark.

Counterpart of the reference's main benchmark entry point
(reference scripts/spmm_arrow_main.py + arrow/arrow_bench.py:12-137):
with no ``--path``, generate a Barabasi-Albert graph, decompose and save
it; load the decomposition, build the distributed runtime, run the
iteration loop with per-segment timing and failure detection, flush the
log.

Differences by design (single SPMD process instead of mpiexec ranks):
``--ranksperside`` becomes the mesh size (``--devices``); rank-budget
validation (arrow_bench.py:64-78) becomes block-count/mesh divisibility
handled by padding; the per-iteration collective failure allreduce
(arrow_bench.py:128-134) becomes a host-side try/except around the step
— device errors surface synchronously at block_until_ready, and there
is exactly one host to abort.
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

from arrow_matrix_tpu.cli.common import (
    add_device_args,
    add_distributed_args,
    add_heal_args,
    make_supervisor,
    setup_platform,
    str2bool,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="Arrow SpMM benchmark.")
    parser.add_argument("-f", "--path", type=str, default=None,
                        help="Decomposition artifact base path (no "
                             "extension).  Default: generate a random "
                             "graph, decompose, and benchmark that "
                             "(arrow_bench.py:28-41).")
    parser.add_argument("-w", "--width", type=int, default=0,
                        help="Width of the decomposition / block height.")
    parser.add_argument("-c", "--features", type=int, default=16,
                        help="Number of feature columns of X.")
    parser.add_argument("-z", "--iterations", type=int, default=1,
                        help="Number of SpMM iterations.")
    parser.add_argument("-v", "--vertices", type=int, default=10_000,
                        help="Vertices of the generated graph (no --path).")
    parser.add_argument("-m", "--ba_neighbors", type=int, default=3,
                        help="Barabasi-Albert attachment count "
                             "(spmm_arrow_main.py:22).")
    parser.add_argument("-s", "--slim", type=str2bool, nargs="?",
                        default=True,
                        help="Layout (reference spmm_arrow_main.py:25-26): "
                             "true = slim (one block-row group per "
                             "device, the default); false = wide (the "
                             "reference's 2t-1-rank row/column split, "
                             "arrow_mpi.py:31-69) — runs the multi-"
                             "level step on a (arm=2, blocks) mesh "
                             "with disjoint head-row and column-block "
                             "device groups; needs an even device "
                             "count >= 4, --mode time, a stacked "
                             "format and --routing gather.  slim=True "
                             "requires --blocked (the reference's "
                             "constraint, arrow_dec_mpi.py:131).")
    parser.add_argument("-b", "--blocked", type=str2bool, nargs="?",
                        default=None, const=True,
                        help="Block-diagonal decomposition (required for "
                             "slim, arrow_dec_mpi.py:131).  Default: "
                             "true.")
    parser.add_argument("--fmt", type=str, default=None,
                        choices=["auto", "dense", "ell", "hyb", "fold",
                                 "sell"],
                        help="Device block format (TPU-specific: dense = "
                             "MXU batched matmuls, ell = gather path, "
                             "hyb = whole-level split-ELL, fold = the "
                             "whole decomposition composed into one "
                             "degree-sorted sliced-ELL operator with "
                             "zero inter-level routing (single-chip, "
                             "like hyb), sell = the padding-free "
                             "feature-major mesh orchestration "
                             "(SellMultiLevel time-shared, "
                             "SellSpaceShared with --mode space; mesh "
                             "only).  Default: the measured-best mode "
                             "for the hardware found at runtime — fold "
                             "on one chip (14.6x vs scipy at protocol "
                             "scale), sell on a mesh (lowest ms/iter "
                             "AND collective bytes in the mode race).")
    parser.add_argument("--feature_dtype", type=str, default=None,
                        choices=["f32", "bf16"],
                        help="Carried-feature storage dtype (fold and "
                             "sell formats): bf16 halves gathered-row "
                             "and collective bytes with f32 "
                             "accumulation (~1e-3 rel err/step; the "
                             "--validate gate widens accordingly).")
    parser.add_argument("--head_fmt", type=str, default="auto",
                        choices=["auto", "flat", "ell", "gell"],
                        help="Head-stack storage for ELL levels: flat "
                             "(scatter-add, O(nnz)), ell (per-block "
                             "gather), gell (global-row gather; "
                             "single-chip only), auto (platform-aware).")
    parser.add_argument("--mode", type=str, default="time",
                        choices=["time", "space"],
                        help="Multi-matrix execution mode: 'time' sweeps "
                             "the levels sequentially on the full mesh "
                             "(MultiLevelArrow); 'space' runs them "
                             "concurrently on disjoint device groups "
                             "(SpaceSharedArrow — the reference's "
                             "per-matrix rank groups, "
                             "arrow_dec_mpi.py:106-177; needs the "
                             "device count divisible by the level "
                             "count).")
    parser.add_argument("--routing", type=str, default=None,
                        choices=["gather", "a2a"],
                        help="Inter-level exchange lowering (time-shared "
                             "mode): 'gather' lets GSPMD lower the "
                             "permutation gathers (may all-gather), "
                             "'a2a' uses explicit precomputed "
                             "send/recv tables over all_to_all "
                             "(O(moved rows) volume; the reference's "
                             "Alltoallv tables, "
                             "arrow_dec_mpi.py:210-281).  Default: a2a "
                             "for the sell mesh orchestration (the "
                             "measured comm-volume winner, 0.70 MB vs "
                             "1.79 MB/iter at the report config), "
                             "gather otherwise.")
    parser.add_argument("--ladder", type=str, default="default",
                        choices=["default", "tight"],
                        help="Degree-ladder tiering for the sell mesh "
                             "layouts: 'default' (growth 1.5, align 8 "
                             "— few tiers, tile-friendly) or 'tight' "
                             "(growth 1.3, align 1 — ~3.4x fewer "
                             "padded gather slots on block-diagonal "
                             "levels, ~2x the tiers; the gather cost "
                             "model favors it, pending a real "
                             "multi-chip race).")
    parser.add_argument("--repl", type=str, default="1",
                        choices=["auto", "1", "2", "4"],
                        help="2.5D replication factor c (graft-repl): "
                             "each of the c replica groups owns a "
                             "static k/c feature slab, cutting every "
                             "per-step exchange's bytes by c at c-fold "
                             "operator memory plus one masked-psum "
                             "merge at gather time.  Composes with "
                             "--fmt sell on a mesh (c must divide the "
                             "device count and --features; --routing "
                             "a2a only) and with --fmt fold on one "
                             "chip (sequential column groups, zero "
                             "comm).  'auto' runs the obs/comm T(c) "
                             "model under the HBM budget (AMT_HBM_GB "
                             "to override) and degrades LOUDLY to c=1 "
                             "when nothing bigger fits.")
    parser.add_argument("--fold_growth", type=float, default=1.2,
                        help="fmt=fold tier growth factor: padded "
                             "slots <= growth x nnz by construction. "
                             "1.1 with --fold_align 1 is the "
                             "'fold_tight' bench candidate (-17%% "
                             "logical slots at the protocol config).")
    parser.add_argument("--fold_align", type=int, default=None,
                        help="fmt=fold slot alignment (default: the "
                             "8-sublane tile; 1 = no alignment — "
                             "fewest logical gather slots, the bench's "
                             "fold_tight packing).")
    parser.add_argument("--memmap", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Memory-map the decomposition artifact and "
                             "stream blocks/shares to the device "
                             "builders without materializing any level "
                             "on the host (reference memmap loading "
                             "graphio.py:283-294 + streaming "
                             "distribution arrow_dec_mpi.py:629-887).")
    parser.add_argument("--validate", type=str2bool, nargs="?",
                        default=False,
                        help="Compare each iteration against the host "
                             "scipy golden (spmm_15d_main.py --validate "
                             "analog).")
    parser.add_argument("--backend", type=str, default="auto",
                        choices=["auto", "native", "numpy"],
                        help="Decomposer linearization backend for the "
                             "generated-graph path (native C++ when "
                             "available; see arrow_decompose --backend).")
    parser.add_argument("--carry", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Carry X across iterations (X := A @ X "
                             "propagation, the GNN-style iterated run) "
                             "instead of the reference benchmark's "
                             "fresh random X per iteration.")
    add_heal_args(parser)
    parser.add_argument("--comm_report", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Account the per-iteration collective "
                             "bytes of the compiled step from its HLO "
                             "before running (communication volume is "
                             "the reference paper's headline metric; "
                             "utils/commstats).")
    parser.add_argument("--mem_report", type=str2bool, nargs="?",
                        default=False, const=True,
                        help="Report the compiled step's per-device "
                             "memory breakdown (argument/output/temp "
                             "bytes via memory_analysis) against the "
                             "format-metadata prediction, plus the "
                             "per-shard load-imbalance report "
                             "(obs/memview, obs/imbalance).")
    parser.add_argument("--trace", type=str, default=None,
                        help="Write a jax.profiler trace of the "
                             "iteration loop to this directory "
                             "(viewable in XProf/TensorBoard; the "
                             "per-op device-time counterpart of the "
                             "named-segment wall timing).")
    parser.add_argument("--obs_dir", type=str, default=None,
                        help="Write graft-scope artifacts for this run "
                             "to this directory: a Perfetto-loadable "
                             "Chrome trace of the iteration loop plus "
                             "metrics.jsonl (per-iteration step time, "
                             "collective-bytes accounting); inspect "
                             "with `graft_trace summarize <dir>`.")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--logdir", type=str, default="./logs")
    add_device_args(parser)
    add_distributed_args(parser)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    blocked_explicit = args.blocked is not None
    args.blocked = True if args.blocked is None else args.blocked
    if args.slim and not args.blocked:
        raise SystemExit("--slim requires a block-diagonal decomposition "
                         "(--blocked true); the reference enforces the "
                         "same (arrow_dec_mpi.py:131)")
    if args.checkpoint and not args.carry:
        # Pure flag error: fail before any decomposition/compile work.
        raise SystemExit("--checkpoint requires --carry (there is no "
                         "iteration state to resume when X is fresh "
                         "every iteration)")
    if args.repl != "1":
        # 2.5D flag preconditions knowable before any backend work.
        if not args.slim:
            raise SystemExit(
                "--repl (2.5D replication) composes with the slim "
                "layout; the wide (arm, blocks) mesh spends its extra "
                "devices on the row/column split, not replicas")
        if args.mode == "space":
            raise SystemExit(
                "--repl composes with --mode time; the space-shared "
                "mesh spends its extra devices on level groups, not "
                "replicas")
        if args.routing == "gather":
            raise SystemExit(
                "--repl carries per-replica-group PARTIAL feature "
                "slabs; the GSPMD gather lowering assumes a "
                "replicated carriage and corrupts the exchange — use "
                "--routing a2a (the sell default)")
    if not args.slim:
        # Wide layout preconditions — loud flag errors before any
        # decomposition/compile work (VERDICT r2 item 7: --slim false
        # must run the wide layout or fail, never silently run slim).
        if args.mode == "space":
            raise SystemExit(
                "--slim false (wide layout) runs time-shared; "
                "--mode space shards its per-level groups slim-style")
        if args.fmt is not None and args.fmt in ("sell", "fold", "hyb"):
            raise SystemExit(
                f"--slim false (wide layout) needs a stacked block "
                f"format (--fmt auto/dense/ell), not {args.fmt!r}")
        if args.routing == "a2a":
            raise SystemExit(
                "--slim false (wide layout) composes with --routing "
                "gather (the a2a tables cover the slim sharding)")
    if args.mode == "space":
        if args.fmt is not None and args.fmt in ("hyb", "fold"):
            raise SystemExit(
                f"--fmt {args.fmt} is a single-chip kernel; "
                "--mode space runs levels on disjoint device groups — "
                "use --fmt auto/dense/ell (stacked) or sell "
                "(feature-major)")
        if args.head_fmt != "auto":
            print(f"warning: --head_fmt {args.head_fmt} applies only to "
                  f"--mode time; the space-shared runtime pre-agrees "
                  f"one head format across levels")
    setup_platform(args)

    import jax

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
        save_decomposition,
    )
    from arrow_matrix_tpu.parallel import (
        MultiLevelArrow,
        make_mesh,
        make_repl_mesh,
    )
    from arrow_matrix_tpu.utils import graphs
    from arrow_matrix_tpu.utils import logging as wb

    # Honor an explicit --devices request even when the backend was
    # initialized earlier with more (force_cpu_devices cannot shrink an
    # already-created backend; sub-meshes can).  Computed BEFORE any
    # decomposition work so device-count preconditions fail as cheaply
    # as the flag errors above.
    n_dev = len(jax.devices())
    if args.devices > 0:
        # Under --coordinator, --devices counts THIS process's local
        # devices; the mesh is global (every process must drive every
        # device of a multi-controller mesh).
        n_dev = min(n_dev, args.devices * jax.process_count())
    if not args.slim and args.mode == "time" and (n_dev < 4 or n_dev % 2):
        raise SystemExit(
            f"--slim false (wide layout) needs an even device count "
            f">= 4 for the (arm=2, blocks) mesh; have {n_dev} (the "
            f"reference's rank-parity requirement, arrow_mpi.py:65-69)")

    # Measured-best defaults (VERDICT r2 item 4): with no --fmt/--routing
    # the run gets the mode the race data picked for this hardware —
    # fold on one chip, sell(+a2a tables) on a mesh — instead of a
    # defensible-but-slowest fallback.  Explicit flags always win.
    if args.fmt is None:
        if not args.slim:
            args.fmt = "auto"   # wide layout runs the stacked formats
        elif args.mode == "space" or n_dev > 1:
            args.fmt = "sell"
        else:
            args.fmt = "fold"
        print(f"auto-selected --fmt {args.fmt} for {n_dev} device(s) "
              f"(measured-best; override with --fmt)")
    if args.ladder != "default" and args.fmt != "sell":
        print(f"warning: --ladder {args.ladder} applies only to the "
              f"sell mesh layouts; --fmt {args.fmt} packs its own way")
    if args.routing is None:
        args.routing = ("a2a" if (args.fmt == "sell" and n_dev > 1
                                  and args.mode == "time")
                        else "gather")
        if args.routing == "a2a":
            print("auto-selected --routing a2a (measured lowest "
                  "collective volume; override with --routing)")
    if args.feature_dtype == "bf16" and args.fmt not in ("fold", "sell"):
        ok = "sell" if args.mode == "space" else "fold or sell"
        raise SystemExit(f"--feature_dtype bf16 needs --fmt {ok} "
                         f"(the other formats carry f32)")
    if args.repl != "1" and args.fmt not in ("sell", "fold"):
        raise SystemExit(
            f"--repl needs --fmt sell (mesh replica groups) or fold "
            f"(single-chip column groups); --fmt {args.fmt} has no "
            f"2.5D mode")

    width = args.width
    if args.path is None:
        # Generate + decompose + save (reference arrow_bench.py:28-41).
        width = width or 512
        n = args.vertices
        base = os.path.join(".", f"ba_{n}_{args.ba_neighbors}")
        # Multi-process: only process 0 generates and writes (the
        # reference's rank-0 generate + barrier, arrow_bench.py:28-41);
        # everyone loads the shared artifact after a cross-process sync.
        if jax.process_index() == 0:
            print(f"generating Barabasi-Albert graph n={n} "
                  f"m={args.ba_neighbors}")
            a = graphs.barabasi_albert(n, args.ba_neighbors,
                                       seed=args.seed)
            levels = arrow_decomposition(
                a, arrow_width=width, max_levels=10,
                block_diagonal=args.blocked, seed=args.seed,
                backend=args.backend)
            # (generated graphs are Barabasi-Albert — the band gate
            # never fires on them, so no flag plumbed here)
            save_decomposition(levels, base, block_diagonal=args.blocked)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("decomposition_saved")
        path = base
    else:
        path = args.path
        if not width:
            raise SystemExit("--width is required with --path "
                             "(it names the artifact files)")

    # Both branches above guarantee a nonzero width (it names the
    # artifact files).
    from arrow_matrix_tpu.io.graphio import ArtifactIntegrityError

    try:
        loaded = load_decomposition(path, width,
                                    block_diagonal=args.blocked,
                                    mem_map=args.memmap)
    except ArtifactIntegrityError as e:
        # Fail before the run, not 900 s into it: a tampered or
        # half-written artifact is a nonzero exit naming the file.
        print(f"artifact integrity check failed: {e}")
        return 1
    widths = load_level_widths(path, width, block_diagonal=args.blocked)
    if widths is None:
        widths = width
    levels = as_levels(loaded, widths, materialize=not args.memmap)
    # The host golden (decomposition_spmm) needs CSR levels; under
    # --memmap they materialize ONLY when --validate asks for the
    # golden (a >RAM run validates offline instead).
    golden_levels = (as_levels(loaded, widths)
                     if args.memmap and args.validate else levels)
    from arrow_matrix_tpu.io.graphio import num_rows

    n = num_rows(levels[0].matrix)

    # 2.5D replication factor (graft-repl).  'auto' runs the T(c)
    # planner on cheap pre-build estimates — operator bytes from nnz,
    # exchange bytes from the paper's O(n_dev * width * k) bound — so
    # an infeasible plan costs nothing but this arithmetic; the HBM
    # certificate (base x c <= budget) is what keeps auto from
    # planning an OOM, and a budget that rejects every c>1 degrades
    # LOUDLY to c=1 (auto_repl prints to stderr).
    repl_c = 1
    if args.repl == "auto":
        from arrow_matrix_tpu.obs.comm import auto_repl

        itemsz = 2 if args.feature_dtype == "bf16" else 4
        nnz = sum(int(lvl.matrix.nnz) for lvl in levels)
        rows_dev = -(-n // max(n_dev, 1))
        base_est = (nnz * 8 // max(n_dev, 1)
                    + 2 * rows_dev * args.features * 4)
        exch_est = (max(n_dev - 1, 0) * width * args.features
                    * itemsz * len(levels)) if n_dev > 1 else 0
        plan = auto_repl(n_dev, args.features, base_est,
                         exchange_bytes=exch_est, n_coll=len(levels),
                         reduce_bytes=rows_dev * args.features * itemsz,
                         iterations=max(args.iterations, 1))
        repl_c = plan["c"]
        pred = ", ".join(f"c={c}: {t:.4f} ms" for c, t
                         in sorted(plan["predicted_ms"].items()))
        print(f"--repl auto plan: c={repl_c} ({pred}; budget "
              f"{plan['budget_bytes'] / 2**30:.2f} GiB, base "
              f"~{plan['base_hbm_bytes']} B"
              + (", DEGRADED" if plan["degraded"] else "") + ")")
    elif args.repl != "1":
        repl_c = int(args.repl)
        if n_dev > 1 and n_dev % repl_c:
            raise SystemExit(
                f"--repl {repl_c} must divide the device count "
                f"({n_dev}): each replica group needs an equal share "
                f"of the mesh")
        if args.features % repl_c:
            raise SystemExit(
                f"--repl {repl_c} must divide --features "
                f"({args.features}): each replica group owns an equal "
                f"static column slab")

    # Version-string run name (reference arrow_bench.py:43-47 pattern),
    # derived from what actually runs: slim-style sharding, banded or
    # block-diagonal tiling, time- or space-shared level execution.
    # SpaceSharedArrow always tiles banded, whatever --blocked says.
    banded_run = args.mode == "space" or not args.blocked
    if args.mode == "space" and args.blocked and blocked_explicit:
        print("warning: --mode space always uses banded tiling; "
              "--blocked affects only the artifact naming")
    algo = (f"ArrowTPU_v{'Banded' if banded_run else 'BlockDiagonal'}"
            f"_{'Slim' if args.slim else 'Wide'}"
            f"_{args.mode.capitalize()}Shared")
    wb.init(algo, os.path.basename(path), config=vars(args))

    with wb.segment("build_time"):
        if args.mode == "space":
            from arrow_matrix_tpu.parallel.space_shared import (
                SpaceSharedArrow,
            )

            if n_dev % len(levels) != 0:
                raise SystemExit(
                    f"--mode space needs the device count ({n_dev}) "
                    f"divisible by the level count ({len(levels)}); "
                    f"rerun with --devices set accordingly (the "
                    f"reference's rank-budget validation analog, "
                    f"arrow_bench.py:64-78)")
            if args.routing != "gather":
                print(f"warning: --routing {args.routing} applies only "
                      f"to --mode time; space-shared exchanges are the "
                      f"composed-gather + cross-group reduce")
            # Explicit mesh so an explicit --devices clamp is honored
            # (the default meshes span every device).
            space_mesh = make_mesh((len(levels), n_dev // len(levels)),
                                   ("lvl", "blocks"))
            if args.fmt == "sell":
                from arrow_matrix_tpu.parallel.sell_space import (
                    SellSpaceShared,
                )

                multi = SellSpaceShared(levels, width, mesh=space_mesh,
                                        feature_dtype=args.feature_dtype,
                                        ladder=args.ladder)
            else:
                multi = SpaceSharedArrow(levels, width, fmt=args.fmt,
                                         mesh=space_mesh)
        else:
            if args.fmt in ("hyb", "fold") and n_dev > 1:
                raise SystemExit(
                    f"--fmt {args.fmt} is single-chip only; rerun with "
                    f"--devices 1 (or pick --fmt auto/dense/ell/sell "
                    f"for the {n_dev}-device mesh)")
            if args.fmt == "sell" and n_dev < 2:
                raise SystemExit(
                    "--fmt sell is the mesh orchestration; on one chip "
                    "use --fmt fold (same layouts, zero routing)")
            if not args.slim:
                # (device-count parity already validated up front)
                mesh = make_mesh((2, n_dev // 2), ("arm", "blocks"))
            elif repl_c > 1 and n_dev > 1:
                # 2.5D: (blocks, repl) — each of the repl_c replica
                # groups runs the whole level schedule over
                # n_dev/repl_c block shards on its own k/c slab.
                mesh = make_repl_mesh(n_dev, repl_c)
                print(f"2.5D mesh: {n_dev // repl_c} block shards "
                      f"x {repl_c} replica groups")
            else:
                mesh = (make_mesh((n_dev,), ("blocks",))
                        if n_dev > 1 else None)
            if args.fmt == "sell":
                from arrow_matrix_tpu.parallel.sell_slim import (
                    SellMultiLevel,
                )

                multi = SellMultiLevel(levels, width, mesh,
                                       routing=args.routing,
                                       feature_dtype=args.feature_dtype,
                                       ladder=args.ladder,
                                       repl_axis=("repl" if repl_c > 1
                                                  else None))
            else:
                multi = MultiLevelArrow(
                    levels, width, mesh=mesh,
                    banded=not args.blocked, fmt=args.fmt,
                    head_fmt=args.head_fmt,
                    feature_dtype=(args.feature_dtype
                                   if args.fmt == "fold" else None),
                    layout="slim" if args.slim else "wide",
                    routing=(args.routing if mesh is not None
                             else "gather"),
                    fold_growth=args.fold_growth,
                    fold_align=args.fold_align,
                    repl=repl_c)

    # Untimed warmup: trace + compile must not pollute iteration 0's
    # spmm_time (the sibling baseline CLIs warm up the same way).
    warm = multi.set_features(
        graphs.random_dense(n, args.features, seed=args.seed))
    jax.block_until_ready(multi.step(warm))

    from arrow_matrix_tpu import obs

    obs_reg = obs.MetricsRegistry(run_dir=args.obs_dir)
    obs_tracer = obs.Tracer("spmm_arrow", registry=obs_reg)

    if args.comm_report:
        from arrow_matrix_tpu.utils import commstats

        if getattr(multi, "mesh", None) is None:
            print("comm report: single-chip execution — zero "
                  "collective bytes by construction")
        else:
            # bf16 carriage: the CPU backend upcasts compiled
            # collectives to f32, so pin the LOWERED module (all
            # a2a-path collectives are explicit shard_map ops and
            # appear there; commstats docstring).  Otherwise "auto"
            # prefers the lowered module and falls back to compiled
            # when the routing is GSPMD-inserted.
            pinned = (getattr(multi, "feature_dtype", None) is not None
                      and getattr(multi, "routing", None) == "a2a")
            itemsize = 2 if args.feature_dtype == "bf16" else 4
            rep = obs.account_collectives(
                "spmm_arrow", multi.step_fn, warm,
                *multi.step_operands(),
                ideal_bytes=obs.ideal_bytes_for(multi, args.features,
                                                itemsize=itemsize),
                mode="lowered" if pinned else "auto",
                repl=getattr(multi, "repl", 1),
                reduce_bytes=obs.reduce_bytes_for(
                    multi, args.features, itemsize=itemsize),
                registry=obs_reg)
            print(f"per-iteration collective bytes "
                  f"({rep['source']} HLO):")
            if (rep["source"] == "compiled"
                    and getattr(multi, "feature_dtype", None) is not None):
                print("(note: on the CPU backend compiled collectives "
                      "upcast bf16 to f32 — bytes shown are the f32 "
                      "upper bound)")
            print(commstats.format_stats(rep["collectives"]))
            if rep["ratio"] is not None:
                print(f"measured vs paper-model ideal: "
                      f"{rep['measured_bytes']} / {rep['ideal_bytes']} "
                      f"bytes = {rep['ratio']:.2f}x")
            if rep["repl"] > 1:
                print(f"2.5D replication c={rep['repl']}: per-step "
                      f"exchange bytes above are cut by c; the final "
                      f"masked-psum merge pays {rep['reduce_bytes']} "
                      f"B/device once per gather")

    if args.mem_report:
        itemsize = 2 if args.feature_dtype == "bf16" else 4
        mem = obs.account_memory(
            "spmm_arrow", multi.step_fn, warm, *multi.step_operands(),
            predicted_bytes=obs.predicted_bytes_for(
                multi, args.features, itemsize=itemsize),
            registry=obs_reg)
        print(obs.format_memory_report(mem))
        imb = obs.account_imbalance("spmm_arrow", multi,
                                    registry=obs_reg)
        if imb is not None:
            print(obs.format_imbalance_report(imb))

    rng = np.random.default_rng(args.seed)
    from arrow_matrix_tpu import faults

    # Layout tag: how X is carried.  A checkpoint written under one
    # executor configuration refuses to resume under another (the
    # checkpoint module's loud-mismatch contract) instead of silently
    # permuting rows.
    layout = (f"{algo}/{args.fmt}/{args.feature_dtype or 'f32'}"
              + (f"/repl{repl_c}" if repl_c > 1 else ""))
    # Under 2.5D replication the carried state is per-replica-group
    # partial; checkpoints must persist the merged canonical form
    # (merge_carries docstring) or a resume would silently restore
    # replica 0's partial slab view.
    canon = (multi.merge_carries
             if repl_c > 1 and hasattr(multi, "merge_carries")
             else None)
    sup = make_supervisor(args, "spmm_arrow", carry=args.carry,
                          layout=layout, registry=obs_reg,
                          canonicalize=canon)
    start_it = 0
    x0 = warm   # the warmup input IS the carry-mode initial state
    if args.carry and args.checkpoint:
        state = sup.resume(like=x0)
        if state is not None:
            x0, start_it = state
            print(f"resumed from {args.checkpoint} at iteration "
                  f"{start_it}")

    def body(x, it):
        wb.set_iteration_data({"iteration": it})
        if args.carry:
            x_host = None
        else:
            # Fresh random X every iteration (arrow_bench.py:114-116).
            x_host = graphs.random_dense(n, args.features,
                                         seed=int(rng.integers(2**31)))
            x = multi.set_features(x_host)
        if args.carry and args.validate:
            # The golden compares one step from the CURRENT state.
            x_host = multi.gather_result(x)
        with obs_tracer.span("step", iteration=it):
            tic = time.perf_counter()
            y = multi.step(x)
            jax.block_until_ready(y)
            dt = time.perf_counter() - tic
        wb.log({"spmm_time": dt})
        obs_reg.record("iteration_time_ms", dt * 1e3,
                       algorithm="spmm_arrow")
        if args.validate:
            from arrow_matrix_tpu.utils import numerics

            got = multi.gather_result(y)
            want = decomposition_spmm(golden_levels, x_host)
            err = numerics.relative_error(got, want)
            # One step separates the compared states (X is fresh per
            # iteration); tolerance per the documented accumulation-
            # order policy (utils/numerics.py).  bf16 carriage rounds
            # inputs and outputs to 8-bit mantissas: the bound becomes
            # the bf16 epsilon, not the f32 accumulation model.
            tol = numerics.relative_tolerance(
                sum(l.matrix.nnz for l in golden_levels) / max(n, 1),
                iters=1)
            if args.feature_dtype == "bf16":
                tol = max(tol, 2e-2)
            wb.log({"frobenius_err": float(err)})
            print(f"iteration {it}: rel err vs host {err:.3e} "
                  f"(gate {tol:.1e})")
            if not np.isfinite(err) or err > tol:
                # Policy failure: the supervisor never retries it, and
                # no checkpoint of this state is written — a rerun must
                # not resume past a numerically bad iteration.
                raise faults.Abort(
                    f"validation gate failed at iteration {it}: rel "
                    f"err {err:.3e} (gate {tol:.1e})")
        return y

    # --trace wraps the iteration loop; the finally below flushes the
    # profiler even when an exception escapes the supervised loop
    # (watchdog escalation, Ctrl-C).
    from contextlib import ExitStack

    _trace_stack = ExitStack()
    if args.trace:
        _trace_stack.enter_context(wb.trace(args.trace))
    try:
        _, ok = sup.run(body, x0, start_it, args.iterations)
        fail = not ok
    finally:
        # The flush must survive exceptions outside the supervised
        # loop — a requested trace must never be lost.
        _trace_stack.close()
    summary = wb.get_log().summarize()
    if "spmm_time" in summary:
        s = summary["spmm_time"]
        print(f"spmm_time mean {s['mean'] * 1e3:.3f} ms over "
              f"{s['count']} iterations (min {s['min'] * 1e3:.3f})")
    if args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)
        obs_reg.merge_segment_log(wb.get_log())
        obs_tracer.save(os.path.join(args.obs_dir,
                                     "spmm_arrow.trace.json"))
        obs_reg.write_jsonl()
        print(f"graft-scope artifacts in {args.obs_dir} "
              f"(graft_trace summarize to inspect)")
    out = wb.finish(args.logdir)
    if out:
        print(f"log written to {out}.json")
    return 1 if fail else 0


if __name__ == "__main__":
    raise SystemExit(main())
