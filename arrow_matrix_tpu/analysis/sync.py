"""graft-sync static analyzer: lock-discipline rules RC1-RC5.

Third member of the analysis family — graft-lint (R1-R9) audits
single-threaded AST patterns, graft-prove (H1-H7) audits lowered HLO,
graft-sync audits the concurrency layer between them.  It reads the
``@guarded_by`` contracts declared in :mod:`arrow_matrix_tpu.sync`
straight from the AST (so never-imported code paths are still
checked), builds the package thread-entry graph and lock-acquisition
graph, and proves:

RC1  guarded-attribute mutation: every attribute a contract declares
     guarded is only mutated inside ``with self.<lock>`` (or an alias,
     or a method proven to run under the lock); ``__init__`` is exempt
     (pre-publication).
RC2  lock-order acyclicity: the static acquisition graph — lexically
     nested ``with``-lock blocks package-wide, flock vertices, plus
     the declared partial order (``sync.DECLARED_ORDER``) — has no
     cycle; a cycle is a potential deadlock.  Raw ``fcntl.flock``
     calls outside the single audited primitive
     (``utils/artifacts.flock_acquire``) are RC2 findings too: an
     unregistered flock site is an edge the graph cannot see.
RC3  callback hygiene: a hook the contract names in ``callbacks``
     (user code that may re-enter the package) is never invoked while
     the class lock is held — the rule ``obs/pulse.py`` follows by
     hand, now checked.
RC4  no blocking call under a lock: socket ``recv``/``accept``,
     ``subprocess`` waits, ``Event.wait()`` without timeout,
     ``time.sleep``, zero-arg ``join()``, ``os.fsync`` — none may
     appear in an under-lock region (a Condition's own ``wait`` is
     exempt: it releases the lock).
RC5  shared module state: a mutable module-level binding mutated by a
     function reachable from a secondary thread entry
     (``threading.Thread`` target, ``atexit`` hook, ``sys.excepthook``)
     must be mutated under a lock or flock — main + that entry are two
     writers.

Verdicts land in a drift-detected ``bench_cache/sync_manifest.json``
(the hlo_manifest.json discipline): ``--check`` recomputes without
writing and fails on any violation OR any drift against the checked-in
manifest.  Waivers mirror graft-lint: ``# graft-sync: disable=RC4`` on
the offending line, ``# graft-sync: disable-file`` anywhere.
"""

from __future__ import annotations

import ast
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULE_IDS = ("RC1", "RC2", "RC3", "RC4", "RC5")

RULE_TITLES = {
    "RC1": "guarded attribute mutated only under its declared lock",
    "RC2": "static lock-acquisition graph is acyclic",
    "RC3": "no contract callback invoked while a lock is held",
    "RC4": "no blocking call under a held lock",
    "RC5": "thread-shared module state is lock-/flock-guarded",
}

DEFAULT_MANIFEST = os.path.join("bench_cache", "sync_manifest.json")

#: Keys the drift comparison ignores (environment, not behavior).
VOLATILE_KEYS = ("timestamp", "python_version", "platform", "generated_by")

_WAIVE_TOKEN = "graft-sync:"
_FLOCK_PRIMITIVE_TOKEN = "graft-sync: flock-primitive"

#: Container-mutating method names treated as writes for RC1/RC5.
_MUTATORS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "merge", "observe", "pop", "popitem", "popleft",
    "remove", "reverse", "rotate", "setdefault", "sort", "update",
})

#: Attribute calls that block (RC4) regardless of arguments.
_BLOCKING_ATTRS = frozenset({
    "accept", "communicate", "fsync", "recv", "recv_into", "recvfrom",
})

#: ``subprocess.<fn>`` calls that block (RC4).
_BLOCKING_SUBPROCESS = frozenset({
    "call", "check_call", "check_output", "run",
})

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                             "BoundedSemaphore"})


class Finding:
    """One rule violation at one source location."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class Contract:
    """A ``@guarded_by`` declaration read from the AST."""

    __slots__ = ("path", "cls", "line", "lock", "node", "attrs",
                 "callbacks", "aliases")

    def __init__(self, path: str, cls: str, line: int, lock: str,
                 node: Optional[str], attrs: Tuple[str, ...],
                 callbacks: Tuple[str, ...], aliases: Tuple[str, ...]):
        self.path = path
        self.cls = cls
        self.line = line
        self.lock = lock
        self.node = node or cls
        self.attrs = attrs
        self.callbacks = callbacks
        self.aliases = aliases

    @property
    def lock_names(self) -> Set[str]:
        return {self.lock, *self.aliases}

    def to_json(self) -> dict:
        return {"path": self.path, "class": self.cls, "line": self.line,
                "lock": self.lock, "node": self.node,
                "attrs": list(self.attrs),
                "callbacks": list(self.callbacks),
                "aliases": list(self.aliases)}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_str_tuple(node) -> Tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = _const_str(elt)
            if s is not None:
                out.append(s)
        return tuple(out)
    return ()


def _decorator_contract(dec) -> Optional[dict]:
    """Parse ``@guarded_by("_lock", node=..., attrs=..., ...)``."""
    if not isinstance(dec, ast.Call):
        return None
    fn = dec.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if name != "guarded_by" or not dec.args:
        return None
    lock = _const_str(dec.args[0])
    if lock is None:
        return None
    kw = {k.arg: k.value for k in dec.keywords if k.arg}
    return {
        "lock": lock,
        "node": _const_str(kw.get("node")) if "node" in kw else None,
        "attrs": _const_str_tuple(kw.get("attrs")),
        "callbacks": _const_str_tuple(kw.get("callbacks")),
        "aliases": _const_str_tuple(kw.get("aliases")),
    }


class _Module:
    """Parsed module plus the name-resolution scraps the rules need."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.disable_file = any(
            _WAIVE_TOKEN in ln and "disable-file" in ln
            for ln in self.lines)
        # import-alias map: local name -> dotted module ("_time" -> "time")
        self.mod_aliases: Dict[str, str] = {}
        # from-import map: local name -> "module.attr"
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        # module-level lock variables: NAME = threading.Lock() / RLock()
        # (possibly wrapped in witnessed("node", ...)).
        self.module_locks: Dict[str, str] = {}
        modname = os.path.splitext(os.path.basename(path))[0]
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                name = stmt.targets[0].id
                node_name = self._lock_factory_node(stmt.value)
                if node_name is not None:
                    self.module_locks[name] = (
                        node_name if node_name != "" else
                        f"{modname}.{name}")
        # module-level DECLARED_ORDER (fixtures / selftests)
        self.declared_order: List[Tuple[str, str]] = []
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "DECLARED_ORDER"
                    and isinstance(stmt.value, (ast.Tuple, ast.List))):
                for elt in stmt.value.elts:
                    pair = _const_str_tuple(elt)
                    if len(pair) == 2:
                        self.declared_order.append((pair[0], pair[1]))

    def _lock_factory_node(self, value) -> Optional[str]:
        """'' for a bare Lock()/RLock() assignment, the witness node
        name for witnessed("node", Lock()), else None."""
        if isinstance(value, ast.Call):
            fn = value.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in _LOCK_FACTORIES):
                return ""
            if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
                return ""
            if (isinstance(fn, ast.Name) and fn.id == "witnessed"
                    and value.args):
                return _const_str(value.args[0]) or ""
        return None

    def waived(self, line: int, rule: str) -> bool:
        if self.disable_file:
            return True
        if 1 <= line <= len(self.lines):
            text = self.lines[line - 1]
            if _WAIVE_TOKEN in text and f"disable={rule}" in text:
                return True
            # multi-line statements: also honor a waiver on the `with`
            # opening line one above
            if line >= 2:
                prev = self.lines[line - 2]
                if _WAIVE_TOKEN in prev and f"disable={rule}" in prev:
                    return True
        return False

    def resolves_to(self, node, module: str) -> bool:
        """Does ``node`` (the value part of an Attribute) name the
        imported module ``module`` under any alias?"""
        return (isinstance(node, ast.Name)
                and self.mod_aliases.get(node.id) == module)


def _self_attr(node) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _mutation_target_attr(stmt) -> List[Tuple[str, int]]:
    """self-attribute names written by an Assign/AugAssign/AnnAssign/
    Delete statement (direct or through one subscript level)."""
    out: List[Tuple[str, int]] = []
    targets: List = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    for t in targets:
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Tuple):
            for elt in t.elts:
                sub = elt.value if isinstance(elt, ast.Subscript) else elt
                a = _self_attr(sub)
                if a is not None:
                    out.append((a, stmt.lineno))
            continue
        a = _self_attr(t)
        if a is not None:
            out.append((a, stmt.lineno))
    return out


def _mutator_call_attr(call) -> Optional[Tuple[str, int]]:
    """``self.<attr>.append(...)``-style container mutation."""
    fn = call.func
    if (isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS):
        a = _self_attr(fn.value)
        if a is not None:
            return a, call.lineno
    return None


class _FlockNode:
    """Resolve with-items / calls that mark flock regions."""

    @staticmethod
    def of_withitem(mod: _Module, item) -> Optional[str]:
        expr = item.context_expr
        if not isinstance(expr, ast.Call):
            return None
        fn = expr.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if name == "flock_witness" and expr.args:
            arg = _const_str(expr.args[0])
            return f"flock:{arg}" if arg else "flock:?"
        if name == "locked_file":
            return "flock:sidecar"
        return None


class _FunctionScan:
    """Single lexical walk of one function body tracking the stack of
    held lock nodes; collects everything RC1-RC4 need."""

    def __init__(self, mod: _Module, contract: Optional[Contract],
                 class_contracts: Dict[str, Contract]):
        self.mod = mod
        self.contract = contract          # enclosing class's, if any
        self.class_contracts = class_contracts
        self.own_lock_held_depth = 0      # contract lock (incl. aliases)
        # (node_name, is_flock) entries currently open; flock regions
        # contribute RC2 graph edges but do not count as "a held lock"
        # for RC3/RC4 — fsync-under-flock is the crash-consistency
        # point of append_jsonl, not a hazard.
        self.lock_stack: List[Tuple[str, bool]] = []
        self.mutations: List[Tuple[str, int, bool]] = []
        self.callback_calls: List[Tuple[str, int, bool]] = []
        self.blocking: List[Tuple[str, int, bool]] = []
        self.self_calls: List[Tuple[str, int, bool]] = []
        self.edges: List[Tuple[str, str, int]] = []
        self.raw_flock: List[int] = []

    # -- lock-expression resolution -------------------------------------

    def _with_lock_node(self, item) -> Optional[Tuple[str, bool]]:
        """(node_name, is_own_class_lock) for a with-item that acquires
        a known lock, else None."""
        expr = item.context_expr
        a = _self_attr(expr)
        if a is not None and self.contract is not None \
                and a in self.contract.lock_names:
            return self.contract.node, True
        if isinstance(expr, ast.Name) \
                and expr.id in self.mod.module_locks:
            return self.mod.module_locks[expr.id], False
        flock = _FlockNode.of_withitem(self.mod, item)
        if flock is not None:
            return flock, False
        return None

    # -- walk ------------------------------------------------------------

    def scan(self, fn) -> None:
        for stmt in fn.body:
            self._visit(stmt)

    def _under(self) -> bool:
        return self.own_lock_held_depth > 0

    def _visit(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return          # nested defs scanned separately (closures
                            # run later, not under this lock region)
        if isinstance(node, ast.With):
            acquired: List[Tuple[str, bool]] = []
            for item in node.items:
                res = self._with_lock_node(item)
                if res is not None:
                    node_name, own = res
                    for open_node, _fl in self.lock_stack:
                        if open_node != node_name:
                            self.edges.append(
                                (open_node, node_name, node.lineno))
                    self.lock_stack.append(
                        (node_name, node_name.startswith("flock:")))
                    acquired.append(res)
                    if own:
                        self.own_lock_held_depth += 1
                for sub in ([item.context_expr] +
                            ([item.optional_vars]
                             if item.optional_vars else [])):
                    self._visit_expr(sub)
            for stmt in node.body:
                self._visit(stmt)
            for node_name, own in reversed(acquired):
                self.lock_stack.pop()
                if own:
                    self.own_lock_held_depth -= 1
            return
        for attr, line in _mutation_target_attr(node):
            self.mutations.append((attr, line, self._under()))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            else:
                self._visit(child)

    def _visit_expr(self, node) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._note_call(sub)

    def _note_call(self, call) -> None:
        mut = _mutator_call_attr(call)
        if mut is not None:
            self.mutations.append((mut[0], mut[1], self._under()))
        a = _self_attr(call.func)
        if a is not None:
            self.self_calls.append((a, call.lineno, self._under()))
            if self.contract is not None \
                    and a in self.contract.callbacks:
                self.callback_calls.append(
                    (a, call.lineno, self._thread_lock_held()))
        self._note_blocking(call)
        self._note_raw_flock(call)

    def _thread_lock_held(self) -> bool:
        return any(not is_flock for _, is_flock in self.lock_stack)

    def _note_blocking(self, call) -> None:
        fn = call.func
        held = self._thread_lock_held()
        desc = None
        if isinstance(fn, ast.Attribute):
            if fn.attr in _BLOCKING_ATTRS:
                desc = f".{fn.attr}()"
            elif fn.attr == "sleep" and (
                    self.mod.resolves_to(fn.value, "time")):
                desc = "time.sleep()"
            elif fn.attr in _BLOCKING_SUBPROCESS and (
                    self.mod.resolves_to(fn.value, "subprocess")):
                desc = f"subprocess.{fn.attr}()"
            elif fn.attr == "join" and not call.args \
                    and not call.keywords \
                    and not isinstance(fn.value, ast.Constant):
                desc = "zero-arg .join()"
            elif fn.attr == "wait":
                recv = _self_attr(fn.value)
                is_own_cond = (recv is not None
                               and self.contract is not None
                               and recv in self.contract.lock_names)
                has_timeout = bool(call.args) or any(
                    k.arg == "timeout" for k in call.keywords)
                if not is_own_cond and not has_timeout:
                    desc = ".wait() without timeout"
        elif isinstance(fn, ast.Name):
            tgt = self.mod.from_imports.get(fn.id)
            if tgt in ("time.sleep", "os.fsync"):
                desc = f"{tgt}()"
        if desc is not None:
            self.blocking.append((desc, call.lineno, held))

    def _note_raw_flock(self, call) -> None:
        fn = call.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "flock"
                and self.mod.resolves_to(fn.value, "fcntl")):
            line = call.lineno
            text = self.mod.lines[line - 1] \
                if 1 <= line <= len(self.mod.lines) else ""
            if _FLOCK_PRIMITIVE_TOKEN not in text:
                self.raw_flock.append(line)


def _iter_functions(tree):
    """Every function in the module exactly once, paired with its
    class when it is a direct class-body method (nested closures —
    thread targets — come through with None: they run later, not under
    their enclosure's lock region)."""
    class_of = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    class_of[id(sub)] = node
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield class_of.get(id(node)), node


class ModuleReport:
    """Everything one module contributes to the package verdict."""

    def __init__(self, mod: _Module):
        self.mod = mod
        self.contracts: List[Contract] = []
        self.findings: List[Finding] = []
        self.edges: List[Tuple[str, str, int]] = []
        self.thread_entries: List[dict] = []
        self.declared_order = list(mod.declared_order)


def _class_contract(mod: _Module, classdef) -> Optional[Contract]:
    for dec in classdef.decorator_list:
        parsed = _decorator_contract(dec)
        if parsed is not None:
            return Contract(mod.path, classdef.name, classdef.lineno,
                            parsed["lock"], parsed["node"],
                            parsed["attrs"], parsed["callbacks"],
                            parsed["aliases"])
    return None


def _under_lock_methods(scans: Dict[str, _FunctionScan]) -> Set[str]:
    """Methods proven to run with the class lock held: the ``*_locked``
    naming convention, plus private methods whose every intra-class
    call site is under the lock (lexically or transitively)."""
    under: Set[str] = {name for name in scans if name.endswith("_locked")}
    # call sites per callee; ``__init__`` call sites are excluded —
    # pre-publication calls run before any other thread can hold a
    # reference, so an unlocked call there does not defeat the proof
    sites: Dict[str, List[Tuple[str, bool]]] = {}
    for caller, scan in scans.items():
        if caller == "__init__":
            continue
        for callee, _line, lexical in scan.self_calls:
            if callee in scans:
                sites.setdefault(callee, []).append((caller, lexical))
    changed = True
    while changed:
        changed = False
        for name, scan in scans.items():
            if name in under or not name.startswith("_") \
                    or name.startswith("__"):
                continue
            callers = sites.get(name)
            if not callers:
                continue
            if all(lexical or caller in under
                   for caller, lexical in callers):
                under.add(name)
                changed = True
    return under


def analyze_module(path: str, source: Optional[str] = None
                   ) -> ModuleReport:
    if source is None:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
    mod = _Module(path, source)
    report = ModuleReport(mod)

    class_contracts: Dict[str, Contract] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            c = _class_contract(mod, node)
            if c is not None:
                class_contracts[node.name] = c
                report.contracts.append(c)

    # ---- per-class scans (RC1, RC3) + shared RC2/RC4 collection ----
    all_scans: List[Tuple[Optional[Contract], str, _FunctionScan]] = []
    for classdef in [n for n in mod.tree.body
                     if isinstance(n, ast.ClassDef)]:
        contract = class_contracts.get(classdef.name)
        scans: Dict[str, _FunctionScan] = {}
        for fn in classdef.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            scan = _FunctionScan(mod, contract, class_contracts)
            scan.scan(fn)
            scans[fn.name] = scan
            all_scans.append((contract, fn.name, scan))
        if contract is None:
            continue
        under = _under_lock_methods(scans)
        for name, scan in scans.items():
            if name == "__init__":
                continue
            method_under = name in under
            for attr, line, lexical in scan.mutations:
                if attr in contract.attrs and not lexical \
                        and not method_under \
                        and not mod.waived(line, "RC1"):
                    report.findings.append(Finding(
                        "RC1", mod.path, line,
                        f"{contract.cls}.{attr} is declared guarded by "
                        f"self.{contract.lock} but mutated outside it "
                        f"in {name}()"))
            for cb, line, held in scan.callback_calls:
                if (held or method_under) \
                        and not mod.waived(line, "RC3"):
                    report.findings.append(Finding(
                        "RC3", mod.path, line,
                        f"{contract.cls}.{cb} is a declared callback "
                        f"but invoked while a lock is held in {name}()"))
            for desc, line, held in scan.blocking:
                if (held or method_under) \
                        and not mod.waived(line, "RC4"):
                    report.findings.append(Finding(
                        "RC4", mod.path, line,
                        f"blocking {desc} under a held lock in "
                        f"{contract.cls}.{name}()"))

    # module-level + nested functions (RC2 edges, RC4 under module
    # locks, raw-flock sites)
    for classdef, fn in _iter_functions(mod.tree):
        if classdef is not None:
            continue       # class methods already scanned
        scan = _FunctionScan(mod, None, class_contracts)
        scan.scan(fn)
        all_scans.append((None, fn.name, scan))
        for desc, line, held in scan.blocking:
            if held and not mod.waived(line, "RC4"):
                report.findings.append(Finding(
                    "RC4", mod.path, line,
                    f"blocking {desc} under a held lock in {fn.name}()"))

    for _, _, scan in all_scans:
        report.edges.extend(scan.edges)
        for line in scan.raw_flock:
            if not mod.waived(line, "RC2"):
                report.findings.append(Finding(
                    "RC2", mod.path, line,
                    "raw fcntl.flock outside the audited primitive "
                    "(utils/artifacts.flock_acquire) — an unregistered "
                    "flock site is invisible to the lock graph"))

    _scan_thread_entries(mod, report)
    _check_rc5(mod, report)
    return report


# ---------------------------------------------------------------------------
# Thread-entry graph + RC5
# ---------------------------------------------------------------------------


def _call_name(call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _scan_thread_entries(mod: _Module, report: ModuleReport) -> None:
    """Every secondary entry into this module's code: Thread targets,
    atexit hooks, excepthook assignments."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "Thread":
                for k in node.keywords:
                    if k.arg == "target":
                        report.thread_entries.append({
                            "module": mod.path, "kind": "thread",
                            "target": _target_name(k.value),
                            "line": node.lineno})
            elif name == "register" and isinstance(
                    node.func, ast.Attribute) and mod.resolves_to(
                        node.func.value, "atexit") and node.args:
                report.thread_entries.append({
                    "module": mod.path, "kind": "atexit",
                    "target": _target_name(node.args[0]),
                    "line": node.lineno})
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and t.attr == "excepthook"
                        and mod.resolves_to(t.value, "sys")):
                    report.thread_entries.append({
                        "module": mod.path, "kind": "excepthook",
                        "target": _target_name(node.value),
                        "line": node.lineno})


def _target_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    return "<expr>"


def _mutable_globals(mod: _Module) -> Set[str]:
    """Module-level names bound to mutable containers, plus names
    rebound via ``global`` inside functions."""
    out: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            v = stmt.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
            if isinstance(v, ast.Call):
                n = _call_name(v)
                mutable = n in ("dict", "list", "set", "deque",
                                "Counter", "defaultdict", "OrderedDict")
            if mutable:
                out.add(stmt.targets[0].id)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _check_rc5(mod: _Module, report: ModuleReport) -> None:
    mutables = _mutable_globals(mod)
    if not mutables:
        return
    entry_targets = {e["target"] for e in report.thread_entries
                     if e["module"] == mod.path}
    if not entry_targets:
        return

    # intra-module call graph by simple name (module functions, nested
    # closures, and methods all participate — pragmatic resolution).
    fns: Dict[str, ast.AST] = {}
    calls: Dict[str, Set[str]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)
            out = calls.setdefault(node.name, set())
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    n = _call_name(sub)
                    if n:
                        out.add(n)
    reachable: Set[str] = set()
    frontier = [t for t in entry_targets if t in fns]
    while frontier:
        cur = frontier.pop()
        if cur in reachable:
            continue
        reachable.add(cur)
        frontier.extend(c for c in calls.get(cur, ()) if c in fns)

    lock_names = set(mod.module_locks)
    for name in reachable:
        fn = fns[name]
        # lock depth tracking within this function for guard detection
        self_mutations = _global_mutations(fn, mutables, mod)
        for gname, line, guarded in self_mutations:
            if not guarded and not mod.waived(line, "RC5"):
                report.findings.append(Finding(
                    "RC5", mod.path, line,
                    f"module-level {gname!r} mutated in {name}() which "
                    f"is reachable from a secondary thread entry "
                    f"({', '.join(sorted(entry_targets))}) without a "
                    f"lock or flock guard"))


def _global_mutations(fn, mutables: Set[str], mod: _Module
                      ) -> List[Tuple[str, int, bool]]:
    """(name, line, guarded) for mutations of module globals in fn."""
    out: List[Tuple[str, int, bool]] = []
    declared_global: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)

    def visit(node, depth):
        if isinstance(node, ast.With):
            d = depth
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) \
                        and expr.id in mod.module_locks:
                    d += 1
                elif _FlockNode.of_withitem(mod, item) is not None:
                    d += 1
                elif _self_attr(expr) is not None:
                    d += 1      # any instance lock counts as a guard
            for stmt in node.body:
                visit(stmt, d)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Delete)):
            targets = (node.targets if isinstance(
                node, (ast.Assign, ast.Delete)) else [node.target])
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                if isinstance(base, ast.Name) and base.id in mutables:
                    if isinstance(t, ast.Subscript) \
                            or base.id in declared_global:
                        out.append((base.id, node.lineno, depth > 0))
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in mutables:
                out.append((f.value.id, node.lineno, depth > 0))
        for child in ast.iter_child_nodes(node):
            visit(child, depth)

    for stmt in fn.body:
        visit(stmt, 0)
    return out


# ---------------------------------------------------------------------------
# Package-level assembly: RC2 cycle check + manifest
# ---------------------------------------------------------------------------


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _iter_package_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "_native")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def _declared_order() -> List[Tuple[str, str]]:
    from arrow_matrix_tpu.sync import DECLARED_ORDER
    return list(DECLARED_ORDER)


def _cycle_findings(edges: List[Tuple[str, str, int, str]],
                    declared: Sequence[Tuple[str, str]]) -> List[Finding]:
    succ: Dict[str, Set[str]] = {}
    where: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for a, b in declared:
        succ.setdefault(a, set()).add(b)
        where.setdefault((a, b), ("<declared>", 0))
    for a, b, line, path in edges:
        if a != b:
            succ.setdefault(a, set()).add(b)
            where.setdefault((a, b), (path, line))
    findings: List[Finding] = []
    # DFS cycle detection with path recovery
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []
    reported: Set[frozenset] = set()

    def dfs(u: str):
        color[u] = GRAY
        stack.append(u)
        for v in sorted(succ.get(u, ())):
            if color.get(v, WHITE) == WHITE:
                dfs(v)
            elif color.get(v) == GRAY:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key not in reported:
                    reported.add(key)
                    path, line = where.get((u, v), ("<unknown>", 0))
                    findings.append(Finding(
                        "RC2", path, line,
                        "lock-acquisition cycle (potential deadlock): "
                        + " -> ".join(cyc)))
        stack.pop()
        color[u] = BLACK

    for node in sorted(succ):
        if color.get(node, WHITE) == WHITE:
            dfs(node)
    return findings


class SyncReport:
    def __init__(self):
        self.findings: List[Finding] = []
        self.contracts: List[Contract] = []
        self.edges: List[Tuple[str, str, int, str]] = []
        self.thread_entries: List[dict] = []
        self.modules = 0
        self.declared: List[Tuple[str, str]] = []

    @property
    def ok(self) -> bool:
        return not self.findings


def analyze_paths(paths: Sequence[str],
                  declared: Optional[Sequence[Tuple[str, str]]] = None,
                  sources: Optional[Dict[str, str]] = None) -> SyncReport:
    report = SyncReport()
    module_declared: List[Tuple[str, str]] = []
    for path in paths:
        src = sources.get(path) if sources else None
        try:
            mr = analyze_module(path, src)
        except SyntaxError as e:
            report.findings.append(Finding(
                "RC2", path, e.lineno or 0, f"unparseable module: {e}"))
            continue
        report.modules += 1
        report.findings.extend(mr.findings)
        report.contracts.extend(mr.contracts)
        report.thread_entries.extend(mr.thread_entries)
        module_declared.extend(mr.declared_order)
        for a, b, line in mr.edges:
            report.edges.append((a, b, line, path))
    report.declared = (list(declared) if declared is not None
                       else module_declared)
    report.findings.extend(_cycle_findings(report.edges, report.declared))
    report.findings.sort(key=lambda f: (f.rule, f.path, f.line))
    return report


def analyze_package(root: Optional[str] = None) -> SyncReport:
    root = root or _package_root()
    return analyze_paths(_iter_package_files(root),
                         declared=_declared_order())


def analyze_source(source: str, path: str = "<fixture>",
                   declared: Optional[Sequence[Tuple[str, str]]] = None
                   ) -> SyncReport:
    """Fixture/selftest entry: analyze one module given as a string."""
    return analyze_paths([path], declared=declared,
                         sources={path: source})


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------


def _res(status: str, detail: str) -> dict:
    return {"status": status, "detail": detail}


def _repo_rel(path: str) -> str:
    """Repo-relative form for manifest paths: the committed manifest
    must not drift just because two machines check the repo out under
    different roots."""
    repo = os.path.dirname(_package_root())
    ap = os.path.abspath(path)
    if ap.startswith(repo + os.sep):
        return os.path.relpath(ap, repo)
    return path


def build_manifest(report: SyncReport) -> dict:
    import datetime
    import platform as _platform

    rules: Dict[str, dict] = {}
    for rule in RULE_IDS:
        hits = [f for f in report.findings if f.rule == rule]
        if hits:
            rules[rule] = _res("fail", "; ".join(
                f.format() for f in hits[:8]) + (
                    f" (+{len(hits) - 8} more)" if len(hits) > 8 else ""))
        else:
            rules[rule] = _res("pass", RULE_TITLES[rule])
    nodes = sorted({c.node for c in report.contracts}
                   | {a for a, *_ in report.edges}
                   | {b for _, b, *_ in report.edges}
                   | {x for pair in report.declared for x in pair})
    edges = sorted({(a, b) for a, b, _, _ in report.edges}
                   | set(report.declared))
    return {
        "generated_by": "python -m arrow_matrix_tpu.analysis sync",
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "python_version": sys.version.split()[0],
        "platform": _platform.platform(),
        "package": "arrow_matrix_tpu",
        "modules": report.modules,
        "rules": rules,
        "contracts": sorted(
            (dict(c.to_json(), path=_repo_rel(c.path))
             for c in report.contracts),
            key=lambda c: (c["path"], c["class"])),
        "lock_graph": {"nodes": nodes,
                       "edges": [list(e) for e in edges]},
        "thread_entries": sorted(
            (dict(e, module=_repo_rel(e["module"]))
             for e in report.thread_entries),
            key=lambda e: (e["module"], e["line"])),
        "findings": [dict(f.to_json(), path=_repo_rel(f.path))
                     for f in report.findings],
        "ok": report.ok,
    }


def manifest_digest(manifest: dict) -> dict:
    """The behavior-only view the drift gate compares: rule statuses,
    contract shapes, the lock graph, and the thread-entry set —
    everything except the volatile environment keys."""
    return {
        "rules": {r: v["status"]
                  for r, v in manifest.get("rules", {}).items()},
        "contracts": {
            f"{c['path']}::{c['class']}": {
                "lock": c["lock"], "node": c["node"],
                "attrs": sorted(c["attrs"]),
                "callbacks": sorted(c["callbacks"]),
                "aliases": sorted(c["aliases"]),
            }
            for c in manifest.get("contracts", ())
        },
        "lock_graph": {
            "nodes": list(manifest.get("lock_graph", {})
                          .get("nodes", ())),
            "edges": [tuple(e) for e in manifest.get("lock_graph", {})
                      .get("edges", ())],
        },
        "thread_entries": sorted(
            f"{e['module']}:{e['kind']}:{e['target']}"
            for e in manifest.get("thread_entries", ())),
        "findings": sorted(
            f"{f['rule']}:{f['path']}:{f['message']}"
            for f in manifest.get("findings", ())),
        "ok": manifest.get("ok"),
    }


def manifest_drift(old: dict, new: dict) -> List[str]:
    """Human-readable differences between two manifests' digests
    (empty = no drift)."""
    a, b = manifest_digest(old), manifest_digest(new)
    problems: List[str] = []
    for rule in sorted(set(a["rules"]) | set(b["rules"])):
        if a["rules"].get(rule) != b["rules"].get(rule):
            problems.append(
                f"rule {rule} changed: {a['rules'].get(rule)} -> "
                f"{b['rules'].get(rule)}")
    for key in sorted(set(a["contracts"]) | set(b["contracts"])):
        if key not in b["contracts"]:
            problems.append(f"contract disappeared: {key}")
        elif key not in a["contracts"]:
            problems.append(f"new unrecorded contract: {key}")
        elif a["contracts"][key] != b["contracts"][key]:
            problems.append(f"contract changed: {key}")
    if a["lock_graph"] != b["lock_graph"]:
        old_e = set(a["lock_graph"]["edges"])
        new_e = set(b["lock_graph"]["edges"])
        for e in sorted(new_e - old_e):
            problems.append(f"new lock-graph edge: {e[0]} -> {e[1]}")
        for e in sorted(old_e - new_e):
            problems.append(f"lock-graph edge disappeared: "
                            f"{e[0]} -> {e[1]}")
        if old_e == new_e:
            problems.append("lock-graph nodes changed")
    if a["thread_entries"] != b["thread_entries"]:
        problems.append("thread-entry graph changed")
    if a["findings"] != b["findings"]:
        problems.append("finding set changed")
    if a["ok"] != b["ok"]:
        problems.append(f"overall ok changed: {a['ok']} -> {b['ok']}")
    return problems


def run_sync(out_path: str = DEFAULT_MANIFEST,
             root: Optional[str] = None, write: bool = True) -> dict:
    """Analyze the whole package; return (and write) the manifest."""
    report = analyze_package(root)
    manifest = build_manifest(report)
    if write:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    return manifest


# ---------------------------------------------------------------------------
# Fixtures + selftest
# ---------------------------------------------------------------------------


def fixture_contract(path: str) -> str:
    """Expected rule for a planted-violation fixture, from its
    ``rcN_*.py`` filename."""
    base = os.path.basename(path)
    for rule in RULE_IDS:
        if base.lower().startswith(rule.lower() + "_"):
            return rule
    raise ValueError(
        f"fixture {base!r} does not follow the rcN_<slug>.py convention")


def verify_fixture(path: str) -> Tuple[bool, str]:
    """(ok, detail): the fixture must fire its expected rule."""
    expected = fixture_contract(path)
    report = analyze_paths([path])
    fired = sorted({f.rule for f in report.findings})
    if expected in fired:
        return True, (f"{os.path.basename(path)}: {expected} fired "
                      f"({len(report.findings)} finding(s))")
    return False, (f"{os.path.basename(path)}: expected {expected}, "
                   f"got {fired or 'nothing'}")


_SELFTEST_GOOD = '''
import threading
from arrow_matrix_tpu.sync import guarded_by

@guarded_by("_lock", node="good", attrs=("items", "count"),
            callbacks=("on_done",))
class Good:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self.count = 0
        self.on_done = None

    def add(self, x):
        with self._lock:
            self.items.append(x)
            self.count += 1
        if self.on_done is not None:
            self.on_done(x)
'''

_SELFTEST_BROKEN = {
    "RC1": '''
import threading
from arrow_matrix_tpu.sync import guarded_by

@guarded_by("_lock", node="bad1", attrs=("items",))
class Bad:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def add(self, x):
        self.items.append(x)
''',
    "RC2": '''
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()

def forward():
    with LOCK_A:
        with LOCK_B:
            pass

def backward():
    with LOCK_B:
        with LOCK_A:
            pass
''',
    "RC3": '''
import threading
from arrow_matrix_tpu.sync import guarded_by

@guarded_by("_lock", node="bad3", callbacks=("on_done",))
class Bad:
    def __init__(self, on_done):
        self._lock = threading.Lock()
        self.on_done = on_done

    def fire(self):
        with self._lock:
            self.on_done()
''',
    "RC4": '''
import os
import threading
from arrow_matrix_tpu.sync import guarded_by

@guarded_by("_lock", node="bad4")
class Bad:
    def __init__(self):
        self._lock = threading.Lock()

    def flush(self, fd):
        with self._lock:
            os.fsync(fd)
''',
    "RC5": '''
import threading

CACHE = {}

def worker():
    CACHE["k"] = 1

def start():
    t = threading.Thread(target=worker)
    t.start()
''',
}


def selftest() -> Tuple[bool, List[str]]:
    """Inline good/broken twins (no dependence on the tests/ tree — the
    doctor probe runs this from any cwd) plus a runtime-witness
    round trip."""
    lines: List[str] = []
    ok = True

    good = analyze_source(_SELFTEST_GOOD, "<good>")
    if good.findings:
        ok = False
        lines.append("selftest GOOD twin produced findings: " + "; ".join(
            f.format() for f in good.findings))
    else:
        lines.append("good twin clean")
    for rule, src in _SELFTEST_BROKEN.items():
        rep = analyze_source(src, f"<broken-{rule}>")
        fired = {f.rule for f in rep.findings}
        if rule not in fired:
            ok = False
            lines.append(f"selftest broken twin for {rule} did not fire "
                         f"(got {sorted(fired) or 'nothing'})")
        else:
            lines.append(f"{rule} fires on its broken twin")

    # runtime witness round trip: an inverted order must raise, a
    # consistent reentrant one must not.
    import threading as _threading

    from arrow_matrix_tpu.sync import (LockOrderViolation, LockRegistry,
                                       _WitnessLock)

    reg = LockRegistry(declared=(("a", "b"),))
    la = _WitnessLock("a", _threading.RLock(), reg)
    lb = _WitnessLock("b", _threading.RLock(), reg)
    with la:
        with la:            # reentrant: no self-edge
            with lb:
                pass
    try:
        with lb:
            with la:
                pass
        ok = False
        lines.append("witness FAILED to raise on inverted order")
    except LockOrderViolation:
        lines.append("witness raises on inverted acquisition order")
    if reg.reentries < 1:
        ok = False
        lines.append("witness missed the reentrant acquisition")
    return ok, lines


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="graft_sync", description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_MANIFEST)
    ap.add_argument("--root", default=None,
                    help="package root to analyze (default: the "
                         "installed arrow_matrix_tpu)")
    ap.add_argument("--check", action="store_true",
                    help="do not write; fail on any violation OR drift "
                         "against the checked-in manifest")
    ap.add_argument("--selftest", action="store_true",
                    help="run the inline good/broken twins + witness "
                         "round trip and exit")
    ap.add_argument("--fixture", action="append", default=[],
                    help="verify a planted-violation fixture fires its "
                         "expected rule (repeatable)")
    args = ap.parse_args(argv)

    if args.selftest:
        ok, lines = selftest()
        for ln in lines:
            print(ln)
        print("selftest passed" if ok else "SELFTEST FAILED")
        return 0 if ok else 1

    if args.fixture:
        rc = 0
        for path in args.fixture:
            ok, detail = verify_fixture(path)
            print(("ok   " if ok else "FAIL ") + detail)
            rc = rc or (0 if ok else 1)
        return rc

    manifest = run_sync(out_path=args.out, root=args.root,
                        write=not args.check)
    for rule in RULE_IDS:
        v = manifest["rules"][rule]
        mark = "ok  " if v["status"] == "pass" else "FAIL"
        print(f"[{mark}] {rule}: {v['detail']}")
    print(f"contracts: {len(manifest['contracts'])}  "
          f"lock-graph edges: {len(manifest['lock_graph']['edges'])}  "
          f"thread entries: {len(manifest['thread_entries'])}  "
          f"modules: {manifest['modules']}")

    rc = 0 if manifest["ok"] else 1
    if args.check:
        try:
            with open(args.out, encoding="utf-8") as fh:
                checked_in = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"no readable checked-in manifest at {args.out}: {e}")
            return 1
        drift = manifest_drift(checked_in, manifest)
        for d in drift:
            print(f"drift: {d}")
        if drift:
            print(f"sync drift against {args.out} — rerun "
                  f"`python -m arrow_matrix_tpu.analysis sync` and "
                  f"commit the refreshed manifest")
            rc = 1
    else:
        print(f"manifest: {args.out}")
    print("sync proof passed" if rc == 0 else "SYNC PROOF FAILED")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
