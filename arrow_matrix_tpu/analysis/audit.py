"""Trace-time recompile audit (graft-lint engine 2).

The AST rules catch hazards syntactically; this engine catches them
*behaviorally*: it builds each core SpMM entry point on the host-CPU
virtual mesh, runs the jitted step twice with same-shape inputs, and
asserts the second call hits the compilation cache — zero recompiles.
A recompile on call two means a drifting static argument, an
unhashable cache key, or a fresh-jit-per-call factory: exactly the
regressions that turn the iterated ``X := A @ X`` bench from
compute-bound into compile-bound.

Alongside the cache check, each entry point is abstract-evaluated
(``jax.eval_shape``) and the output aval recorded, so shape/dtype
drift in the step contract also diffs in review.  Results land in a
manifest (default ``bench_cache/compile_manifest.json``) that is
checked in; ``tests/test_analysis.py`` re-runs the audit at reduced
scale inside tier-1.

Run standalone: ``python -m arrow_matrix_tpu.analysis audit``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable, Optional

import numpy as np


def _cache_size(fn) -> Optional[int]:
    """Entries in a jitted callable's compilation cache (None when the
    installed jax lacks the introspection hook)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class _CompileLogCounter(logging.Handler):
    """Fallback compile counter for jax without ``_cache_size``:
    counts log_compiles records while attached."""

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.count = 0

    def emit(self, record):
        msg = record.getMessage()
        if "ompil" in msg:   # "Compiling ..." / "Finished XLA compilation"
            self.count += 1

    def __enter__(self):
        import jax

        self._prev = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        logging.getLogger("jax").addHandler(self)
        return self

    def __exit__(self, *exc):
        import jax

        logging.getLogger("jax").removeHandler(self)
        jax.config.update("jax_log_compiles", False if not self._prev
                          else self._prev)


def _measure(step_fn, call: Callable[[], object]) -> dict:
    """Run ``call`` twice; return compile counts per call (preferring
    the jit cache size, falling back to compile-log counting)."""
    before = _cache_size(step_fn)
    if before is not None:
        call()
        after_first = _cache_size(step_fn)
        call()
        after_second = _cache_size(step_fn)
        return {"method": "cache_size",
                "compiles_first_call": after_first - before,
                "recompiles_second_call": after_second - after_first}
    with _CompileLogCounter() as c1:
        call()
    with _CompileLogCounter() as c2:
        call()
    return {"method": "log_compiles",
            "compiles_first_call": c1.count,
            "recompiles_second_call": c2.count}


def _aval(tree) -> object:
    import jax

    return jax.tree_util.tree_map(
        lambda s: {"shape": list(s.shape), "dtype": str(s.dtype)}, tree)


def audit_entry(name: str, step_fn, call: Callable[[], object],
                eval_shape: Callable[[], object]) -> dict:
    rec = {"entry": name}
    rec.update(_measure(step_fn, call))
    try:
        rec["abstract_eval"] = _aval(eval_shape())
    except Exception as e:  # aval is informational; the count is the gate
        rec["abstract_eval"] = f"error: {type(e).__name__}: {e}"
    rec["ok"] = (rec["recompiles_second_call"] == 0
                 and rec["compiles_first_call"] >= 1)
    return rec


# ---------------------------------------------------------------------------
# The audited entry points
# ---------------------------------------------------------------------------


def _entries(n: int, width: int, k: int, n_dev: int):
    """Build each core SpMM entry point at audit scale and yield
    (name, step_fn, call, eval_shape) quadruples."""
    import jax

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.utils.graphs import (
        barabasi_albert,
        random_csr,
        random_dense,
    )

    devs = jax.devices()[:n_dev]
    a = random_csr(n, n, 4, seed=7).astype(np.float32)
    x_host = random_dense(n, k, seed=3)

    # parallel/spmm_1d.py — PETSc-style 1-D row partition.
    from arrow_matrix_tpu.parallel.spmm_1d import MatrixSlice1D

    mesh1 = make_mesh((n_dev,), ("slices",), devices=devs)
    d1 = MatrixSlice1D(a, mesh1)
    x1 = d1.set_features(x_host)
    yield ("spmm_1d.MatrixSlice1D", d1._step,
           lambda: jax.block_until_ready(d1.spmm(x1)),
           lambda: jax.eval_shape(d1.spmm, x1))

    # parallel/spmm_15d.py — A-stationary 1.5D partition.
    from arrow_matrix_tpu.parallel.spmm_15d import SpMM15D

    c = 2 if n_dev % 4 == 0 else 1
    mesh15 = make_mesh((n_dev // c, c), ("rows", "repl"), devices=devs)
    d15 = SpMM15D(a, mesh15)
    x15 = d15.set_features(x_host)
    yield ("spmm_15d.SpMM15D", d15._step,
           lambda: jax.block_until_ready(d15.spmm(x15)),
           lambda: jax.eval_shape(d15.spmm, x15))

    # Arrow decomposition shared by the slim paths.
    ba = barabasi_albert(n, 4, seed=11)
    levels = arrow_decomposition(ba, width, max_levels=3,
                                 block_diagonal=True, seed=1)
    meshb = make_mesh((n_dev,), ("blocks",), devices=devs)

    # parallel/sell_slim.py — padding-free distributed slim layout.
    from arrow_matrix_tpu.parallel.sell_slim import SellSlim

    ds = SellSlim(levels[0].matrix, width, meshb)
    xs = ds.set_features(random_dense(levels[0].matrix.shape[0], k, seed=5))
    yield ("sell_slim.SellSlim", ds._step,
           lambda: jax.block_until_ready(ds.spmm(xs)),
           lambda: jax.eval_shape(ds.spmm, xs))

    # parallel/multi_level.py — the full multi-level arrow operator.
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow

    ml = MultiLevelArrow(levels, width, mesh=meshb)
    xm = ml.set_features(x_host[:ba.shape[0]])
    yield ("multi_level.MultiLevelArrow", ml._step,
           lambda: jax.block_until_ready(ml.step(xm)),
           lambda: jax.eval_shape(ml.step, xm))


def run_audit(out_path: str = os.path.join("bench_cache",
                                           "compile_manifest.json"),
              n: int = 512, width: int = 64, k: int = 8,
              n_dev: int = 4, write: bool = True) -> dict:
    """Audit every core SpMM entry point; return (and write) the
    manifest.  Requires an initialized multi-device jax (the CLI path
    forces a virtual CPU pool first; under pytest the conftest pool is
    reused)."""
    import datetime

    import jax

    entries = [audit_entry(*quad) for quad in _entries(n, width, k, n_dev)]
    manifest = {
        "generated_by": "python -m arrow_matrix_tpu.analysis audit",
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "jax_version": jax.__version__,
        "platform": jax.devices()[0].platform,
        "n_devices": n_dev,
        "scale": {"n": n, "width": width, "k": k},
        "entries": entries,
        "ok": all(e["ok"] for e in entries),
    }
    if write:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    return manifest


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="graft_lint audit", description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join("bench_cache",
                                                  "compile_manifest.json"))
    ap.add_argument("--devices", type=int, default=4,
                    help="virtual CPU devices (forced before jax init)")
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--k", type=int, default=8)
    args = ap.parse_args(argv)

    # The audit is a CPU-trace exercise by contract: force the virtual
    # pool BEFORE the first backend touch (conftest does the same for
    # tests; a tunneled TPU would both wedge and measure the wrong
    # thing).
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.devices)

    manifest = run_audit(out_path=args.out, n=args.n, width=args.width,
                         k=args.k, n_dev=args.devices)
    for e in manifest["entries"]:
        mark = "ok  " if e["ok"] else "FAIL"
        print(f"[{mark}] {e['entry']}: {e['compiles_first_call']} compile(s) "
              f"on call 1, {e['recompiles_second_call']} recompile(s) on "
              f"call 2 [{e['method']}]")
    print(f"manifest: {args.out}")
    print("audit passed" if manifest["ok"] else "AUDIT FAILED")
    return 0 if manifest["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
