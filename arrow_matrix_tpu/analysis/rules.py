"""The shipped graft-lint rules (R1-R9).

* R1 host-sync-in-jit — float()/.item()/np.asarray on traced values
* R2 recompile-hazard — jit-in-loop, jit-then-call, unhashable statics
* R3 missing-donation — scan-carry entry points jitted undonated
* R4 spec-axis-consistency — PartitionSpec axes the mesh never declares
* R5 dtype-promotion — bare float literals in traced arithmetic
* R6 unguarded-device-get — unbounded device->host fetches
* R7 unsynced-timing — perf_counter regions with no block_until_ready
* R8 swallowed-exception — broad except handlers that only discard
* R9 env-read-in-step — AMT_* environment reads inside the hot loop

Each rule encodes a hazard this codebase has actually met (or defends
against by convention), grounded at the call sites named in its
docstring.  Rules are registered with ``core.register`` and receive a
``ModuleContext``; they yield ``(line, message)`` pairs.  Suppress a
deliberate violation inline with ``# graft-lint: disable=Rn``.

The R rules are one quarter of the package's static-rule family:
H1-H7 (analysis/prove.py) prove HLO collective contracts, RC1-RC5
(analysis/sync.py, graft-sync) prove the serving stack's lock
discipline, and KC1-KC5 (analysis/kernels.py, graft-kcert) certify
the Pallas kernel layer's bounds, budgets, DMA ring discipline,
accumulator widths, and output coverage.  Ids are unique across all
four engines so one finding line always names one rule.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, Optional, Tuple

from arrow_matrix_tpu.analysis.core import (
    JIT_WRAPPERS,
    ModuleContext,
    register,
)

# ---------------------------------------------------------------------------
# Shared predicates
# ---------------------------------------------------------------------------

#: Attribute reads that are static (python values) under tracing.
_STATIC_ATTRS = frozenset({"shape", "ndim", "size", "itemsize", "dtype",
                           "nbytes", "n_blocks", "width", "banded", "fmt"})

#: Calls whose results are static python values under tracing.
_STATIC_CALLS = frozenset({"len", "min", "max", "abs", "round", "isinstance",
                           "numpy.prod", "math.prod", "numpy.dtype",
                           "math.ceil", "math.floor", "math.log2"})


def _is_static_expr(ctx: ModuleContext, node) -> bool:
    """Conservative: True only for expressions that trace to python
    values (shape arithmetic, dtype metadata, literals)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return True
    if isinstance(node, ast.Subscript):
        return _is_static_expr(ctx, node.value)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(ctx, node.operand)
    if isinstance(node, ast.BinOp):
        return (_is_static_expr(ctx, node.left)
                and _is_static_expr(ctx, node.right))
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_static_expr(ctx, e) for e in node.elts)
    if isinstance(node, ast.Call):
        full = ctx.resolve(node.func)
        if full in _STATIC_CALLS:
            return True
    return False


def _traced_calls(ctx: ModuleContext) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and ctx.in_traced_scope(node):
            yield node


def _jit_calls(ctx: ModuleContext) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and ctx.resolve(node.func) in JIT_WRAPPERS):
            yield node


def _keyword(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _wrapped_function(ctx: ModuleContext, call: ast.Call):
    """The function object a jit call wraps, unwrapping
    functools.partial: (node-or-None, display-name)."""
    if not call.args:
        return None, ""
    arg = call.args[0]
    if (isinstance(arg, ast.Call)
            and ctx.resolve(arg.func) == "functools.partial" and arg.args):
        arg = arg.args[0]
    if isinstance(arg, ast.Lambda):
        return arg, "<lambda>"
    if isinstance(arg, ast.Name):
        fns = ctx.funcs_by_name.get(arg.id, ())
        return (fns[0] if fns else None), arg.id
    return None, ctx.dotted(arg) or "<expr>"


# ---------------------------------------------------------------------------
# R1 — host-sync-in-jit
# ---------------------------------------------------------------------------


@register("R1", "host-sync-in-jit",
          "float()/int()/.item()/np.asarray on a traced value forces a "
          "blocking device->host transfer inside a jitted function")
def check_host_sync(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """Host-sync in a traced scope.

    ``float()``, ``.item()``, ``int(np.asarray(...))`` and friends are
    fine at build time (the ops/arrow_blocks.py packers run on the
    host), but inside a function passed to ``jax.jit``/``shard_map``
    they either fail on tracers or — worse, via ``io_callback``-style
    escapes — serialize the step on a device round-trip.  Shape/dtype
    reads (``x.shape``, ``len(x)``) are static and exempt.
    """
    for call in _traced_calls(ctx):
        line = call.lineno
        func = call.func
        if (isinstance(func, ast.Name) and func.id in ("float", "int", "bool")
                and len(call.args) == 1
                and not _is_static_expr(ctx, call.args[0])):
            yield line, (f"{func.id}() on a traced value is a host sync "
                         f"inside a jitted scope; keep it an array (or "
                         f"compute it from static shape/dtype metadata)")
        elif (isinstance(func, ast.Attribute) and func.attr == "item"
              and not call.args):
            yield line, (".item() blocks on device->host transfer inside "
                         "a traced scope")
        elif ctx.is_numpy_call(call, "asarray") or ctx.is_numpy_call(
                call, "array"):
            yield line, ("np.asarray/np.array inside a traced scope pulls "
                         "the value to the host every step; use jnp, or "
                         "hoist the conversion out of the jitted function")
        elif ctx.resolve(func) == "jax.device_get":
            yield line, "jax.device_get inside a traced scope is a host sync"


# ---------------------------------------------------------------------------
# R2 — recompile-hazard
# ---------------------------------------------------------------------------


def _lru_cached(ctx: ModuleContext, fn) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for deco in fn.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        if ctx.resolve(target) in ("functools.lru_cache", "functools.cache"):
            return True
    return False


_UNHASHABLE_ANNOS = frozenset({"list", "dict", "set", "List", "Dict", "Set",
                               "numpy.ndarray", "jax.Array"})


@register("R2", "recompile-hazard",
          "jit call sites that defeat the compilation cache: jit inside "
          "a loop, jit-then-call in a function body, unhashable static "
          "arguments")
def check_recompile(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """Jit-cache misses.

    A ``jax.jit(...)`` call creates a NEW cache; doing it per loop
    iteration or per function call recompiles every time (the hazard
    the cached ``_replicator`` in parallel/mesh.py exists to avoid).
    Static arguments must be hashable — a list/dict/ndarray-typed
    static arg raises or, with drifting values, recompiles per call.
    """
    for call in _jit_calls(ctx):
        line = call.lineno
        if ctx.in_loop(call):
            yield line, ("jax.jit inside a loop builds a fresh compilation "
                         "cache every iteration; hoist the jit out of the "
                         "loop (or functools.lru_cache the factory)")
        parent = ctx.parents.get(call)
        encl = ctx.enclosing_function(call)
        if (isinstance(parent, ast.Call) and parent.func is call
                and encl is not None and not _lru_cached(ctx, encl)):
            yield line, ("jit-then-call in a function body drops the "
                         "compiled cache on return (recompiles every "
                         "call); cache the jitted callable, e.g. via "
                         "functools.lru_cache keyed on the static config")

        fn, name = _wrapped_function(ctx, call)
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = fn.args.args
        defaults = fn.args.defaults
        default_of = {}
        for p, d in zip(params[len(params) - len(defaults):], defaults):
            default_of[p.arg] = d
        static_params = []
        nums = _keyword(call, "static_argnums")
        names = _keyword(call, "static_argnames")
        for v in ([nums] if nums is not None else []):
            for c in ([v] if isinstance(v, ast.Constant) else
                      getattr(v, "elts", [])):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        static_params.append(params[c.value].arg)
        for v in ([names] if names is not None else []):
            for c in ([v] if isinstance(v, ast.Constant) else
                      getattr(v, "elts", [])):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    static_params.append(c.value)
        for pname in static_params:
            d = default_of.get(pname)
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield line, (f"static argument {pname!r} of {name!r} "
                             f"defaults to an unhashable "
                             f"{type(d).__name__.lower()}; jit static "
                             f"args must be hashable (use a tuple)")
            ann = next((p.annotation for p in params if p.arg == pname
                        and p.annotation is not None), None)
            if ann is not None:
                a = ctx.resolve(ann) or ""
                if a.split("[")[0] in _UNHASHABLE_ANNOS:
                    yield line, (f"static argument {pname!r} of {name!r} "
                                 f"is annotated {a}; unhashable static "
                                 f"args raise (or recompile per call)")


# ---------------------------------------------------------------------------
# R3 — missing-donation
# ---------------------------------------------------------------------------

#: loop primitive -> positional index of its carry-init argument.
_CARRY_INIT_POS = {"jax.lax.scan": 1, "jax.lax.fori_loop": 3,
                   "jax.lax.while_loop": 2}
_CARRY_INIT_KW = {"jax.lax.scan": "init", "jax.lax.fori_loop": "init_val",
                  "jax.lax.while_loop": "init_val"}


def _first_param(fn) -> Optional[str]:
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        args = fn.args.args
        if args:
            first = args[0].arg
            return args[1].arg if first == "self" and len(args) > 1 else first
    return None


def _is_scan_carry_fn(ctx: ModuleContext, fn) -> bool:
    """Does ``fn`` thread its first parameter as the carry of a lax
    loop primitive (the iterated-update X := A @ X shape)?"""
    first = _first_param(fn)
    if first is None:
        return False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        full = ctx.resolve(node.func)
        if full not in _CARRY_INIT_POS:
            continue
        pos = _CARRY_INIT_POS[full]
        init = (node.args[pos] if len(node.args) > pos
                else _keyword(node, _CARRY_INIT_KW[full]))
        if isinstance(init, ast.Name) and init.id == first:
            return True
    return False


@register("R3", "missing-donation",
          "an iterated-update function (lax.scan over its first array "
          "argument) jitted without donate_argnums doubles its carry's "
          "memory footprint")
def check_donation(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """Missing buffer donation on the iterated SpMM scan.

    The ``X := A @ X`` scan rebinds its carry every call; without
    ``donate_argnums`` the old X stays live across the step and the
    footprint doubles (at protocol scale that is the difference between
    fitting in HBM and not).  A sibling jit of the SAME function WITH
    donation (the parallel/multi_level.py donated/undonated pair, where
    the undonated variant deliberately preserves its input) waives the
    site.
    """
    donated_names = set()
    candidates = []
    for call in _jit_calls(ctx):
        fn, name = _wrapped_function(ctx, call)
        if fn is None or not _is_scan_carry_fn(ctx, fn):
            continue
        has_donate = (_keyword(call, "donate_argnums") is not None
                      or _keyword(call, "donate_argnames") is not None)
        if has_donate:
            donated_names.add(name)
        else:
            candidates.append((call.lineno, name, fn))
    for line, name, fn in candidates:
        if name != "<lambda>" and name in donated_names:
            continue
        carry = _first_param(fn)
        yield line, (f"{name!r} scans its first argument {carry!r} as an "
                     f"iterated carry but is jitted without "
                     f"donate_argnums; donate the carry (or add a donated "
                     f"sibling jit) so the old buffer is reused")


# ---------------------------------------------------------------------------
# R4 — spec-axis-consistency
# ---------------------------------------------------------------------------

#: The package-default mesh axis, declared by parallel/mesh.py
#: ``make_mesh(axis_names=("blocks",))`` — in scope for any module that
#: imports the mesh helpers.
DEFAULT_MESH_AXES = frozenset({"blocks"})

_MESH_CTORS = frozenset({"Mesh", "make_mesh", "make_hybrid_mesh",
                         "AbstractMesh"})


def _declared_axes(ctx: ModuleContext) -> set:
    axes: set = set()

    def add_strings(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            axes.add(node.value)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                add_strings(e)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            full = ctx.resolve(node.func) or ""
            if full.rsplit(".", 1)[-1] in _MESH_CTORS:
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    add_strings(arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            pos_with_default = args.args[len(args.args)
                                         - len(args.defaults):]
            for p, d in list(zip(pos_with_default, args.defaults)) + list(
                    zip(args.kwonlyargs, args.kw_defaults)):
                if d is None:
                    continue
                if p.arg == "axis" or p.arg.endswith("_axis") \
                        or p.arg == "axis_names":
                    add_strings(d)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and "axis" in t.id.lower():
                    add_strings(node.value)
    if any(v.startswith("arrow_matrix_tpu.parallel")
           for v in ctx.aliases.values()):
        axes |= DEFAULT_MESH_AXES
    return axes


@register("R4", "spec-axis-consistency",
          "every PartitionSpec axis-name literal must be declared by a "
          "Mesh/make_mesh axis-names literal reachable in the module "
          "(or be the package default 'blocks' from parallel/mesh.py)")
def check_spec_axes(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """PartitionSpec axis names the mesh does not declare.

    ``P("rowz")`` against a mesh with axes ``("rows", "repl")`` fails
    only at dispatch — deep inside shard_map, with an error naming
    neither the spec nor the site.  The rule checks every string
    literal passed to ``PartitionSpec`` against the axis names declared
    in the module (Mesh/make_mesh literals, ``*_axis`` parameter
    defaults) plus the package default axis.  Skipped when the module
    declares no axes at all (no mesh context to check against).
    """
    declared = _declared_axes(ctx)
    if not declared:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        full = ctx.resolve(node.func) or ""
        if full.rsplit(".", 1)[-1] != "PartitionSpec":
            continue
        for arg in node.args:
            elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
                else [arg]
            for e in elts:
                if (isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                        and e.value not in declared):
                    yield node.lineno, (
                        f"PartitionSpec axis {e.value!r} is not declared "
                        f"by any mesh in scope (known axes: "
                        f"{sorted(declared)}); a mismatched spec fails "
                        f"only at dispatch time")


# ---------------------------------------------------------------------------
# R5 — dtype-promotion
# ---------------------------------------------------------------------------

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
              ast.Pow)


@register("R5", "dtype-promotion",
          "bare python float literals in traced arithmetic promote "
          "narrow dtypes (bf16 -> f32) silently")
def check_dtype_promotion(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """Python float literals in jitted arithmetic.

    Under jit, ``x * 0.5`` with a bf16 ``x`` stays bf16 only through
    weak-type promotion; the moment the literal is wrapped (e.g.
    ``np.float64(0.5)`` from a config) or promotion rules change, the
    whole hot-loop array silently widens and the layout-padding law
    (PERFORMANCE.md) is paying double bytes.  State the dtype:
    ``x * x.dtype.type(0.5)`` or ``jnp.asarray(0.5, x.dtype)``.
    Integer literals (shape arithmetic, indexing) are exempt.
    """
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, _ARITH_OPS)
                and ctx.in_traced_scope(node)):
            continue
        for lit, other in ((node.left, node.right),
                           (node.right, node.left)):
            if (isinstance(lit, ast.Constant)
                    and isinstance(lit.value, float)
                    and not _is_static_expr(ctx, other)):
                yield node.lineno, (
                    f"bare float literal {lit.value!r} in traced "
                    f"arithmetic relies on weak-type promotion; spell "
                    f"the dtype (x.dtype.type({lit.value!r}) or "
                    f"jnp.asarray({lit.value!r}, x.dtype))")
                break


# ---------------------------------------------------------------------------
# R6 — unguarded-device-get
# ---------------------------------------------------------------------------

#: Call roots that produce device arrays.
_DEVICE_PRODUCERS = ("jax.numpy.", "jax.lax.")
_DEVICE_CALLS = frozenset({
    "jax.device_put", "jax.make_array_from_callback",
    "jax.make_array_from_single_device_arrays", "jax.block_until_ready",
})


def _scope_nodes(ctx: ModuleContext):
    """(scope, nodes-in-scope) for the module and every function, where
    a node belongs to the innermost enclosing function only."""
    scopes: dict = {None: []}
    for fn in ast.walk(ctx.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
            scopes[fn] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Call)):
            scopes.setdefault(ctx.enclosing_function(node), []).append(node)
    for scope, nodes in scopes.items():
        nodes.sort(key=lambda n: (n.lineno, n.col_offset))
        yield scope, nodes


def _produces_device_value(ctx: ModuleContext, expr, device_names) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in device_names
    if isinstance(expr, ast.Call):
        full = ctx.resolve(expr.func) or ""
        if full in _DEVICE_CALLS or full.startswith(_DEVICE_PRODUCERS):
            return True
        # Method chain rooted at a known device value: y = x.sum() etc.
        root = expr.func
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id in device_names:
            return True
        if isinstance(root, ast.Call):
            return _produces_device_value(ctx, root, device_names)
    if isinstance(expr, (ast.Subscript, ast.Attribute)):
        return _produces_device_value(ctx, expr.value, device_names)
    if isinstance(expr, ast.BinOp):
        return (_produces_device_value(ctx, expr.left, device_names)
                or _produces_device_value(ctx, expr.right, device_names))
    return False


@register("R6", "unguarded-device-get",
          "np.asarray/np.array on a jax.Array outside utils/transfer.py "
          "is an unbounded device->host fetch")
def check_device_get(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """Unbounded device fetches.

    A tunneled TPU can wedge mid-transfer (utils/transfer.py
    postmortem): every large device->host or host->device movement must
    ride the bounded helpers (``chunked_asarray``,
    ``fetch_replicated``).  The rule tracks names assigned from
    jnp/lax/device_put expressions within each function and flags
    ``np.asarray``/``np.array`` applied to them — module
    utils/transfer.py itself is the one sanctioned home for the raw
    conversion.
    """
    if ctx.path.replace("\\", "/").endswith("utils/transfer.py"):
        return
    for scope, nodes in _scope_nodes(ctx):
        device_names: set = set()
        for node in nodes:
            if isinstance(node, ast.Assign):
                if _produces_device_value(ctx, node.value, device_names):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            device_names.add(t.id)
                else:
                    # Rebinding to a host value clears the mark.
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            device_names.discard(t.id)
            elif isinstance(node, ast.Call):
                if not (ctx.is_numpy_call(node, "asarray")
                        or ctx.is_numpy_call(node, "array")):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if _produces_device_value(ctx, arg, device_names):
                    name = (arg.id if isinstance(arg, ast.Name)
                            else ast.unparse(arg)[:40])
                    yield node.lineno, (
                        f"np.asarray({name}) fetches a device array "
                        f"through one unbounded RPC; route it through "
                        f"utils.transfer/fetch_replicated (bounded, "
                        f"wedge-safe) or waive if provably tiny")


# ---------------------------------------------------------------------------
# R7 — unsynced-timing
# ---------------------------------------------------------------------------

#: Host clocks used to time wall intervals.
_TIMER_CALLS = frozenset({"time.perf_counter", "time.monotonic",
                          "time.time"})


def _is_timer_call(ctx: ModuleContext, node) -> bool:
    return (isinstance(node, ast.Call)
            and ctx.resolve(node.func) in _TIMER_CALLS)


def _is_block_call(ctx: ModuleContext, node) -> bool:
    """Any spelling of a dispatch barrier: ``jax.block_until_ready(x)``,
    ``x.block_until_ready()``, or the tolerant helper from
    utils/logging.py imported as a bare name."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Name) and func.id == "block_until_ready":
        return True
    if isinstance(func, ast.Attribute) and func.attr == "block_until_ready":
        return True
    full = ctx.resolve(func) or ""
    return full.endswith("block_until_ready")


@register("R7", "unsynced-timing",
          "a perf_counter region that times a jitted callable without "
          "block_until_ready measures async dispatch, not device "
          "execution")
def check_unsynced_timing(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """Timing a jitted call without synchronising.

    JAX dispatch is asynchronous: ``t0 = time.perf_counter(); y = f(x);
    dt = time.perf_counter() - t0`` with a jitted ``f`` measures launch
    overhead (microseconds) while the device is still computing — the
    hazard the block-until-ready harness in obs/tracer.py exists to
    close.  The rule tracks names assigned from ``jax.jit(...)``, finds
    ``start = perf_counter()`` / ``... perf_counter() - start`` pairs in
    the same function, and flags jitted-name calls inside the region
    when no ``block_until_ready`` (any spelling) appears between start
    and stop.
    """
    jit_names: set = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and ctx.resolve(node.value.func) in JIT_WRAPPERS):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jit_names.add(t.id)
    if not jit_names:
        return
    for scope, nodes in _scope_nodes(ctx):
        starts = {}
        for node in nodes:
            if (isinstance(node, ast.Assign)
                    and _is_timer_call(ctx, node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        starts[t.id] = node.lineno
        if not starts:
            continue
        body = ctx.tree if scope is None else scope
        regions = []
        for node in ast.walk(body):
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and _is_timer_call(ctx, node.left)
                    and isinstance(node.right, ast.Name)
                    and node.right.id in starts
                    and node.lineno > starts[node.right.id]
                    and ctx.enclosing_function(node) is scope):
                regions.append((starts[node.right.id], node.lineno))
        for lo, hi in regions:
            in_region = [c for c in nodes
                         if isinstance(c, ast.Call)
                         and lo < c.lineno <= hi]
            if any(_is_block_call(ctx, c) for c in in_region):
                continue
            for call in in_region:
                if (isinstance(call.func, ast.Name)
                        and call.func.id in jit_names):
                    yield call.lineno, (
                        f"{call.func.id!r} (a jitted callable) is timed "
                        f"by a perf_counter region with no "
                        f"block_until_ready; dispatch is asynchronous, "
                        f"so this measures launch overhead, not device "
                        f"time — block on the result inside the region")


@register("R8", "swallowed-exception",
          "a broad `except Exception: pass` in runtime code silently "
          "swallows device errors, injected faults, and watchdog "
          "escapes — recovery must see them")
def check_swallowed_exception(ctx: ModuleContext
                              ) -> Iterable[Tuple[int, str]]:
    """Broad exception handlers whose only action is to discard.

    ``except Exception: pass`` (or bare ``except:``, or a tuple
    containing ``Exception``/``BaseException``, with a body of only
    ``pass``/``continue``/``...``) turns every failure — device OOM,
    injected chaos-gate faults, a supervisor's watchdog escape riding a
    worker thread — into silent success.  The graft-heal contract is
    that every fault is *seen* (flight-recorder event, metrics counter,
    retry) before any decision to continue; a swallow-and-go handler
    around a narrow, documented hazard should name the narrow exception
    type, and a deliberate broad swallow takes an inline waiver
    (``# graft-lint: disable=R8``) stating why.
    """
    broad = {"Exception", "BaseException"}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not node.body or not all(
                isinstance(s, ast.Pass) or isinstance(s, ast.Continue)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis)
                for s in node.body):
            continue
        t = node.type
        types = ([] if t is None
                 else list(t.elts) if isinstance(t, ast.Tuple)
                 else [t])
        names = [(ctx.resolve(nd) or "").rsplit(".", 1)[-1]
                 for nd in types]
        if t is not None and not any(nm in broad for nm in names):
            continue
        caught = ("bare except" if t is None
                  else "except " + "/".join(n for n in names if n))
        yield node.lineno, (
            f"{caught} whose body only discards swallows every "
            f"failure silently — catch the narrow exception this site "
            f"expects, or record the fault (obs.flight / metrics) "
            f"before continuing; a deliberate broad swallow takes an "
            f"inline `# graft-lint: disable=R8` waiver")


# ---------------------------------------------------------------------------
# R9 — env-read-in-step
# ---------------------------------------------------------------------------

#: Spellings of an environment read, post alias resolution.
_ENV_GETTERS = frozenset({"os.getenv", "os.environ.get"})


def _env_read_name(ctx: ModuleContext, node) -> Optional[str]:
    """The constant variable name an expression reads from the
    environment, or None when it is not an env read / not constant."""
    if isinstance(node, ast.Call):
        if ctx.resolve(node.func) in _ENV_GETTERS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    elif isinstance(node, ast.Subscript):
        if ctx.resolve(node.value) == "os.environ":
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return sl.value
    return None


@register("R9", "env-read-in-step",
          "os.environ/os.getenv reads of AMT_* knobs inside a jitted "
          "step function or a per-iteration loop re-read host state "
          "every step; resolve the knob once at build time")
def check_env_read_in_step(ctx: ModuleContext) -> Iterable[Tuple[int, str]]:
    """AMT_* environment reads on the per-step path.

    The AMT_* knobs are build-time configuration (the pallas_sell.py
    fuse gate, decompose worker counts, the comm chunk sizes): every
    shipped read happens once at module import or object construction.
    An ``os.environ.get("AMT_...")`` inside a function handed to
    jax.jit/shard_map is worse than slow — the value is baked at TRACE
    time, so flipping the knob later silently does nothing while the
    code reads as if it were live.  Inside a per-iteration loop it is a
    dict probe plus getenv lock on the hot path and drifts the bench
    timings the obs layer records.  Hoist the read to build time and
    thread the value in as an argument or closure constant; a
    deliberate per-step read (e.g. a chaos-gate probe) takes an inline
    ``# graft-lint: disable=R9`` waiver stating why.
    """
    for node in ast.walk(ctx.tree):
        name = _env_read_name(ctx, node)
        if name is None or not name.startswith("AMT_"):
            continue
        if ctx.in_traced_scope(node):
            yield node.lineno, (
                f"environment read of {name!r} inside a jitted scope is "
                f"baked at trace time (silently stale after the first "
                f"compile); hoist it to build time and pass the value in")
        elif (ctx.in_loop(node)
              and ctx.enclosing_function(node) is not None):
            yield node.lineno, (
                f"environment read of {name!r} inside a per-iteration "
                f"loop probes host state every step; resolve the knob "
                f"once before the loop")
