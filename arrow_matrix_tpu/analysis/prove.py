"""HLO-level collective-contract verifier (graft-lint engine 3).

The paper's value proposition is a *provable* communication bound —
arrow decomposition caps per-step exchange volume — yet obs/comm can
only check that bound dynamically, after a run.  This engine proves it
statically: each parallel executor exports a ``collective_contract``
(analysis/contracts.py), the prover lowers every shipped entry point
on the host-CPU virtual mesh, parses the optimized HLO into a
structured ``CollectiveSummary``, and checks six rules:

* **H1** no unattributed collectives — every collective kind in the
  lowered AND compiled step must be declared (a GSPMD surprise
  all-gather fails here before it ever regresses a bench);
* **H2** collective bytes match the contract's ideal within the
  declared ratio band (the static twin of obs/comm's measured/ideal);
* **H3** repl=c programs carry k/(c·S) feature slabs through every
  collective (the ÷c law, visible as the leading shape dimension) and
  the deferred psum merge prices EXACTLY ``reduce_comm_bytes``;
* **H4** no silent dtype upcasts: no f64 anywhere in the lowered step
  and no float-widening ``convert`` ops beyond the benign index/mask
  allowlist.  graft-classes relaxes this *per-class* into **H4'**: a
  reduced-precision contract (dtype bf16/int8, the approx traffic
  class) declares its carriage->f32 accumulator widening — that
  convert is benign — but in exchange every collective operand must
  actually carry the reduced dtype (an approx program whose exchanges
  still move f32 never earned its smaller byte band);
* **H5** donated inputs are actually aliased — the lowered stablehlo
  carries ``jax.buffer_donor``/``tf.aliasing_output`` and the compiled
  HLO header carries ``input_output_alias`` for the declared
  parameters (a dropped donation shows neither: the phantom-copy /
  use-after-donate detector);
* **H6** no layout thrash in the hot loop: zero ``transpose`` ops and
  at most ``hot_copy_budget`` ``copy`` ops inside while-loop bodies.

Results land in ``bench_cache/hlo_manifest.json`` (checked in and
diffable, like compile_manifest.json).  Run standalone:
``python -m arrow_matrix_tpu.analysis prove`` or the ``graft_prove``
console script; ``tools/proof_gate.py`` is the nonzero-exit CI
wrapper, and the tier-1 suite re-runs the prover at the same reduced
scale and fails on manifest drift.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from arrow_matrix_tpu.analysis.contracts import CollectiveContract
from arrow_matrix_tpu.utils import commstats

RULE_IDS = ("H1", "H2", "H3", "H4", "H5", "H6", "H7")

DEFAULT_MANIFEST = os.path.join("bench_cache", "hlo_manifest.json")

#: Prove scale — shared by the CLI default, the checked-in manifest,
#: and the tier-1 drift test (tests/test_prove.py); the manifest is
#: only comparable at one fixed scale.
PROVE_SCALE = {"n": 128, "width": 32, "k": 8, "n_dev": 4}

# ---------------------------------------------------------------------------
# HLO text analysis (host-only; no jax import required)
# ---------------------------------------------------------------------------

#: ``%y = f32[8,16] convert(s32[8,16] %x)`` -> ("f32", "s32").
_CONVERT_RE = re.compile(r"=\s*(\w+)\[[0-9,]*\]\S*\s+convert\(\s*(\w+)\[")

_FLOAT_BYTES = {"f16": 2, "bf16": 2, "f32": 4, "f64": 8}

#: Carriage itemsize by contract dtype name, for the H4' operand
#: check (HLO spells int8 "s8"; contracts use the numpy name).
_CARRIAGE_BYTES = {"s8": 1, "u8": 1, "int8": 1, "uint8": 1,
                   "f16": 2, "bf16": 2, "f32": 4, "f64": 8}

#: (src, dst) convert pairs that are benign on every backend: index
#: widening and mask materialization, not a carried-value upcast.
BENIGN_CONVERTS = frozenset({
    ("pred", "f32"), ("pred", "s32"),
    ("s8", "s32"), ("u8", "s32"), ("s16", "s32"), ("u16", "s32"),
    ("u32", "s32"), ("s32", "u32"),
})


@dataclasses.dataclass
class CollectiveSummary:
    """Structured account of one HLO program text."""

    #: kind -> {"count": int, "bytes": int} (commstats schema).
    kinds: Dict[str, dict]
    total_bytes: int
    #: Leading dimension of every collective output shape, in order.
    leading_dims: List[int]
    #: Element dtype of every collective output shape, in order
    #: (tuple shapes contribute one entry per element) — the H4'
    #: evidence that an approx program's exchanges really carry the
    #: reduced carriage dtype.
    collective_dtypes: List[str]
    #: (src_dtype, dst_dtype) of every convert op.
    converts: List[Tuple[str, str]]
    has_f64: bool
    #: copy / transpose ops inside while-loop body computations.
    while_copies: int
    while_transposes: int
    #: Parameter numbers carried by the input_output_alias header.
    aliased_params: Tuple[int, ...]

    def present_kinds(self) -> frozenset:
        return frozenset(k for k in commstats.COLLECTIVE_OPS
                         if self.kinds[k]["count"])


def _collective_shapes(text: str) -> Tuple[List[int], List[str]]:
    """(leading dims, element dtypes) of every collective output
    shape, in program order."""
    dims: List[int] = []
    dtypes: List[str] = []
    for line in text.splitlines():
        s = line.strip()
        if "=" not in s:
            continue
        for kind in commstats.COLLECTIVE_OPS:
            m = re.search(rf"=\s*(.+?)\s{re.escape(kind)}(?:-start)?\(", s)
            if m:
                for dt, d in commstats._SHAPE_RE.findall(m.group(1)):
                    dtypes.append(dt)
                    first = d.split(",")[0]
                    if first:
                        dims.append(int(first))
                break
    return dims, dtypes


def _computation_blocks(text: str) -> Dict[str, List[str]]:
    """HLO computation name -> its body lines."""
    blocks: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    for line in text.splitlines():
        m = re.match(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\.clone\S*)?\(",
                     line)
        if m and line.rstrip().endswith("{"):
            cur = m.group(1)
            blocks[cur] = []
        elif cur is not None:
            if line.startswith("}"):
                cur = None
            else:
                blocks[cur].append(line)
    return blocks


def _while_body_ops(text: str) -> Tuple[int, int]:
    """(copy, transpose) op counts inside while-loop body computations."""
    bodies = set(re.findall(r"body=%?([\w.\-]+)", text))
    blocks = _computation_blocks(text)
    copies = transposes = 0
    for name in bodies:
        for line in blocks.get(name, ()):
            if re.search(r"=\s*\S+\s+copy\(", line):
                copies += 1
            elif re.search(r"=\s*\S+\s+transpose\(", line):
                transposes += 1
    return copies, transposes


def _aliased_params(text: str) -> Tuple[int, ...]:
    """Parameter numbers in the compiled-HLO input_output_alias header,
    e.g. ``input_output_alias={ {}: (0, {}, may-alias) }`` -> (0,)."""
    m = re.search(r"input_output_alias=\{(.*?)\}\s*(?:,|$)", text,
                  flags=re.MULTILINE | re.DOTALL)
    if not m:
        return ()
    return tuple(sorted({int(p) for p in
                         re.findall(r"\(\s*(\d+)\s*,", m.group(1))}))


def summarize_hlo(text: str) -> CollectiveSummary:
    """Parse one HLO program text into a CollectiveSummary."""
    stats = commstats._parse_hlo_collectives(text)
    copies, transposes = _while_body_ops(text)
    dims, coll_dtypes = _collective_shapes(text)
    return CollectiveSummary(
        kinds={k: dict(stats[k]) for k in commstats.COLLECTIVE_OPS},
        total_bytes=int(stats["total_bytes"]),
        leading_dims=dims,
        collective_dtypes=coll_dtypes,
        converts=[(src, dst) for dst, src in _CONVERT_RE.findall(text)],
        has_f64=bool(re.search(r"\bf64\[", text)),
        while_copies=copies,
        while_transposes=transposes,
        aliased_params=_aliased_params(text),
    )


# ---------------------------------------------------------------------------
# The six rules.  Each returns {"status": "pass"|"fail"|"skip",
# "detail": str}; pure functions over summaries so the fixture tests
# and proof_gate share them without compiling anything.
# ---------------------------------------------------------------------------


def _res(status: str, detail: str) -> dict:
    return {"status": status, "detail": detail}


def check_h1(lowered: CollectiveSummary, compiled: CollectiveSummary,
             contract: CollectiveContract) -> dict:
    """No unattributed collectives in either HLO source."""
    bad = []
    for label, summ, allowed in (
            ("lowered", lowered, frozenset(contract.lowered_kinds)),
            ("compiled", compiled, frozenset(contract.compiled_kinds))):
        extra = summ.present_kinds() - allowed
        if extra:
            bad.append(f"{label} HLO contains undeclared "
                       f"{sorted(extra)} (declared: {sorted(allowed)})")
    if bad:
        return _res("fail", "; ".join(bad))
    return _res("pass",
                f"lowered={sorted(lowered.present_kinds())} "
                f"compiled={sorted(compiled.present_kinds())} all declared")


def check_h2(measured_bytes: int, source: str,
             contract: CollectiveContract) -> dict:
    """Collective bytes match the contract's ideal within tolerance."""
    if contract.step_bytes == 0:
        if measured_bytes == 0:
            return _res("pass", "zero-comm contract, zero measured")
        return _res("fail",
                    f"contract promises zero communication but the "
                    f"{source} HLO carries {measured_bytes} collective "
                    f"bytes")
    ratio = measured_bytes / contract.step_bytes
    lo, hi = contract.ratio_band
    if lo <= ratio <= hi:
        return _res("pass",
                    f"{measured_bytes} B ({source}) / ideal "
                    f"{contract.step_bytes} B = {ratio:.3f} in "
                    f"[{lo}, {hi}]")
    return _res("fail",
                f"{measured_bytes} B ({source}) / ideal "
                f"{contract.step_bytes} B = {ratio:.3f} outside "
                f"[{lo}, {hi}]")


def check_host_bytes(contract: CollectiveContract, num_hosts: int,
                     num_devices: int, measured_bytes: int,
                     pattern: str = "ring",
                     band: Optional[Tuple[float, float]] = None) -> dict:
    """graft-host extension of H2: the measured bytes that cross a
    host fault-domain boundary match the contract's inter-host slice
    (``CollectiveContract.inter_host_bytes``) within the band.

    Deliberately NOT in :data:`RULE_IDS` — H1–H7 are topology-free
    promises checked against the checked-in manifest at one fixed
    scale, while the inter-host slice depends on the deployment's
    host split; this check runs from the fleet/host gates, which know
    the split they rehearsed.  Defaults to the contract's own H2
    ``ratio_band``."""
    ideal = contract.inter_host_bytes(num_hosts, num_devices,
                                      pattern=pattern)
    if ideal == 0:
        if measured_bytes == 0:
            return _res("pass",
                        f"hosts={num_hosts}: no inter-host slice "
                        f"promised, none measured")
        return _res("fail",
                    f"hosts={num_hosts} promises zero inter-host "
                    f"bytes but {measured_bytes} B crossed a domain "
                    f"boundary")
    lo, hi = band if band is not None else contract.ratio_band
    ratio = measured_bytes / ideal
    detail = (f"{measured_bytes} B inter-host / ideal {ideal} B "
              f"({pattern}, hosts={num_hosts}, devices={num_devices})"
              f" = {ratio:.3f} vs [{lo}, {hi}]")
    return _res("pass" if lo <= ratio <= hi else "fail", detail)


def check_h3(lowered: CollectiveSummary, contract: CollectiveContract,
             k: int, merge_bytes: Optional[int] = None) -> dict:
    """The ÷c law: repl=c exchanges carry k/(c·S) slabs, and the
    deferred psum merge prices exactly ``reduce_comm_bytes``."""
    if contract.h3_exempt:
        return _res("skip", contract.h3_exempt)
    if contract.repl <= 1:
        if contract.reduce_bytes != 0:
            return _res("fail",
                        f"repl=1 contract declares nonzero merge bytes "
                        f"({contract.reduce_bytes})")
        return _res("pass", "repl=1: no replica merge priced")
    slab = contract.expected_slab(k)
    bad_dims = [d for d in lowered.leading_dims if d != slab]
    if bad_dims:
        return _res("fail",
                    f"repl={contract.repl} S={contract.overlap_slabs} "
                    f"expects every collective to carry a {slab}-row "
                    f"feature slab, found leading dims {bad_dims}")
    if merge_bytes is not None and merge_bytes != contract.reduce_bytes:
        return _res("fail",
                    f"replica merge program carries {merge_bytes} B "
                    f"but the contract prices exactly "
                    f"{contract.reduce_bytes} B")
    return _res("pass",
                f"all collectives carry the k/(c*S)={slab} slab; merge "
                f"prices {contract.reduce_bytes} B"
                + (" (verified)" if merge_bytes is not None else ""))


def check_h4(lowered: CollectiveSummary,
             contract: CollectiveContract) -> dict:
    """No silent dtype upcasts in the lowered (dtype-honest) step.

    The exact (f32) class gets the original H4.  A reduced-precision
    contract (graft-classes approx carriage: bf16 or int8) gets H4':
    the carriage->f32 accumulator widening is *declared* by the
    contract's dtype, so that one convert is benign — but in exchange
    every collective operand must actually carry a dtype no wider
    than the carriage, otherwise the program is paying exact-class
    exchange bytes while claiming the approx byte band."""
    carriage = contract.dtype
    approx = carriage in _CARRIAGE_BYTES and _CARRIAGE_BYTES[carriage] < 4
    bad = []
    if lowered.has_f64 and carriage != "f64":
        bad.append(f"f64 shapes in a {carriage}-carriage program "
                   f"(weak-type promotion or a float64 literal)")
    for src, dst in lowered.converts:
        if approx and src == carriage and dst == "f32":
            continue   # H4': the declared accumulator widening
        if (src in _FLOAT_BYTES and dst in _FLOAT_BYTES
                and _FLOAT_BYTES[dst] > _FLOAT_BYTES[src]
                and (src, dst) not in BENIGN_CONVERTS):
            bad.append(f"float-widening convert {src}->{dst}")
    if approx:
        limit = _CARRIAGE_BYTES[carriage]
        wide = sorted({dt for dt in lowered.collective_dtypes
                       if _CARRIAGE_BYTES.get(dt, 0) > limit})
        if wide:
            bad.append(f"{carriage}-class collectives carry "
                       f"full-precision operands {wide} — the approx "
                       f"byte band was never earned")
    if bad:
        return _res("fail", "; ".join(sorted(set(bad))))
    if approx:
        n_acc = sum(1 for src, dst in lowered.converts
                    if src == carriage and dst == "f32")
        kinds = sorted(set(lowered.collective_dtypes)) or ["none"]
        return _res("pass",
                    f"H4'({carriage}): collective operands {kinds}, "
                    f"{n_acc} declared accumulator widening(s), no "
                    f"other upcasts")
    n_benign = len(lowered.converts)
    return _res("pass",
                f"no f64, no widening converts "
                f"({n_benign} benign index/mask convert(s))")


def check_h5(donor_attrs: bool, compiled_scan: Optional[CollectiveSummary],
             contract: CollectiveContract) -> dict:
    """Donated inputs actually alias their outputs."""
    if not contract.donated_params:
        return _res("skip", "no donated entry point shipped")
    if compiled_scan is None:
        return _res("fail", "contract declares donated params but no "
                            "donated program was provided to the prover")
    missing = set(contract.donated_params) - set(
        compiled_scan.aliased_params)
    if not donor_attrs:
        return _res("fail",
                    "donation dropped at lowering: no jax.buffer_donor/"
                    "tf.aliasing_output attribute in the stablehlo (the "
                    "donated argument no longer matches an output)")
    if missing:
        return _res("fail",
                    f"compiled HLO aliases params "
                    f"{list(compiled_scan.aliased_params)} but the "
                    f"contract donates {list(contract.donated_params)} "
                    f"— phantom copy on {sorted(missing)}")
    return _res("pass",
                f"params {list(contract.donated_params)} aliased in "
                f"the compiled HLO (input_output_alias)")


def check_h7(stage_summaries: Optional[List[CollectiveSummary]],
             contract: CollectiveContract) -> dict:
    """graft-reshard's bounded-scratch law, statically: every stage of
    a staged exchange keeps its per-device send+recv collective
    buffers within the declared scratch budget.  The HLO accountant
    counts each all-to-all's per-device recv shape once; the send
    payload is the same size, so a stage's scratch is 2x its counted
    collective bytes.  An over-budget stage in the LOWERED HLO means
    the plan compiler emitted exactly the memory cliff the staging
    exists to remove."""
    if contract.scratch_budget_bytes <= 0:
        return _res("skip", "no staged scratch budget declared")
    if not stage_summaries:
        return _res("fail",
                    "contract declares a scratch budget of "
                    f"{contract.scratch_budget_bytes} B but no stage "
                    f"programs were provided to the prover")
    budget = contract.scratch_budget_bytes
    over = []
    peak = 0
    for i, s in enumerate(stage_summaries):
        scratch = 2 * s.total_bytes
        peak = max(peak, scratch)
        if scratch > budget:
            over.append(f"stage {i} carries {scratch} B send+recv "
                        f"> budget {budget} B")
    if over:
        return _res("fail", "; ".join(over))
    return _res("pass",
                f"{len(stage_summaries)} stage(s), peak per-device "
                f"send+recv {peak} B <= budget {budget} B")


def check_h6(compiled: CollectiveSummary,
             contract: CollectiveContract) -> dict:
    """No layout-thrash copy/transpose ops in the hot loop."""
    if compiled.while_transposes:
        return _res("fail",
                    f"{compiled.while_transposes} transpose op(s) in "
                    f"while-loop bodies — layout thrash every iteration")
    if compiled.while_copies > contract.hot_copy_budget:
        return _res("fail",
                    f"{compiled.while_copies} copy op(s) in while-loop "
                    f"bodies exceed the budget of "
                    f"{contract.hot_copy_budget}")
    return _res("pass",
                f"hot loop: {compiled.while_copies} copy(s) (budget "
                f"{contract.hot_copy_budget}), 0 transposes")


# ---------------------------------------------------------------------------
# Fixture verification (shared by tests, proof_gate --fixture, doctor)
# ---------------------------------------------------------------------------


def fixture_contract() -> CollectiveContract:
    """The contract the checked-in repl=2 HLO fixtures are judged
    against (tests/fixtures/collectives_repl2.hlo and its
    intentionally-broken sibling): a SELL-style repl=2 step at k=8
    (4-row slabs) — one tuple all-to-all (2 x f32[4,64] = 2048 B) and
    one replica-group all-reduce (f32[4,64] = 1024 B), merge priced at
    2048 B."""
    return CollectiveContract(
        algorithm="fixture_sell_repl2",
        step_bytes=3072, reduce_bytes=2048, repl=2, overlap_slabs=1,
        dtype="f32",
        lowered_kinds=("all-to-all", "all-reduce"),
        compiled_kinds=("all-to-all", "all-reduce"),
        ratio_band=(0.5, 2.0),
        notes="pinned parsing contract for the H1-H3 fixture tests")


def verify_fixture(text: str, contract: Optional[CollectiveContract] = None,
                   k: int = 8, merge_bytes: int = 2048) -> dict:
    """Run H1-H3 on one HLO fixture text; returns
    ``{"H1": {...}, "H2": {...}, "H3": {...}, "ok": bool}``.  The same
    text stands in for both sources (fixtures are single programs)."""
    contract = contract or fixture_contract()
    summ = summarize_hlo(text)
    results = {
        "H1": check_h1(summ, summ, contract),
        "H2": check_h2(summ.total_bytes, "fixture", contract),
        "H3": check_h3(summ, contract, k, merge_bytes=merge_bytes),
    }
    results["ok"] = all(r["status"] == "pass" for r in results.values()
                        if isinstance(r, dict))
    return results


#: Minimal inline twins of the checked-in fixtures, for the in-process
#: self-test (amt_doctor must not depend on the tests/ tree existing).
_SELFTEST_GOOD = """\
HloModule selftest_repl2_good
ENTRY %main (p0: f32[4,64]) -> f32[4,64] {
  %p0 = f32[4,64]{1,0} parameter(0)
  %a2a = (f32[4,64], f32[4,64]) all-to-all(f32[4,64]{1,0} %p0, f32[4,64]{1,0} %p0), replica_groups={{0,1}}
  ROOT %ar = f32[4,64]{1,0} all-reduce(f32[4,64]{1,0} %p0), replica_groups={{0,1}}, to_apply=%add
}
"""

_SELFTEST_BROKEN = _SELFTEST_GOOD.replace(
    "ROOT %ar",
    "%ag = f32[8,256]{1,0} all-gather(f32[4,64]{1,0} %p0), "
    "replica_groups={{0,1}}, dimensions={0}\n  ROOT %ar")


def selftest() -> bool:
    """The gate must pass a conforming program and trip on a planted
    surprise all-gather (wrong kind, wrong bytes, wrong slab)."""
    good = verify_fixture(_SELFTEST_GOOD)
    broken = verify_fixture(_SELFTEST_BROKEN)
    return bool(good["ok"]) and not broken["ok"] and all(
        broken[r]["status"] == "fail" for r in ("H1", "H2", "H3"))


# ---------------------------------------------------------------------------
# The proved entry points
# ---------------------------------------------------------------------------


def _entries(n: int, width: int, k: int, n_dev: int):
    """Build every contracted executor over the (c, S) grid at prove
    scale; yield ``(name, contract, programs)`` where programs is a
    dict of lowerable callables:

    * ``step``: (jit_fn, args, kwargs) — the per-iteration program;
    * ``scan``: donated scan entry, when the executor ships one;
    * ``merge``: the deferred 2.5D psum merge, when repl > 1.

    Unsupported grid combos are yielded as ``(name, None, reason)`` so
    the manifest records WHY a cell is absent instead of silently
    shrinking coverage.
    """
    import jax

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel.mesh import make_mesh, make_repl_mesh
    from arrow_matrix_tpu.utils.graphs import (
        barabasi_albert,
        random_csr,
        random_dense,
    )

    devs = jax.devices()[:n_dev]
    import numpy as np

    a = random_csr(n, n, 4, seed=7).astype(np.float32)
    x_host = random_dense(n, k, seed=3)

    ba = barabasi_albert(n, 4, seed=11)
    levels = arrow_decomposition(ba, width, max_levels=3,
                                 block_diagonal=True, seed=1)

    # -- spmm_1d (petsc-style 1-D): no replication/overlap modes -------
    from arrow_matrix_tpu.parallel.spmm_1d import MatrixSlice1D

    d1 = MatrixSlice1D(a, make_mesh((n_dev,), ("slices",), devices=devs))
    x1 = d1.set_features(x_host)
    yield ("spmm_1d[c=1,S=1]", d1.collective_contract(k), {
        "step": (d1._step, (d1.l_cols, d1.l_data, d1.nl_cols,
                            d1.nl_data, d1.send_idx, x1), {}),
    })
    yield ("spmm_1d[c=2]", None,
           "MatrixSlice1D has no replication mode (the 1.5D/SELL "
           "executors carry the 2.5D scheme)")

    # -- spmm_15d (A-stationary 1.5D): c via the mesh repl axis --------
    from arrow_matrix_tpu.parallel.spmm_15d import SpMM15D

    for c in (1, 2):
        mesh15 = make_mesh((n_dev // c, c), ("rows", "repl"),
                           devices=devs)
        d15 = SpMM15D(a, mesh15)
        x15 = d15.set_features(x_host)
        yield (f"spmm_15d[c={c},S=1]", d15.collective_contract(k), {
            "step": (d15._step, (d15.a_cols, d15.a_data, x15), {}),
        })
    yield ("spmm_15d[S=2]", None,
           "SpMM15D has no overlap schedule (its round loop already "
           "pipelines the broadcast)")

    # -- sell_slim / sell_multi over the full (c, S) grid --------------
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel, SellSlim

    for c in (1, 2):
        if c == 1:
            mesh = make_mesh((n_dev,), ("blocks",), devices=devs)
            repl_axis = None
        else:
            mesh = make_repl_mesh(n_dev, c, devices=devs)
            repl_axis = "repl"
        for s in (1, 2):
            ds = SellSlim(levels[0].matrix, width, mesh,
                          overlap_slabs=s, repl_axis=repl_axis)
            xs = ds.set_features(
                random_dense(levels[0].matrix.shape[0], k, seed=5))
            o = ds.ops
            progs = {"step": (ds._step, (o.body, o.head, o.head_unsort,
                                         o.orig_pos, xs), {})}
            if c > 1:
                ct = ds.spmm(xs)
                progs["merge"] = (ds._merge, (ct,), {})
            yield (f"sell_slim[c={c},S={s}]",
                   ds.collective_contract(k), progs)

            ml = SellMultiLevel(levels, width, mesh, routing="a2a",
                                overlap_slabs=s, repl_axis=repl_axis)
            xm = ml.set_features(random_dense(ml.n, k, seed=5))
            args = (xm,) + ml.step_operands()
            progs = {
                "step": (ml._step, args, {}),
                "scan": (ml._scan_donated, args, {"n": 2}),
            }
            if c > 1:
                ct = ml.step(xm)
                progs["merge"] = (ml._merge, (ct,), {})
            yield (f"sell_multi[c={c},S={s}]",
                   ml.collective_contract(k), progs)

    # -- multi_level: a2a mesh (c=1) and single-chip fold (c via repl) -
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow

    meshb = make_mesh((n_dev,), ("blocks",), devices=devs)
    for s in (1, 2):
        ml = MultiLevelArrow(levels, width, mesh=meshb, routing="a2a",
                             overlap_slabs=s)
        xm = ml.set_features(x_host[:ba.shape[0]])
        args = (xm,) + ml.step_operands()
        yield (f"multi_level_a2a[c=1,S={s}]",
               ml.collective_contract(k), {
                   "step": (ml._step, args, {}),
                   "scan": (ml._scan_steps_donated, args, {"n": 2}),
               })
    yield ("multi_level_a2a[c=2]", None,
           "MultiLevelArrow repl>1 requires fmt='fold' (mesh "
           "replication is the SellSlim/SellMultiLevel repl_axis mode)")

    for c in (1, 2):
        mf = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                             repl=c)
        xf = mf.set_features(x_host[:ba.shape[0]])
        args = (xf,) + mf.step_operands()
        yield (f"multi_level_fold[c={c},S=1]",
               mf.collective_contract(k), {
                   "step": (mf._step, args, {}),
                   "scan": (mf._scan_steps_donated, args, {"n": 2}),
               })

    # -- graft-classes approx carriage (H4') ---------------------------
    # The traffic-class entries: the mesh executors at bf16 (real
    # reduced-precision collectives, ideal bands halved by the contract
    # itemsize) and the single-chip fold at int8 (zero-comm quantized
    # (q, scale) carriage).  One grid cell each — the dtype is the
    # variable, the (c, S) sweep above already covers the schedules.
    smb = SellMultiLevel(levels, width,
                         make_mesh((n_dev,), ("blocks",), devices=devs),
                         routing="a2a", feature_dtype="bf16")
    xsb = smb.set_features(random_dense(smb.n, k, seed=5))
    args = (xsb,) + smb.step_operands()
    yield ("sell_multi[c=1,S=1,bf16]", smb.collective_contract(k), {
        "step": (smb._step, args, {}),
        "scan": (smb._scan_donated, args, {"n": 2}),
    })

    yield ("multi_level_a2a[c=1,S=1,bf16]", None,
           "MultiLevelArrow carries feature_dtype on fmt='fold' only; "
           "the mesh approx carriage is SellMultiLevel's "
           "(feature-major, the executor graft-tune promotes)")

    mfi = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                          feature_dtype="int8")
    xfi = mfi.set_features(x_host[:ba.shape[0]])
    args = (xfi,) + mfi.step_operands()
    yield ("multi_level_fold[c=1,S=1,int8]", mfi.collective_contract(k), {
        "step": (mfi._step, args, {}),
        "scan": (mfi._scan_steps_donated, args, {"n": 2}),
    })

    # -- graft-synth generated program (H1-H7 over a synthesized ------
    # per-level schedule): the fold executor running the degree-ladder-
    # derived schedule through the fused kernel.  Zero-comm contract —
    # a generated schedule repartitions slabs, it must introduce no new
    # collective kinds and hold the fold's copy discipline: the
    # contract budget grows by one declared 8-copy loop-state set per
    # scheduled tier (scalar/index-sized carried state of each tier's
    # streaming loop under interpret lowering), and H6 still forbids
    # any (rows, k) slab-sized copy or transpose in the hot loop.
    from arrow_matrix_tpu.tune.fingerprint import structure_fingerprint
    from arrow_matrix_tpu.tune.synth import synthesize_schedule

    sched = synthesize_schedule(
        structure_fingerprint(levels, width, np.float32))
    if sched:
        mfs = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                              kernel="pallas_sell",
                              kernel_opts={"interpret": True,
                                           "schedule": sched})
        xfs = mfs.set_features(x_host[:ba.shape[0]])
        args = (xfs,) + mfs.step_operands()
        yield ("multi_level_fold[c=1,S=1,synth]",
               mfs.collective_contract(k), {
                   "step": (mfs._step, args, {}),
                   "scan": (mfs._scan_steps_donated, args, {"n": 2}),
               })
    else:
        yield ("multi_level_fold[c=1,S=1,synth]", None,
               "the prove-scale structure synthesized an empty "
               "schedule (no non-zero ladder tiers)")

    # -- graft-reshard staged redistribution (H7) ----------------------
    # Two (src, dst) layout pairs, including a repl c change: the plan
    # compiler's bounded-scratch promise, proved from each stage's
    # lowered all-to-all buffers.  The one-shot route is the entry's
    # "step" (H1/H2 price its full exchange); the staged sub-routes are
    # the "stages" H7 audits against the declared budget.
    from jax.sharding import NamedSharding, PartitionSpec

    from arrow_matrix_tpu.parallel import routing as routing_mod
    from arrow_matrix_tpu.parallel.mesh import put_global
    from arrow_matrix_tpu.parallel.reshard import (
        Layout,
        plan_route_table,
        redistribution_plan,
    )

    reshard_budget = 2048
    rng = np.random.default_rng(13)
    pairs = [
        ("reshard[shuffle,d4]",
         Layout(n, n_dev=n_dev, tag="prove_src"),
         Layout(n, n_dev=n_dev, tag="prove_dst"),
         rng.permutation(n).astype(np.int64)),
        ("reshard[repl1to2,d4]",
         Layout(n, n_dev=n_dev, repl=1, tag="prove_src"),
         Layout(n, n_dev=n_dev, repl=2, tag="prove_dst"),
         None),
    ]
    mesh_r = make_mesh((n_dev,), ("blocks",), devices=devs)
    x_r = put_global(x_host.astype(np.float32),
                     NamedSharding(mesh_r, PartitionSpec("blocks")))

    def _route_fn(rt):
        return jax.jit(lambda xx: routing_mod.routed_take(
            xx, rt, mesh_r, "blocks"))

    for rname, src_lay, dst_lay, perm in pairs:
        plan = redistribution_plan(src_lay, dst_lay, reshard_budget,
                                   k=k, perm_map=perm)
        tbl, mask = plan_route_table(plan)
        route = routing_mod.build_route(
            tbl, n_dev, src_total=src_lay.stored_rows, pad_mask=mask)
        sroute = routing_mod.split_route_stages(route, k,
                                                reshard_budget)
        contract = CollectiveContract(
            algorithm=rname,
            step_bytes=route.device_bytes_per_exchange(k, 4),
            reduce_bytes=0, repl=1, overlap_slabs=1, dtype="f32",
            lowered_kinds=("all-to-all",),
            compiled_kinds=("all-to-all",),
            ratio_band=(0.99, 1.01),
            scratch_budget_bytes=reshard_budget,
            h3_exempt="redistribution carries full-k rows, not "
                      "replica slabs",
            notes=f"staged (src={src_lay.total_rows}x{src_lay.repl}"
                  f"c -> dst={dst_lay.total_rows}x{dst_lay.repl}c on "
                  f"{n_dev} devices): plan {plan.n_stages} host "
                  f"stage(s), route {sroute.n_stages} device "
                  f"stage(s)")
        yield (rname, contract, {
            "step": (_route_fn(routing_mod.shard_route(
                route, mesh_r, "blocks")), (x_r,), {}),
            "stages": [
                (_route_fn(routing_mod.shard_route(st, mesh_r,
                                                   "blocks")),
                 (x_r,), {})
                for st in sroute.stages],
        })


def _auto_bytes(lowered: CollectiveSummary,
                compiled: CollectiveSummary) -> Tuple[int, str]:
    """The obs/comm "auto" account: the lowered (explicit-collective)
    bytes when any exist, else the compiled (partitioner) bytes."""
    if lowered.total_bytes > 0:
        return lowered.total_bytes, "lowered"
    if compiled.total_bytes > 0:
        return compiled.total_bytes, "compiled"
    return 0, "lowered"


def prove_entry(name: str, contract: CollectiveContract,
                programs: dict, k: int) -> dict:
    """Lower + compile one entry's programs and run H1-H6."""
    step_fn, step_args, step_kwargs = programs["step"]
    step_lowered = step_fn.lower(*step_args, **step_kwargs)
    lowered = summarize_hlo(step_lowered.as_text(dialect="hlo"))
    compiled = summarize_hlo(step_lowered.compile().as_text())

    merge_bytes = None
    if "merge" in programs:
        m_fn, m_args, m_kwargs = programs["merge"]
        m_low = m_fn.lower(*m_args, **m_kwargs)
        m_lowered = summarize_hlo(m_low.as_text(dialect="hlo"))
        m_compiled = summarize_hlo(m_low.compile().as_text())
        merge_bytes, _ = _auto_bytes(m_lowered, m_compiled)

    donor_attrs = False
    scan_compiled = None
    hot = compiled
    if "scan" in programs:
        s_fn, s_args, s_kwargs = programs["scan"]
        s_low = s_fn.lower(*s_args, **s_kwargs)
        stable = s_low.as_text()
        donor_attrs = ("jax.buffer_donor" in stable
                       or "tf.aliasing_output" in stable)
        scan_compiled = summarize_hlo(s_low.compile().as_text())
        hot = scan_compiled

    stage_summaries = None
    if "stages" in programs:
        stage_summaries = []
        for g_fn, g_args, g_kwargs in programs["stages"]:
            g_low = g_fn.lower(*g_args, **g_kwargs)
            stage_summaries.append(
                summarize_hlo(g_low.as_text(dialect="hlo")))

    measured, source = _auto_bytes(lowered, compiled)
    rules = {
        "H1": check_h1(lowered, compiled, contract),
        "H2": check_h2(measured, source, contract),
        "H3": check_h3(lowered, contract, k, merge_bytes=merge_bytes),
        "H4": check_h4(lowered, contract),
        "H5": check_h5(donor_attrs, scan_compiled, contract),
        "H6": check_h6(hot, contract),
        "H7": check_h7(stage_summaries, contract),
    }
    return {
        "entry": name,
        "contract": contract.to_json(),
        "measured": {
            "auto_bytes": measured,
            "source": source,
            "lowered_bytes": lowered.total_bytes,
            "compiled_bytes": compiled.total_bytes,
            "lowered_kinds": {kd: v for kd, v in lowered.kinds.items()
                              if v["count"]},
            "compiled_kinds": {kd: v for kd, v in compiled.kinds.items()
                               if v["count"]},
            "merge_bytes": merge_bytes,
            "hot_loop_copies": hot.while_copies,
            "hot_loop_transposes": hot.while_transposes,
            "aliased_params": (list(scan_compiled.aliased_params)
                               if scan_compiled is not None else None),
            "stage_scratch_bytes": (
                [2 * s.total_bytes for s in stage_summaries]
                if stage_summaries is not None else None),
        },
        "rules": rules,
        "ok": all(r["status"] in ("pass", "skip")
                  for r in rules.values()),
    }


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------

#: Keys the drift comparison ignores (environment, not behavior).
VOLATILE_KEYS = ("timestamp", "jax_version", "platform", "generated_by")


def manifest_digest(manifest: dict) -> dict:
    """The behavior-only view of a manifest the drift gate compares:
    entry names, per-rule statuses, measured byte accounts, and the
    skip ledger — everything except the volatile environment keys."""
    return {
        "scale": manifest.get("scale"),
        "entries": {
            e["entry"]: {
                "ok": e["ok"],
                "rules": {r: v["status"]
                          for r, v in e["rules"].items()},
                "auto_bytes": e["measured"]["auto_bytes"],
                "merge_bytes": e["measured"]["merge_bytes"],
            }
            for e in manifest.get("entries", ())
        },
        "skipped": {s["entry"]: s["reason"]
                    for s in manifest.get("skipped", ())},
        "ok": manifest.get("ok"),
    }


def manifest_drift(old: dict, new: dict) -> List[str]:
    """Human-readable differences between two manifests' digests
    (empty = no drift)."""
    a, b = manifest_digest(old), manifest_digest(new)
    problems: List[str] = []
    if a["scale"] != b["scale"]:
        problems.append(f"scale changed: {a['scale']} -> {b['scale']}")
    for name in sorted(set(a["entries"]) | set(b["entries"])):
        if name not in b["entries"]:
            problems.append(f"entry disappeared: {name}")
        elif name not in a["entries"]:
            problems.append(f"new unrecorded entry: {name}")
        elif a["entries"][name] != b["entries"][name]:
            problems.append(
                f"entry changed: {name}: {a['entries'][name]} -> "
                f"{b['entries'][name]}")
    for name in sorted(set(a["skipped"]) | set(b["skipped"])):
        if a["skipped"].get(name) != b["skipped"].get(name):
            problems.append(f"skip ledger changed for {name}")
    if a["ok"] != b["ok"]:
        problems.append(f"overall ok changed: {a['ok']} -> {b['ok']}")
    return problems


def run_prove(out_path: str = DEFAULT_MANIFEST,
              n: int = PROVE_SCALE["n"], width: int = PROVE_SCALE["width"],
              k: int = PROVE_SCALE["k"], n_dev: int = PROVE_SCALE["n_dev"],
              write: bool = True) -> dict:
    """Prove every contracted entry point; return (and write) the
    manifest.  Requires an initialized multi-device jax (the CLI path
    forces a virtual CPU pool first; under pytest the conftest pool is
    reused)."""
    import datetime

    import jax

    entries: List[dict] = []
    skipped: List[dict] = []
    for name, contract, programs in _entries(n, width, k, n_dev):
        if contract is None:
            skipped.append({"entry": name, "reason": programs})
            continue
        entries.append(prove_entry(name, contract, programs, k))
    manifest = {
        "generated_by": "python -m arrow_matrix_tpu.analysis prove",
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "jax_version": jax.__version__,
        "platform": jax.devices()[0].platform,
        "n_devices": n_dev,
        "scale": {"n": n, "width": width, "k": k},
        "entries": entries,
        "skipped": skipped,
        "ok": all(e["ok"] for e in entries),
    }
    if write:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    return manifest


def _format_entry(e: dict) -> str:
    mark = "ok  " if e["ok"] else "FAIL"
    verdicts = " ".join(
        f"{r}:{e['rules'][r]['status']}" for r in RULE_IDS)
    line = (f"[{mark}] {e['entry']}: {e['measured']['auto_bytes']} B "
            f"({e['measured']['source']}) vs ideal "
            f"{e['contract']['step_bytes']} B | {verdicts}")
    for r in RULE_IDS:
        if e["rules"][r]["status"] == "fail":
            line += f"\n       {r}: {e['rules'][r]['detail']}"
    return line


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="graft_prove", description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_MANIFEST)
    ap.add_argument("--devices", type=int, default=PROVE_SCALE["n_dev"],
                    help="virtual CPU devices (forced before jax init)")
    ap.add_argument("--n", type=int, default=PROVE_SCALE["n"])
    ap.add_argument("--width", type=int, default=PROVE_SCALE["width"])
    ap.add_argument("--k", type=int, default=PROVE_SCALE["k"])
    ap.add_argument("--check", action="store_true",
                    help="do not write; fail on any violation OR drift "
                         "against the checked-in manifest")
    args = ap.parse_args(argv)

    # The prover is a CPU-trace exercise by contract: force the virtual
    # pool BEFORE the first backend touch (a tunneled TPU would both
    # wedge and prove the wrong partitioning).
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(args.devices)

    manifest = run_prove(out_path=args.out, n=args.n, width=args.width,
                         k=args.k, n_dev=args.devices,
                         write=not args.check)
    for e in manifest["entries"]:
        print(_format_entry(e))
    for s in manifest["skipped"]:
        print(f"[skip] {s['entry']}: {s['reason']}")

    rc = 0 if manifest["ok"] else 1
    if args.check:
        try:
            with open(args.out, encoding="utf-8") as fh:
                checked_in = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"no readable checked-in manifest at {args.out}: {e}")
            return 1
        drift = manifest_drift(checked_in, manifest)
        for d in drift:
            print(f"drift: {d}")
        if drift:
            print(f"proof drift against {args.out} — rerun "
                  f"`python -m arrow_matrix_tpu.analysis prove` and "
                  f"commit the refreshed manifest")
            rc = 1
    else:
        print(f"manifest: {args.out}")
    print("proof passed" if rc == 0 else "PROOF FAILED")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
