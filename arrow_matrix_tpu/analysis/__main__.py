"""graft-lint CLI.

Usage:
  python -m arrow_matrix_tpu.analysis <paths...>      lint (default)
  python -m arrow_matrix_tpu.analysis lint <paths...> lint, explicitly
  python -m arrow_matrix_tpu.analysis audit           trace-time audit
  python -m arrow_matrix_tpu.analysis prove           HLO contract proof
  python -m arrow_matrix_tpu.analysis sync            lock-discipline proof
  python -m arrow_matrix_tpu.analysis kernels         Pallas kernel certifier
  python -m arrow_matrix_tpu.analysis --list-rules    rule table

Exit status: 0 when no (unwaived) findings, 1 otherwise — the CI gate
contract (tools/lint_gate.py).  ``--json`` emits machine-readable
findings; waivers are ``# graft-lint: disable=R1`` inline comments.
"""

from __future__ import annotations

import argparse
import os
import sys

from arrow_matrix_tpu.analysis.core import (
    findings_to_json,
    lint_paths,
    rule_table,
)


def _package_dir() -> str:
    import arrow_matrix_tpu

    return os.path.dirname(os.path.abspath(arrow_matrix_tpu.__file__))


def _print_rules() -> None:
    for spec in rule_table():
        print(f"{spec.rule_id}  {spec.name:<24} {spec.summary}")


def run_lint(paths, select=None, as_json=False, quiet=False) -> int:
    findings, waived = lint_paths(paths, select=select)
    if as_json:
        print(findings_to_json(findings, waived))
    else:
        for f in findings:
            print(f.format())
        if not quiet:
            print(f"graft-lint: {len(findings)} finding(s), "
                  f"{len(waived)} waived, "
                  f"{len(rule_table())} rules", file=sys.stderr)
    return 1 if findings else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "audit":
        from arrow_matrix_tpu.analysis.audit import main as audit_main

        return audit_main(argv[1:])
    if argv and argv[0] == "prove":
        from arrow_matrix_tpu.analysis.prove import main as prove_main

        return prove_main(argv[1:])
    if argv and argv[0] == "sync":
        from arrow_matrix_tpu.analysis.sync import main as sync_main

        return sync_main(argv[1:])
    if argv and argv[0] == "kernels":
        from arrow_matrix_tpu.analysis.kernels import main as kcert_main

        return kcert_main(argv[1:])
    if argv and argv[0] == "lint":
        argv = argv[1:]

    ap = argparse.ArgumentParser(
        prog="graft_lint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "installed arrow_matrix_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON findings on stdout")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the summary line")
    args = ap.parse_args(argv)

    if args.list_rules:
        _print_rules()
        return 0
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    paths = args.paths or [_package_dir()]
    return run_lint(paths, select=select, as_json=args.json,
                    quiet=args.quiet)


def gate(argv=None) -> int:
    """Console entry point for CI (``graft_lint`` script / the tier-1
    lint gate): lint the installed package, exit non-zero on findings."""
    return main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
