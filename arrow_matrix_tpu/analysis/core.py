"""graft-lint framework: registry, module context, waivers, walking.

The linter is purely syntactic (``ast``): it never imports the modules
it checks, so it is safe on broken trees, costs milliseconds per file,
and cannot wedge on accelerator init — the property that lets it run
inside tier-1 and inside ``amt_doctor`` unconditionally.

Scope contract: traced-scope rules (R1, R5) apply inside functions this
module can PROVE are traced — jit/shard_map/vmap/scan call sites and
decorators within the same module, closed transitively over
module-local calls and nested defs.  Cross-module tracing (a function
jitted by its importer) is out of scope by design; the trace-time audit
engine (analysis/audit.py) covers the composed entry points instead.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Findings and registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}


@dataclasses.dataclass(frozen=True)
class RuleSpec:
    rule_id: str
    name: str
    summary: str
    check: Callable  # (ModuleContext) -> Iterable[tuple[int, str]]


#: rule_id -> RuleSpec, populated by the ``register`` decorator.
RULES: dict = {}


def register(rule_id: str, name: str, summary: str):
    """Class/function decorator adding a checker to the registry.

    A checker is ``check(ctx: ModuleContext) -> Iterable[(line, msg)]``.
    """
    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id}")
        RULES[rule_id] = RuleSpec(rule_id, name, summary, fn)
        return fn
    return deco


def rule_table() -> List[RuleSpec]:
    _ensure_rules_loaded()
    return [RULES[k] for k in sorted(RULES)]


def _ensure_rules_loaded() -> None:
    # The rules module registers itself on import; core must not import
    # it at module level (rules imports core for the registry).
    if not RULES:
        import arrow_matrix_tpu.analysis.rules  # noqa: F401


# ---------------------------------------------------------------------------
# Waivers
# ---------------------------------------------------------------------------

#: Inline waiver on the finding's line: ``# graft-lint: disable=R1,R6``
#: (no ``=RULES`` suffix disables every rule for that line).
WAIVER_RE = re.compile(
    r"#\s*graft-lint:\s*disable(?:-file)?(?:=(?P<rules>[A-Za-z0-9, ]+))?")
FILE_WAIVER_RE = re.compile(
    r"#\s*graft-lint:\s*disable-file(?:=(?P<rules>[A-Za-z0-9, ]+))?")


def _parse_rule_list(m) -> frozenset:
    spec = m.group("rules")
    if spec is None:
        return frozenset()          # empty set == every rule
    return frozenset(r.strip() for r in spec.split(",") if r.strip())


def parse_waivers(source: str) -> Tuple[dict, frozenset]:
    """(line -> waived rule-ids, file-level waived rule-ids).

    An empty rule set means "all rules" (bare ``disable``).
    """
    per_line: dict = {}
    file_level: frozenset = None
    for i, text in enumerate(source.splitlines(), start=1):
        if "graft-lint" not in text:
            continue
        fm = FILE_WAIVER_RE.search(text)
        if fm:
            rules = _parse_rule_list(fm)
            file_level = (rules if file_level is None
                          else file_level | rules)
            continue
        m = WAIVER_RE.search(text)
        if m:
            per_line[i] = _parse_rule_list(m)
    return per_line, (file_level if file_level is not None else None)


def _waived(f: Finding, per_line: dict, file_level) -> bool:
    if file_level is not None and (not file_level or f.rule in file_level):
        return True
    rules = per_line.get(f.line)
    if rules is None:
        return False
    return not rules or f.rule in rules


# ---------------------------------------------------------------------------
# Module context (shared pre-analysis the rules build on)
# ---------------------------------------------------------------------------

#: Wrappers whose function-valued arguments execute under a JAX trace.
TRACE_WRAPPERS = frozenset({
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.eval_shape", "jax.make_jaxpr",
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop",
    "jax.lax.cond", "jax.lax.map", "jax.lax.switch",
})

#: The subset that is a jit cache (compilation) boundary.
JIT_WRAPPERS = frozenset({
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
})


class ModuleContext:
    """Parsed module plus the shared analyses every rule consumes."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.parents: dict = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.aliases = self._collect_aliases()
        self.funcs_by_name = self._collect_functions()
        self.traced = self._compute_traced()

    # -- imports / name resolution --------------------------------------

    def _collect_aliases(self) -> dict:
        """local name -> canonical dotted module/object path."""
        aliases: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def dotted(self, node) -> Optional[str]:
        """Source-level dotted name of a Name/Attribute chain."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def resolve(self, node) -> Optional[str]:
        """Canonical dotted name with import aliases substituted
        (``np.asarray`` -> ``numpy.asarray``, bare ``jit`` ->
        ``jax.jit``) and the jnp/lax shorthands normalized."""
        d = self.dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        full = self.aliases.get(head, head) + (("." + rest) if rest else "")
        for src, dst in (("jax.experimental.shard_map.shard_map",
                          "jax.shard_map"),
                         ("jax.experimental.pjit.pjit", "jax.pjit"),
                         ("jax.ad_checkpoint.checkpoint", "jax.checkpoint")):
            if full == src:
                full = dst
        return full

    def is_numpy_call(self, call: ast.Call, attr: str) -> bool:
        """Is ``call`` ``numpy.<attr>(...)`` under any alias?  jax.numpy
        aliases resolve to ``jax.numpy.*`` and never match."""
        return self.resolve(call.func) == f"numpy.{attr}"

    # -- functions and traced scopes ------------------------------------

    def _collect_functions(self) -> dict:
        funcs: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                             ast.Lambda):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        funcs.setdefault(t.id, []).append(node.value)
        return funcs

    def _callable_args(self, call: ast.Call) -> list:
        """Function-valued argument nodes of a trace-wrapper call:
        lambdas, local function names, and functools.partial wraps."""
        out = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Lambda):
                out.append(arg)
            elif isinstance(arg, ast.Name) and arg.id in self.funcs_by_name:
                out.append(arg)
            elif (isinstance(arg, ast.Call)
                  and self.resolve(arg.func) == "functools.partial"
                  and arg.args):
                inner = arg.args[0]
                if isinstance(inner, (ast.Lambda, ast.Name)):
                    out.append(inner)
        return out

    def _compute_traced(self) -> set:
        """Fixpoint set of function/lambda nodes that run under trace."""
        traced: set = set()
        pending_names: set = set()

        def mark(node):
            if isinstance(node, ast.Name):
                pending_names.add(node.id)
            elif node is not None:
                traced.add(node)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                full = self.resolve(node.func)
                if full in TRACE_WRAPPERS:
                    for fn in self._callable_args(node):
                        mark(fn)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    full = self.resolve(target)
                    if full in TRACE_WRAPPERS:
                        traced.add(node)
                    elif (isinstance(deco, ast.Call)
                          and full == "functools.partial" and deco.args
                          and self.resolve(deco.args[0]) in TRACE_WRAPPERS):
                        traced.add(node)

        # Close over (a) names marked at wrapper call sites, (b) nested
        # defs inside traced bodies, (c) module-local calls from traced
        # bodies — everything a trace reaches within this module.
        changed = True
        while changed:
            changed = False
            for name in list(pending_names):
                for fn in self.funcs_by_name.get(name, ()):
                    if fn not in traced:
                        traced.add(fn)
                        changed = True
            pending_names.clear()
            for fn in list(traced):
                for sub in ast.walk(fn):
                    if sub is fn:
                        continue
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                        if sub not in traced:
                            traced.add(sub)
                            changed = True
                    elif (isinstance(sub, ast.Call)
                          and isinstance(sub.func, ast.Name)
                          and sub.func.id in self.funcs_by_name):
                        for g in self.funcs_by_name[sub.func.id]:
                            if g not in traced:
                                traced.add(g)
                                changed = True
        return traced

    def enclosing_function(self, node):
        """Nearest enclosing FunctionDef/Lambda, or None at module level."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def in_traced_scope(self, node) -> bool:
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced:
                return True
            fn = self.enclosing_function(fn)
        return False

    def in_loop(self, node) -> bool:
        """Inside a Python for/while body (within the same function)."""
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                      ast.Module)):
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                return True
            cur = self.parents.get(cur)
        return False


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None
                ) -> Tuple[List[Finding], List[Finding]]:
    """Lint one source string -> (findings, waived findings)."""
    _ensure_rules_loaded()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        f = Finding(path, e.lineno or 1, "E0",
                    f"syntax error: {e.msg}")
        return [f], []
    ctx = ModuleContext(path, source, tree)
    rules = [RULES[r] for r in (select or sorted(RULES))]
    raw: List[Finding] = []
    for spec in rules:
        for line, msg in spec.check(ctx):
            raw.append(Finding(path, line, spec.rule_id, msg))
    per_line, file_level = parse_waivers(source)
    findings = [f for f in raw if not _waived(f, per_line, file_level)]
    waived = [f for f in raw if _waived(f, per_line, file_level)]
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings, waived


def lint_file(path: str, select: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], List[Finding]]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path, select=select)


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_paths(paths: Iterable[str],
               select: Optional[Sequence[str]] = None
               ) -> Tuple[List[Finding], List[Finding]]:
    """Lint files/directories -> (findings, waived), both sorted."""
    findings: List[Finding] = []
    waived: List[Finding] = []
    for f in iter_python_files(paths):
        got, w = lint_file(f, select=select)
        findings.extend(got)
        waived.extend(w)
    return findings, waived


def findings_to_json(findings: Sequence[Finding],
                     waived: Sequence[Finding]) -> str:
    return json.dumps(
        {"findings": [f.to_json() for f in findings],
         "waived": [f.to_json() for f in waived],
         "count": len(findings)},
        indent=2, sort_keys=True)
