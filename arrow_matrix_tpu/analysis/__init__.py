"""graft-lint: static analysis for the JAX/TPU hot paths.

Two complementary engines guard the invariants the benches depend on
(PERFORMANCE.md measurement discipline):

* **AST pass** (`core` + `rules`): a visitor-based linter over the
  package source with an extensible rule registry.  The shipped rules
  (R1-R7) encode the recompilation, host-sync, and sharding hazards
  that silently destroy TPU throughput — the class of bug an MPI code
  never meets but a jit/shard_map code re-discovers one bench
  regression at a time.
* **Trace-time audit** (`audit`): jit-compiles the core SpMM entry
  points on the host CPU mesh and asserts zero recompiles across two
  same-shape calls, recording a compile-count manifest under
  ``bench_cache/`` so compile-cache regressions diff in review.

Run ``python -m arrow_matrix_tpu.analysis <paths>`` to lint and
``python -m arrow_matrix_tpu.analysis audit`` for the trace audit;
``graft_lint`` is the installed console script (tools/lint_gate.py is
the CI wrapper).  Findings are suppressed inline with
``# graft-lint: disable=R1`` (see core.WAIVER_RE).
"""

from arrow_matrix_tpu.analysis.core import (
    Finding,
    lint_file,
    lint_paths,
    lint_source,
    rule_table,
)

__all__ = [
    "Finding",
    "lint_file",
    "lint_paths",
    "lint_source",
    "rule_table",
]
