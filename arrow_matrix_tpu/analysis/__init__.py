"""graft-lint + graft-prove + graft-sync + graft-kcert: static
analysis for the JAX/TPU hot paths, the serving stack's concurrency
discipline, and the Pallas kernel layer.

Five complementary engines guard the invariants the benches depend on
(PERFORMANCE.md measurement discipline):

* **AST pass** (`core` + `rules`): a visitor-based linter over the
  package source with an extensible rule registry.  The shipped rules
  (R1-R9, enumerated in rules.py) encode the recompilation, host-sync,
  sharding, and hot-loop-env hazards that silently destroy TPU
  throughput — the class of bug an MPI code never meets but a
  jit/shard_map code re-discovers one bench regression at a time.
* **Trace-time audit** (`audit`): jit-compiles the core SpMM entry
  points on the host CPU mesh and asserts zero recompiles across two
  same-shape calls, recording a compile-count manifest under
  ``bench_cache/`` so compile-cache regressions diff in review.
* **HLO contract prover** (`prove` + `contracts`): lowers every
  distributed executor on a virtual mesh, parses the optimized HLO,
  and checks seven static rules (H1-H7) against the executor's declared
  ``collective_contract`` — no unattributed collectives, bytes within
  tolerance of the ideal model, the repl=c ÷c slab law plus exactly
  the priced psum merge, no silent dtype upcasts, donated buffers
  actually aliased, no layout thrash in the hot loop.  Verdicts land
  in the checked-in ``bench_cache/hlo_manifest.json``.
* **Lock-discipline analyzer** (`sync`, graft-sync): reads the
  runtime ``@guarded_by`` contracts (arrow_matrix_tpu/sync.py) off
  the AST and proves five concurrency rules over the serving stack —
  RC1 guarded attributes are mutated only under their declared lock,
  RC2 the lock-acquisition graph (including flock file-lock sites) is
  acyclic against the declared partial order, RC3 no user callback
  runs under a lock, RC4 no blocking call (socket accept/recv,
  subprocess wait, untimed ``Event.wait``) runs under a lock, RC5
  mutable module state reachable from two thread entry points is
  guarded.  Verdicts land in the checked-in
  ``bench_cache/sync_manifest.json`` (the hlo_manifest drift
  discipline), and the same contracts arm the runtime lock-order
  witness under ``AMT_LOCK_WITNESS=1``.
* **Pallas kernel certifier** (`kernels`, graft-kcert): proves five
  rules (KC1-KC5) over every kernel builder's declared
  ``KernelContract`` and its concretized call metas at representative
  (row_block, ring, k) points — KC1 every SMEM/VMEM/HBM index in
  bounds, KC2 VMEM blocks + scratch and SMEM prefetch inside their
  budgets, KC3 DMA ring discipline (waited before slot reuse, no
  in-flight aliasing, replayed in a ring simulator), KC4 the
  accumulator >= f32 regardless of carriage dtype (H4' at the kernel
  level), KC5 the output index map covers every output block exactly
  once.  Verdicts land in the checked-in
  ``bench_cache/kernel_manifest.json``, tune/space prunes
  uncertifiable candidates through ``certify_candidate_opts``, and
  generated programs (ROADMAP item 3) enter via
  ``ops/kernel_contract.register_kernel``.

Together R1-R9 (lint), H1-H7 (prove), RC1-RC5 (sync), and KC1-KC5
(kcert) are one rule family: every id is unique, every verdict is
drift-gated, and every engine exits non-zero on an unwaived finding.

Run ``python -m arrow_matrix_tpu.analysis <paths>`` to lint,
``python -m arrow_matrix_tpu.analysis audit`` for the trace audit,
``python -m arrow_matrix_tpu.analysis prove`` for the HLO proof,
``python -m arrow_matrix_tpu.analysis sync`` for the lock proof, and
``python -m arrow_matrix_tpu.analysis kernels`` for the kernel
certification; ``graft_lint`` / ``graft_prove`` / ``graft_sync`` /
``graft_kcert`` are the installed console scripts (tools/lint_gate.py,
tools/proof_gate.py, tools/sync_gate.py, and tools/kernel_gate.py are
the CI wrappers).  Findings are suppressed inline with
``# graft-lint: disable=R1`` (core.WAIVER_RE) and
``# graft-sync: disable=RC1`` (sync waivers).
"""

from arrow_matrix_tpu.analysis.contracts import CollectiveContract
from arrow_matrix_tpu.analysis.core import (
    Finding,
    lint_file,
    lint_paths,
    lint_source,
    rule_table,
)

__all__ = [
    "CollectiveContract",
    "Finding",
    "lint_file",
    "lint_paths",
    "lint_source",
    "rule_table",
]
