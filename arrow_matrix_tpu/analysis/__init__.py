"""graft-lint + graft-prove: static analysis for the JAX/TPU hot paths.

Three complementary engines guard the invariants the benches depend on
(PERFORMANCE.md measurement discipline):

* **AST pass** (`core` + `rules`): a visitor-based linter over the
  package source with an extensible rule registry.  The shipped rules
  (R1-R9, enumerated in rules.py) encode the recompilation, host-sync,
  sharding, and hot-loop-env hazards that silently destroy TPU
  throughput — the class of bug an MPI code never meets but a
  jit/shard_map code re-discovers one bench regression at a time.
* **Trace-time audit** (`audit`): jit-compiles the core SpMM entry
  points on the host CPU mesh and asserts zero recompiles across two
  same-shape calls, recording a compile-count manifest under
  ``bench_cache/`` so compile-cache regressions diff in review.
* **HLO contract prover** (`prove` + `contracts`): lowers every
  distributed executor on a virtual mesh, parses the optimized HLO,
  and checks six static rules (H1-H6) against the executor's declared
  ``collective_contract`` — no unattributed collectives, bytes within
  tolerance of the ideal model, the repl=c ÷c slab law plus exactly
  the priced psum merge, no silent dtype upcasts, donated buffers
  actually aliased, no layout thrash in the hot loop.  Verdicts land
  in the checked-in ``bench_cache/hlo_manifest.json``.

Run ``python -m arrow_matrix_tpu.analysis <paths>`` to lint,
``python -m arrow_matrix_tpu.analysis audit`` for the trace audit, and
``python -m arrow_matrix_tpu.analysis prove`` for the HLO proof;
``graft_lint`` / ``graft_prove`` are the installed console scripts
(tools/lint_gate.py and tools/proof_gate.py are the CI wrappers).
Findings are suppressed inline with ``# graft-lint: disable=R1``
(see core.WAIVER_RE).
"""

from arrow_matrix_tpu.analysis.contracts import CollectiveContract
from arrow_matrix_tpu.analysis.core import (
    Finding,
    lint_file,
    lint_paths,
    lint_source,
    rule_table,
)

__all__ = [
    "CollectiveContract",
    "Finding",
    "lint_file",
    "lint_paths",
    "lint_source",
    "rule_table",
]
