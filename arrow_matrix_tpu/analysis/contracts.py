"""Collective contracts: what each distributed executor PROMISES its
compiled program looks like (graft-prove engine 3, the static twin of
obs/comm's measured/ideal ratio).

A ``CollectiveContract`` is exported by every parallel executor
(``collective_contract(k)``) and declares, for one step at feature
width ``k``:

* which collective op kinds the lowered (explicit shard_map) and
  compiled (post-GSPMD) HLO may legitimately contain — anything else
  is a partitioner surprise (H1);
* the ideal per-step exchange bytes (``ideal_comm_bytes``, already
  divided by the 2.5D replication factor c) and the accepted
  measured/ideal ratio band — the HLO accountant counts per-device
  output shapes while the paper model counts logical row traffic, so
  each executor carries its own empirically-grounded tolerance (H2);
* the replication factor, overlap slab count, and the priced psum
  merge bytes (``reduce_comm_bytes``) the ÷c law is checked against
  (H3);
* the carried feature dtype (H4) and the flat HLO parameter numbers
  a donated entry point must alias (H5);
* the hot-loop copy budget XLA's while-loop copy insertion is allowed
  (H6 — transposes are never allowed).

The contract is a plain frozen value: analysis/prove.py consumes it,
and ``to_json`` makes it diffable inside bench_cache/hlo_manifest.json.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class CollectiveContract:
    """One executor's static communication promise at feature width k."""

    algorithm: str
    #: Ideal per-step exchange bytes (paper cost model; already ÷c).
    step_bytes: int
    #: Once-per-gather 2.5D psum merge bytes (0 when repl == 1).
    reduce_bytes: int
    #: 2.5D replication factor c.
    repl: int
    #: Overlap schedule slab count S (each collective carries k/(c·S)).
    overlap_slabs: int
    #: Carried feature dtype short name ("f32", "bf16", ...).
    dtype: str
    #: Collective kinds the LOWERED (pre-partitioning) step may contain.
    lowered_kinds: Tuple[str, ...]
    #: Collective kinds the COMPILED (post-GSPMD) step may contain.
    compiled_kinds: Tuple[str, ...]
    #: Accepted measured/ideal byte ratio (lo, hi) for H2.
    ratio_band: Tuple[float, float]
    #: Flat HLO parameter numbers the donated entry point must alias
    #: (empty = the executor ships no donated entry point; H5 skips).
    donated_params: Tuple[int, ...] = ()
    #: While-body copies tolerated in the hot loop (XLA's loop copy
    #: insertion is benign up to this; transposes are never allowed).
    hot_copy_budget: int = 8
    #: Non-empty exempts H3 with this rationale (e.g. 1.5D replication
    #: reduces broadcast rounds instead of slab width).
    h3_exempt: str = ""
    #: graft-reshard: declared per-device per-stage send+recv scratch
    #: ceiling for a staged exchange (0 = not a staged program; H7
    #: skips).
    scratch_budget_bytes: int = 0
    #: Free-text pricing notes surfaced in the manifest.
    notes: str = ""

    def __post_init__(self):
        if self.repl < 1:
            raise ValueError(f"repl must be >= 1, got {self.repl}")
        if self.overlap_slabs < 1:
            raise ValueError(
                f"overlap_slabs must be >= 1, got {self.overlap_slabs}")
        lo, hi = self.ratio_band
        if not (0 <= lo <= hi):
            raise ValueError(f"ratio_band must be 0 <= lo <= hi, "
                             f"got {self.ratio_band}")
        if self.step_bytes < 0 or self.reduce_bytes < 0 \
                or self.scratch_budget_bytes < 0:
            raise ValueError("byte counts must be non-negative")

    def expected_slab(self, k: int) -> int:
        """Leading feature dimension every collective in the lowered
        step must carry: the k/(c·S) slab of the 2.5D + overlap
        schedule (the statically-visible form of the ÷c law)."""
        return k // self.repl // self.overlap_slabs

    def inter_host_bytes(self, num_hosts: int, num_devices: int,
                         pattern: str = "ring") -> int:
        """The slice of ``step_bytes`` that crosses a host boundary
        when the global device axis is split into ``num_hosts``
        contiguous blocks (graft-host fault domains).

        Arrow-matrix exchange is neighbor traffic: under ``"ring"``,
        each device sends one hop along the axis, so exactly the
        ``num_hosts`` block-edge hops (of ``num_devices`` total)
        leave their host — the inter-host fraction is ``hosts /
        devices``.  Under ``"alltoall"`` every device pairs with all
        ``num_devices - 1`` others, of which ``num_devices /
        num_hosts - 1`` share its host, so the fraction is
        ``1 - (d/h - 1)/(d - 1)``.  This is a METHOD, not a field:
        host topology is a deployment property, and keeping it out of
        the dataclass keeps ``to_json`` / the checked-in HLO manifest
        byte-stable across host counts."""
        if num_hosts < 1 or num_devices < 1:
            raise ValueError("num_hosts and num_devices must be >= 1")
        if num_devices % num_hosts != 0:
            raise ValueError(
                f"num_devices ({num_devices}) must split evenly over "
                f"num_hosts ({num_hosts})")
        if num_hosts == 1 or num_devices == 1:
            return 0
        if pattern == "ring":
            frac = num_hosts / num_devices
        elif pattern == "alltoall":
            per_host = num_devices // num_hosts
            frac = 1.0 - (per_host - 1) / (num_devices - 1)
        else:
            raise ValueError(f"unknown pattern {pattern!r} "
                             f"(want 'ring' or 'alltoall')")
        return int(round(self.step_bytes * frac))

    def to_json(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["lowered_kinds"] = sorted(self.lowered_kinds)
        rec["compiled_kinds"] = sorted(self.compiled_kinds)
        rec["ratio_band"] = list(self.ratio_band)
        rec["donated_params"] = list(self.donated_params)
        return rec
