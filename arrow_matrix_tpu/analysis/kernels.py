"""graft-kcert: static certifier for the Pallas kernel layer (KC1-KC5).

The rule families above this layer — R1-R9 (lint), H1-H7 (prove),
RC1-RC5 (sync) — stop at the HLO boundary: nothing checked what the
hand-written Pallas kernels actually do with their grids, DMA rings,
and accumulators.  graft-kcert closes that last tier.  Every Pallas
kernel builder exports a frozen :class:`~arrow_matrix_tpu.ops.
kernel_contract.KernelContract` plus ``kcert_metas()`` — literal
descriptions of its concretized ``pallas_call``\\ s at representative
parameter points, the SAME dicts the builder derives its real
grid/block/scratch numbers from — and this module proves five rules
over them:

* **KC1** every index into SMEM cols / VMEM slabs / the HBM-packed
  feature table is in bounds given the fingerprint invariants
  (exact block tiling, grid-extent x block <= shape, slot-major slab
  arithmetic, granule packing), backed by an interpret-mode boundary
  witness in which every slot points at the LAST feature row;
* **KC2** the sum of double-buffered VMEM BlockSpec blocks plus
  ``scratch_shapes`` fits the declared VMEM budget, and the
  scalar-prefetch bytes fit the SMEM budget, statically per
  (row_block, ring, k) point;
* **KC3** DMA ring discipline — extracted from the builder source by
  AST (the ``copy``/``issue``/``wait`` schedule convention of
  ``ops/pallas_sell.kernel_stream``) and then replayed in a Python
  ring simulator at every certified (ring, wave, n_waves) point:
  every ``pltpu.make_async_copy`` is waited before its semaphore slot
  is reused, reuse distance >= ring depth, sem indices ring-modular,
  no two in-flight copies alias one scratch slab.  A kernel whose
  copies do not match the recognized schedule fails CLOSED;
* **KC4** the accumulation dtype is >= f32 regardless of the carriage
  dtype (H4' at the kernel level), both in the declared meta and in
  the source (no narrow ``jnp.zeros`` accumulator, every ``jnp.dot``
  pinned to ``preferred_element_type=f32``);
* **KC5** the output BlockSpec index map covers every output block
  exactly once across the whole grid — no gap, no overlap — except
  grid axes the contract explicitly declares as revisiting
  (``head_spmm_pallas``'s k-innermost accumulation axis), which must
  revisit uniformly.

Verdicts land in the drift-detected ``bench_cache/
kernel_manifest.json`` (the hlo/sync manifest discipline) and a
``kind="kcert"`` ledger record so ledger_gate drift-checks rule-count
regressions; ``tune/space.py`` calls :func:`certify_candidate_opts`
to prune uncertifiable candidates BEFORE any child process spawns,
and ROADMAP item 3's generated programs enter through
``kernel_contract.register_kernel`` and are certified with zero
changes here.

Usage:
  python -m arrow_matrix_tpu.analysis kernels            certify + write
  python -m arrow_matrix_tpu.analysis kernels --check    certify + drift
  python -m arrow_matrix_tpu.analysis kernels --selftest inline twins
  python -m arrow_matrix_tpu.analysis kernels --fixture F planted fixture
(``graft_kcert`` is the console script; tools/kernel_gate.py the CI
wrapper.)
"""

from __future__ import annotations

import ast
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from arrow_matrix_tpu.ops.kernel_contract import (
    CARRIAGE_ITEMSIZE,
    WIDE_ACCUM_DTYPES,
    KernelEntry,
    registered_kernels,
)

RULE_IDS = ("KC1", "KC2", "KC3", "KC4", "KC5")

RULE_TITLES = {
    "KC1": "every SMEM/VMEM/HBM index in bounds at every grid point",
    "KC2": "VMEM blocks + scratch and SMEM prefetch fit their budgets",
    "KC3": "DMA ring discipline: waited before slot reuse, no aliasing",
    "KC4": "accumulation dtype >= f32 regardless of carriage dtype",
    "KC5": "output index map covers every output block exactly once",
}

DEFAULT_MANIFEST = os.path.join("bench_cache", "kernel_manifest.json")

#: Keys the drift comparison ignores (environment, not behavior).
VOLATILE_KEYS = ("timestamp", "python_version", "platform",
                 "generated_by")

#: KC5 refuses to enumerate grids beyond this many points: a generated
#: program with an absurd grid is a finding, not a hang.
MAX_GRID_POINTS = 1_000_000


class Finding:
    """One rule violation at one (kernel, parameter point)."""

    __slots__ = ("rule", "kernel", "where", "message")

    def __init__(self, rule: str, kernel: str, where: str,
                 message: str):
        self.rule = rule
        self.kernel = kernel
        self.where = where
        self.message = message

    def format(self) -> str:
        return f"{self.kernel}[{self.where}]: {self.rule}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "kernel": self.kernel,
                "where": self.where, "message": self.message}


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def point_label(meta: dict) -> str:
    """Deterministic compact label of one meta (manifest/digest key)."""
    parts = [str(meta.get("kind", "?"))]
    st = meta.get("stream")
    if st:
        parts.append(f"rb{st.get('row_block')}g{st.get('ring')}"
                     f"w{st.get('wave')}")
    grid = meta.get("grid") or []
    parts.append("grid" + ("x".join(str(s) for _a, s in grid) or "0"))
    out = meta.get("out") or {}
    parts.append("out" + "x".join(str(b) for b in out.get("block", ())))
    parts.append(str(meta.get("carriage_dtype", "f32")))
    return "/".join(parts)


# ---------------------------------------------------------------------------
# Meta checks: KC1, KC2, KC4 (declared), KC5
# ---------------------------------------------------------------------------


def check_meta(meta: dict) -> List[Finding]:
    """Prove KC1/KC2/KC4/KC5 arithmetically over one concretized call
    meta (see ``ops/pallas_sell.slab_call_meta`` for the schema)."""
    findings: List[Finding] = []
    kernel = str(meta.get("kernel", "?"))
    where = point_label(meta)

    def fail(rule: str, message: str) -> None:
        findings.append(Finding(rule, kernel, where, message))

    grid = list(meta.get("grid") or [])
    axes: Dict[str, int] = {}
    for axis, size in grid:
        if int(size) < 1:
            fail("KC1", f"grid axis {axis!r} has nonpositive extent "
                        f"{size}")
        axes[str(axis)] = int(size)

    # -- KC4: declared dtypes -------------------------------------------------
    accum = str(meta.get("accum_dtype", "")).lower()
    if accum not in WIDE_ACCUM_DTYPES:
        fail("KC4", f"accumulation dtype {meta.get('accum_dtype')!r} "
                    f"is narrower than f32 (carriage "
                    f"{meta.get('carriage_dtype')!r} may narrow, the "
                    f"accumulator may not)")
    carriage = str(meta.get("carriage_dtype", "f32"))
    if carriage not in CARRIAGE_ITEMSIZE:
        fail("KC4", f"unknown carriage dtype {carriage!r} (contract "
                    f"serves {tuple(CARRIAGE_ITEMSIZE)})")

    # -- KC1: exact tiling + bounds per blocked operand ----------------------
    out = meta.get("out") or {}
    operands = [("out", out)]
    operands += [(str(op.get("name", f"in{i}")), op)
                 for i, op in enumerate(meta.get("ins") or ())
                 if op.get("block") is not None]
    for name, op in operands:
        shape = list(op.get("shape") or ())
        block = list(op.get("block") or ())
        index = list(op.get("index") or ())
        if not (len(shape) == len(block) == len(index)):
            fail("KC1", f"{name}: shape/block/index ranks disagree "
                        f"({len(shape)}/{len(block)}/{len(index)})")
            continue
        for d, (s, b, ix) in enumerate(zip(shape, block, index)):
            s, b = int(s), int(b)
            if b < 1 or b > s:
                fail("KC1", f"{name} dim {d}: block {b} outside "
                            f"(0, shape={s}]")
                continue
            if s % b:
                fail("KC1", f"{name} dim {d}: block {b} does not "
                            f"tile shape {s} exactly")
            if isinstance(ix, str):
                n = axes.get(ix)
                if n is None:
                    fail("KC1", f"{name} dim {d}: index references "
                                f"unknown grid axis {ix!r}")
                elif n * b > s:
                    fail("KC1", f"{name} dim {d}: grid axis {ix} "
                                f"({n} steps) x block {b} = {n * b} "
                                f"rows exceeds shape {s}")
            else:
                if (int(ix) + 1) * b > s:
                    fail("KC1", f"{name} dim {d}: static origin "
                                f"{ix} x block {b} exceeds shape {s}")

    # -- KC1/KC3: slot-major streaming invariants ----------------------------
    st = meta.get("stream")
    if st:
        rb = int(st.get("row_block", 0))
        wave = int(st.get("wave", 0))
        n_waves = int(st.get("n_waves", 0))
        ring = int(st.get("ring", 0))
        c = int(st.get("granule", 1)) or 1
        slab = int(st.get("slab", 0))
        if wave * n_waves != rb:
            fail("KC1", f"stream: wave {wave} x n_waves {n_waves} != "
                        f"row_block {rb} — the wave loop misses rows")
        if rb % c:
            fail("KC1", f"stream: row_block {rb} is not a granule "
                        f"({c}) multiple")
        if slab < rb or (rb and slab % rb):
            fail("KC1", f"stream: slab {slab} is not a whole number "
                        f"of row blocks ({rb})")
        if grid and rb:
            gsz = axes.get(str(grid[0][0]))
            if gsz is not None and gsz != slab // rb:
                fail("KC1", f"stream: grid extent {gsz} != slab/"
                            f"row_block = {slab // rb}")
        lines = int(st.get("lines", 0))
        if int(st.get("table_rows", lines * c)) != lines * c:
            fail("KC1", f"stream: table_rows "
                        f"{st.get('table_rows')} != lines {lines} x "
                        f"granule {c} — packed-table addressing is "
                        f"off")
        scratch = list(meta.get("scratch") or ())
        if scratch:
            srows = int((scratch[0].get("shape") or (0,))[0])
            if srows != rb:
                fail("KC1", f"stream: scratch rows {srows} != "
                            f"row_block {rb} — a wave lands out of "
                            f"its slab")
        sems = meta.get("sems") or {}
        sshape = list(sems.get("shape") or ())
        if sshape != [ring, wave]:
            fail("KC3", f"stream: semaphore shape {sshape} != "
                        f"[ring={ring}, wave={wave}] — sem indices "
                        f"can leave range")
        if ring < 1:
            fail("KC3", f"stream: ring depth {ring} < 1")

    # -- KC2: VMEM + SMEM budgets --------------------------------------------
    vmem_budget = int(meta.get("vmem_budget") or 0)
    if vmem_budget:
        total = 0
        pieces = []
        if out.get("block"):
            nb = _prod(out["block"]) * int(out.get("itemsize", 4)) * 2
            total += nb
            pieces.append(f"out={nb}")
        for op in meta.get("ins") or ():
            if op.get("block") is not None and \
                    op.get("space", "vmem") == "vmem":
                nb = _prod(op["block"]) * int(op.get("itemsize", 4)) * 2
                total += nb
                pieces.append(f"{op.get('name', 'in')}={nb}")
        for scr in meta.get("scratch") or ():
            nb = _prod(scr.get("shape") or ()) * \
                int(scr.get("itemsize", 4))
            total += nb
            pieces.append(f"{scr.get('name', 'scratch')}={nb}")
        if total > vmem_budget:
            fail("KC2", f"VMEM footprint {total} B exceeds budget "
                        f"{vmem_budget} B ({', '.join(pieces)}; "
                        f"mapped blocks double-buffered)")
    smem = meta.get("smem")
    if smem and smem.get("budget") is not None:
        sbytes = int(smem.get("bytes", 0))
        sbudget = int(smem["budget"])
        if sbytes > sbudget and not smem.get("single_block"):
            fail("KC2", f"scalar-prefetch bytes {sbytes} exceed the "
                        f"SMEM budget {sbudget} and the slab is not "
                        f"already minimal")

    # -- KC5: output coverage -------------------------------------------------
    findings.extend(_check_coverage(meta, kernel, where, axes))
    return findings


def _check_coverage(meta: dict, kernel: str, where: str,
                    axes: Dict[str, int]) -> List[Finding]:
    """Enumerate every grid point and prove the output index map covers
    every output block exactly once (modulo declared revisit axes)."""
    import itertools

    findings: List[Finding] = []

    def fail(rule: str, message: str) -> None:
        findings.append(Finding(rule, kernel, where, message))

    out = meta.get("out") or {}
    shape = list(out.get("shape") or ())
    block = list(out.get("block") or ())
    index = list(out.get("index") or ())
    if not shape or len(shape) != len(block) or \
            len(index) != len(shape):
        return findings  # rank problems already reported under KC1
    if any(int(b) < 1 or int(s) % int(b) for s, b in zip(shape, block)):
        return findings  # tiling problems already reported under KC1

    order = [str(a) for a, _s in (meta.get("grid") or [])]
    n_points = _prod(axes[a] for a in order) if order else 1
    if n_points > MAX_GRID_POINTS:
        fail("KC5", f"grid has {n_points} points (> {MAX_GRID_POINTS})"
                    f" — refusing to certify coverage")
        return findings

    used = {ix for ix in index if isinstance(ix, str)}
    unused = [a for a in order if a not in used]
    revisit_declared = {str(a) for a in meta.get("revisit_axes") or ()}
    bad_revisit = [a for a in unused if a not in revisit_declared]
    expected = _prod(axes[a] for a in unused) if unused else 1
    if expected > 1 and bad_revisit:
        fail("KC5", f"grid axes {bad_revisit} do not appear in the "
                    f"output index map and are not declared revisit "
                    f"axes — every step overwrites the same block")

    counts: Dict[tuple, int] = {}
    for point in itertools.product(*(range(axes[a]) for a in order)):
        env = dict(zip(order, point))
        coord = tuple(env[ix] if isinstance(ix, str) else int(ix)
                      for ix in index)
        counts[coord] = counts.get(coord, 0) + 1

    want = set(itertools.product(
        *(range(int(s) // int(b)) for s, b in zip(shape, block))))
    missing = sorted(want - set(counts))
    if missing:
        fail("KC5", f"{len(missing)} output block(s) never written "
                    f"(first gap at block {missing[0]}) out of "
                    f"{len(want)}")
    extra = sorted(set(counts) - want)
    if extra:
        fail("KC5", f"index map writes {len(extra)} block(s) outside "
                    f"the output (first at {extra[0]})")
    uneven = {coord: n for coord, n in counts.items()
              if coord in want and n != expected}
    if uneven and not missing:
        coord, n = sorted(uneven.items())[0]
        fail("KC5", f"uneven coverage: block {coord} written {n}x, "
                    f"expected {expected}x"
                    + (" (revisit axes must revisit uniformly)"
                       if expected > 1 else ""))
    return findings


# ---------------------------------------------------------------------------
# Source checks: KC3 (ring schedule), KC4 (narrow accumulators / dots)
# ---------------------------------------------------------------------------

_NARROW_DTYPES = {"bfloat16", "float16", "int8", "float8_e4m3",
                  "float8_e5m2"}


def _dtype_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def _scan_kernel_fn(fn: ast.AST) -> dict:
    """Collect the KC3 schedule signals from one kernel function."""
    info = {"copies": 0, "starts": 0, "waits": 0,
            "sem_mod_ring": False, "prologue_min_ring": False,
            "issue_offset_ring": False}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "make_async_copy":
                info["copies"] += 1
                for arg in ast.walk(node):
                    if isinstance(arg, ast.BinOp) and \
                            isinstance(arg.op, ast.Mod):
                        info["sem_mod_ring"] = True
            elif name == "start" and isinstance(node.func,
                                                ast.Attribute):
                info["starts"] += 1      # copy(...).start(): a method
            elif name == "wait" and isinstance(node.func,
                                               ast.Attribute):
                info["waits"] += 1       # copy(...).wait(), not the
                                         # local wait() helper
            elif name == "min" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.BinOp) and \
                        isinstance(a0.op, ast.Sub) and \
                        isinstance(a0.right, ast.Constant) and \
                        a0.right.value == 1:
                    info["prologue_min_ring"] = True
            else:
                # issue(j, w + ring - 1): any call carrying the
                # "+ ring - 1" top-up offset.
                for arg in node.args:
                    if isinstance(arg, ast.BinOp) and \
                            isinstance(arg.op, ast.Sub) and \
                            isinstance(arg.right, ast.Constant) and \
                            arg.right.value == 1 and \
                            isinstance(arg.left, ast.BinOp) and \
                            isinstance(arg.left.op, ast.Add):
                        info["issue_offset_ring"] = True
    return info


def simulate_ring(ring: int, wave: int, n_waves: int) -> List[str]:
    """Replay the recognized prologue/top-up/wait schedule against a
    semaphore-slot model; every returned string is a KC3 violation.
    Proves: slot free on issue (reuse distance >= ring), wave waited
    exactly once, in-flight scratch rows disjoint, ring drained at the
    slot-body end."""
    violations: List[str] = []
    in_flight: Dict[int, int] = {}   # sem slot -> wave id

    def issue(w: int) -> None:
        slot = w % ring
        if slot in in_flight:
            violations.append(
                f"sem slot {slot} reissued for wave {w} while wave "
                f"{in_flight[slot]} is still in flight (reuse "
                f"distance < ring={ring})")
            return
        lo, hi = w * wave, (w + 1) * wave
        for ow in in_flight.values():
            if max(lo, ow * wave) < min(hi, (ow + 1) * wave):
                violations.append(
                    f"waves {ow} and {w} in flight alias scratch "
                    f"rows [{lo}, {hi})")
        in_flight[slot] = w

    def wait(w: int) -> None:
        slot = w % ring
        if in_flight.get(slot) != w:
            violations.append(
                f"wait({w}) finds slot {slot} holding "
                f"{in_flight.get(slot)} — copy never issued or "
                f"already consumed")
        else:
            del in_flight[slot]

    for p in range(min(ring - 1, n_waves)):
        issue(p)
    for w in range(n_waves):
        if w + ring - 1 < n_waves:
            issue(w + ring - 1)
        wait(w)
    if in_flight:
        violations.append(
            f"{len(in_flight)} cop(ies) still in flight at the "
            f"slot-body end (waves {sorted(in_flight.values())})")
    return violations


def analyze_kernel_source(
        source: str, path: str = "<source>",
        stream_points: Sequence[Tuple[int, int, int]] = (),
        ) -> List[Finding]:
    """AST pass over a kernel builder module: KC3 on every function
    whose name contains ``kernel`` and issues async copies, KC4 on
    narrow accumulators and unpinned dots in those functions."""
    findings: List[Finding] = []
    base = os.path.basename(path)
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding("KC1", base, "source",
                        f"unparseable kernel source: {exc}")]

    kernel_fns = [node for node in ast.walk(tree)
                  if isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                  and "kernel" in node.name]
    for fn in kernel_fns:
        where = f"{fn.name}:{fn.lineno}"
        info = _scan_kernel_fn(fn)
        if info["copies"]:
            if not info["waits"]:
                findings.append(Finding(
                    "KC3", base, where,
                    "make_async_copy issued but never .wait()ed — "
                    "the scratch slab is read while the DMA is in "
                    "flight"))
            elif not info["sem_mod_ring"]:
                findings.append(Finding(
                    "KC3", base, where,
                    "semaphore index is not ring-modular "
                    "(sems.at[w % ring, ...]) — in-flight slot "
                    "aliasing cannot be excluded"))
            elif not (info["prologue_min_ring"]
                      and info["issue_offset_ring"]):
                findings.append(Finding(
                    "KC3", base, where,
                    "unrecognized DMA schedule (no min(ring-1, ...) "
                    "prologue / w + ring - 1 top-up) — failing "
                    "closed"))
            else:
                for ring, wv, n_waves in stream_points:
                    for v in simulate_ring(ring, wv, n_waves):
                        findings.append(Finding(
                            "KC3", base,
                            f"{where}@ring{ring}w{wv}n{n_waves}", v))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in ("zeros", "full", "empty", "zeros_like"):
                for kw in node.keywords:
                    if kw.arg == "dtype" and \
                            _dtype_name(kw.value) in _NARROW_DTYPES:
                        findings.append(Finding(
                            "KC4", base,
                            f"{fn.name}:{node.lineno}",
                            f"accumulator initialized at narrow "
                            f"dtype {_dtype_name(kw.value)} — the "
                            f"carriage may narrow, the accumulator "
                            f"may not"))
            elif name == "dot":
                kws = {kw.arg for kw in node.keywords}
                if "preferred_element_type" not in kws:
                    findings.append(Finding(
                        "KC4", base, f"{fn.name}:{node.lineno}",
                        "jnp.dot without preferred_element_type — "
                        "the MXU accumulates at the carriage dtype"))
    return findings


# ---------------------------------------------------------------------------
# Entry certification + manifest
# ---------------------------------------------------------------------------


def stream_points_of(metas: Sequence[dict]) -> List[Tuple[int, int, int]]:
    return sorted({(int(m["stream"]["ring"]), int(m["stream"]["wave"]),
                    int(m["stream"]["n_waves"]))
                   for m in metas if m.get("stream")})


def certify_entry(entry: KernelEntry) -> dict:
    """Prove KC1-KC5 for one registered kernel; returns its manifest
    record (rule verdicts, witness detail, wall time)."""
    t0 = time.perf_counter()
    findings: List[Finding] = []
    try:
        metas = list(entry.metas())
    except Exception as exc:
        metas = []
        findings.append(Finding("KC1", entry.name, "metas",
                                f"meta enumeration raised: {exc!r}"))
    for meta in metas:
        findings.extend(check_meta(meta))
    src = entry.source()
    if src is not None:
        findings.extend(analyze_kernel_source(
            src, path=entry.source_path or "<source>",
            stream_points=stream_points_of(metas)))
    witness_detail = None
    if entry.witness is not None:
        ok, detail = entry.witness()
        witness_detail = detail
        if not ok:
            findings.append(Finding("KC1", entry.name, "witness",
                                    detail))
    wall_ms = (time.perf_counter() - t0) * 1000.0

    rules: Dict[str, dict] = {}
    for rule in RULE_IDS:
        hits = [f for f in findings if f.rule == rule]
        if hits:
            detail = "; ".join(f.format() for f in hits[:8])
            if len(hits) > 8:
                detail += f" (+{len(hits) - 8} more)"
            rules[rule] = {"status": "fail", "detail": detail}
        else:
            rules[rule] = {"status": "pass",
                           "detail": RULE_TITLES[rule]}
    return {
        "name": entry.name,
        "module": entry.contract.module,
        "kind": entry.contract.kind,
        "contract": entry.contract.to_json(),
        "points": len(metas),
        "rules": rules,
        "witness": witness_detail,
        "wall_ms": round(wall_ms, 2),
        "findings": [f.to_json() for f in findings],
        "ok": not findings,
    }


def certify_all(entries: Optional[Sequence[KernelEntry]] = None
                ) -> List[dict]:
    return [certify_entry(e)
            for e in (registered_kernels() if entries is None
                      else entries)]


def build_manifest(records: Sequence[dict]) -> dict:
    import datetime
    import platform as _platform

    rules: Dict[str, dict] = {}
    for rule in RULE_IDS:
        failed = [r["name"] for r in records
                  if r["rules"][rule]["status"] == "fail"]
        rules[rule] = ({"status": "fail",
                        "detail": "fails in: " + ", ".join(failed)}
                       if failed else
                       {"status": "pass",
                        "detail": RULE_TITLES[rule]})
    return {
        "generated_by": "python -m arrow_matrix_tpu.analysis kernels",
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "python_version": sys.version.split()[0],
        "platform": _platform.platform(),
        "package": "arrow_matrix_tpu",
        "kernels": sorted(records, key=lambda r: r["name"]),
        "rules": rules,
        "counts": {
            "kernels": len(records),
            "points": sum(r["points"] for r in records),
            "findings": sum(len(r["findings"]) for r in records),
            "rules_pass": sum(
                1 for r in records for rule in RULE_IDS
                if r["rules"][rule]["status"] == "pass"),
        },
        "ok": all(r["ok"] for r in records),
    }


def _jsonify(value):
    """Normalize tuples -> lists so an in-memory digest compares equal
    to its JSON round trip."""
    return json.loads(json.dumps(value))


def manifest_digest(manifest: dict) -> dict:
    """The behavior-only view the drift gate compares: rule verdicts,
    contracts, per-point findings — not timestamps or wall times."""
    return _jsonify({
        "rules": {r: v["status"]
                  for r, v in manifest.get("rules", {}).items()},
        "kernels": {
            k["name"]: {
                "kind": k["kind"],
                "contract": k["contract"],
                "points": k["points"],
                "rules": {r: v["status"]
                          for r, v in k["rules"].items()},
                "findings": sorted(
                    f"{f['rule']}:{f['where']}:{f['message']}"
                    for f in k.get("findings", ())),
            }
            for k in manifest.get("kernels", ())
        },
        "counts": {k: v for k, v in
                   (manifest.get("counts") or {}).items()},
        "ok": manifest.get("ok"),
    })


def manifest_drift(old: dict, new: dict) -> List[str]:
    """Human-readable differences between two manifests' digests
    (empty = no drift)."""
    a, b = manifest_digest(old), manifest_digest(new)
    problems: List[str] = []
    for rule in sorted(set(a["rules"]) | set(b["rules"])):
        if a["rules"].get(rule) != b["rules"].get(rule):
            problems.append(f"rule {rule} changed: "
                            f"{a['rules'].get(rule)} -> "
                            f"{b['rules'].get(rule)}")
    for name in sorted(set(a["kernels"]) | set(b["kernels"])):
        if name not in b["kernels"]:
            problems.append(f"kernel disappeared: {name}")
        elif name not in a["kernels"]:
            problems.append(f"new unrecorded kernel: {name}")
        else:
            ka, kb = a["kernels"][name], b["kernels"][name]
            for key in ("kind", "contract", "points", "rules"):
                if ka[key] != kb[key]:
                    problems.append(f"kernel {name}: {key} changed")
            if ka["findings"] != kb["findings"]:
                problems.append(f"kernel {name}: finding set changed")
    if a["counts"] != b["counts"]:
        problems.append(f"verdict counts changed: {a['counts']} -> "
                        f"{b['counts']}")
    if a["ok"] != b["ok"]:
        problems.append(f"overall ok changed: {a['ok']} -> {b['ok']}")
    return problems


def _record_ledger(manifest: dict,
                   ledger_dir: Optional[str] = None) -> None:
    """kind="kcert" verdict-count record: ledger_gate drift-checks the
    pass count the same way it bands perf (a dropped rule or kernel
    shows up as a count regression)."""
    from arrow_matrix_tpu.ledger.store import record as ledger_record

    counts = manifest.get("counts") or {}
    ledger_record(
        "kcert", "rules_pass", float(counts.get("rules_pass", 0)),
        directory=ledger_dir, unit="count", host_load=None,
        knobs={"kernels": counts.get("kernels", 0),
               "points": counts.get("points", 0)},
        payload={"findings": counts.get("findings", 0),
                 "ok": bool(manifest.get("ok"))})


def run_kernels(out_path: str = DEFAULT_MANIFEST,
                write: bool = True,
                ledger_dir: Optional[str] = None,
                record: bool = False) -> dict:
    """Certify every registered kernel; return (and write) the
    manifest."""
    manifest = build_manifest(certify_all())
    if write:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if record:
        _record_ledger(manifest, ledger_dir=ledger_dir)
    return manifest


# ---------------------------------------------------------------------------
# Tune-candidate certification (the pruning hook)
# ---------------------------------------------------------------------------


def certify_candidate_opts(kernel_opts: Optional[dict], k: int, *,
                           interpret: bool = False,
                           feature_dtype=None,
                           m_t: int = 8) -> Optional[str]:
    """Certify one tune candidate's pallas_sell options BEFORE any
    child process spawns: returns ``None`` when the concretized call
    meta proves out under KC1-KC5, else a ``"kcert: ..."`` prune
    reason.  ``m_t`` is a representative tier width (certification is
    shape-generic in m_t: the meta arithmetic scales linearly)."""
    from arrow_matrix_tpu.ops import pallas_sell as ps

    cc = ps.KERNEL_CONTRACT
    opts = dict(kernel_opts or {})
    stream = not interpret
    if stream and not cc.supports_k(k):
        return (f"kcert: streaming pallas_sell needs k % "
                f"{cc.stream_k_multiple} == 0 on chip (k={k})")
    if feature_dtype is None:
        feature_dtype = opts.get("feature_dtype")
    try:
        carriage, _dt = ps.resolve_carriage_dtype(feature_dtype)
    except ValueError as exc:
        return f"kcert: {exc}"
    if carriage not in cc.carriage_dtypes:
        return (f"kcert: carriage dtype {carriage!r} outside the "
                f"contract ({cc.carriage_dtypes})")

    def _point(rb, wave, ring, budget, pt_carriage, pt_m_t):
        # Mimic the runtime's rb/wave normalization; ring and budgets
        # are taken literally (they are what the plan executes with).
        rb = max(cc.granule, int(rb) - int(rb) % cc.granule)
        w = min(int(wave), rb)
        while w > 1 and rb % w:
            w -= 1
        try:
            meta = ps.slab_call_meta(
                pt_m_t, ps.slab_rows(pt_m_t, rb, budget), k, rb, True,
                stream, w, int(ring), carriage=pt_carriage,
                smem_cols_budget=budget)
        except (ValueError, ZeroDivisionError) as exc:
            return f"kcert: {exc}"
        findings = check_meta(meta)
        if findings:
            f0 = findings[0]
            return f"kcert: {f0.rule}: {f0.message}"
        return None

    schedule = opts.get("schedule")
    if schedule:
        # graft-synth per-level schedule: certify EVERY tier's
        # concretized point with its own knobs and realized slot width
        # — one uncertifiable tier prunes the whole candidate.
        try:
            sched = ps._schedule_overrides(schedule)
        except (ValueError, TypeError) as exc:
            return f"kcert: {exc}"
        for t in sorted(sched):
            ov = sched[t]
            pt_c = ov.get("carriage", carriage)
            if pt_c == "int8":
                return (f"kcert: tier {t}: per-tier int8 carriage is "
                        f"not schedulable (whole-call quantization)")
            if pt_c not in cc.carriage_dtypes:
                return (f"kcert: tier {t}: carriage {pt_c!r} outside "
                        f"the contract ({cc.carriage_dtypes})")
            why = _point(
                ov.get("row_block", opts.get("row_block",
                                             ps.DEFAULT_ROW_BLOCK)),
                ov.get("wave", opts.get("wave", ps.DEFAULT_WAVE)),
                ov.get("ring", opts.get("ring", ps.DEFAULT_RING)),
                ov.get("smem_cols_budget", opts.get("smem_cols_budget")),
                pt_c, int(ov.get("m_t", m_t)) or m_t)
            if why is not None:
                return f"kcert: tier {t}: {why[len('kcert: '):]}"
        return None
    return _point(opts.get("row_block", ps.DEFAULT_ROW_BLOCK),
                  opts.get("wave", ps.DEFAULT_WAVE),
                  opts.get("ring", ps.DEFAULT_RING),
                  opts.get("smem_cols_budget"), carriage, m_t)


# ---------------------------------------------------------------------------
# Fixtures + selftest
# ---------------------------------------------------------------------------


def fixture_contract(path: str) -> str:
    """Expected rule for a planted-broken-kernel fixture, from its
    ``kcN_*.py`` filename."""
    base = os.path.basename(path)
    for rule in RULE_IDS:
        if base.lower().startswith(rule.lower() + "_"):
            return rule
    raise ValueError(
        f"fixture {base!r} does not follow the kcN_<slug>.py "
        f"convention")


def certify_paths(paths: Sequence[str]) -> List[Finding]:
    """Certify arbitrary kernel files: literal ``META``/``METAS``
    assignments go through the meta checks, the source through the
    KC3/KC4 AST pass (with stream points read off the metas)."""
    findings: List[Finding] = []
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        metas: List[dict] = []
        try:
            tree = ast.parse(src)
        except SyntaxError as exc:
            findings.append(Finding(
                "KC1", os.path.basename(path), "source",
                f"unparseable kernel source: {exc}"))
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and
                    t.id in ("META", "METAS") for t in node.targets):
                try:
                    val = ast.literal_eval(node.value)
                except ValueError:
                    findings.append(Finding(
                        "KC1", os.path.basename(path),
                        f"line {node.lineno}",
                        "META must be a pure literal"))
                    continue
                metas.extend(val if isinstance(val, list) else [val])
        for meta in metas:
            findings.extend(check_meta(meta))
        findings.extend(analyze_kernel_source(
            src, path=path, stream_points=stream_points_of(metas)))
    return findings


def verify_fixture(path: str) -> Tuple[bool, str]:
    """(ok, detail): the fixture must fire its expected rule."""
    expected = fixture_contract(path)
    findings = certify_paths([path])
    fired = sorted({f.rule for f in findings})
    if expected in fired:
        return True, (f"{os.path.basename(path)}: {expected} fired "
                      f"({len(findings)} finding(s))")
    return False, (f"{os.path.basename(path)}: expected {expected}, "
                   f"got {fired or 'nothing'}")


_SELFTEST_GOOD_META = {
    "kernel": "selftest_sell", "kind": "sell_stream",
    "grid": [["i", 4]],
    "out": {"shape": [128, 128], "block": [32, 128],
            "index": ["i", 0], "itemsize": 4},
    "ins": [
        {"name": "cols_vmem", "shape": [8, 1024], "block": [8, 256],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "weights", "shape": [1, 1024], "block": [1, 256],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "x_packed", "shape": [512, 128], "block": None,
         "index": None, "space": "any", "itemsize": 4},
    ],
    "smem": {"name": "cols_prefetch", "bytes": 32768,
             "budget": 1048576, "single_block": False},
    "scratch": [{"name": "dma_scratch", "shape": [256, 128],
                 "itemsize": 4}],
    "sems": {"shape": [2, 16]},
    "vmem_budget": 8388608,
    "accum_dtype": "f32",
    "carriage_dtype": "f32",
    "revisit_axes": [],
    "stream": {"ring": 2, "wave": 16, "n_waves": 16,
               "row_block": 256, "granule": 8, "slab": 1024,
               "m_t": 8, "lines": 512, "table_rows": 4096},
}


def _broken_meta(**patch) -> dict:
    import copy

    meta = copy.deepcopy(_SELFTEST_GOOD_META)
    for key, val in patch.items():
        if isinstance(val, dict) and isinstance(meta.get(key), dict):
            meta[key].update(val)
        else:
            meta[key] = val
    return meta


_SELFTEST_BROKEN_METAS = {
    # grid x block overruns the out rows AND the slab arithmetic.
    "KC1": _broken_meta(grid=[["i", 5]]),
    # 32 MB scratch against the 8 MB budget.
    "KC2": _broken_meta(scratch=[{"name": "dma_scratch",
                                  "shape": [4096, 2048],
                                  "itemsize": 4}]),
    # sem ring narrower than declared: slot aliasing in range.
    "KC3": _broken_meta(sems={"shape": [1, 16]}),
    # narrow accumulator declared.
    "KC4": _broken_meta(accum_dtype="bf16"),
    # grid covers 3 of 4 output blocks.
    "KC5": _broken_meta(grid=[["i", 3]],
                        stream={"slab": 768},
                        smem={"bytes": 24576},
                        out={"shape": [96, 128]},
                        ins=[
                            {"name": "cols_vmem", "shape": [8, 768],
                             "block": [8, 256], "index": [0, "i"],
                             "space": "vmem", "itemsize": 4},
                            {"name": "weights", "shape": [1, 768],
                             "block": [1, 256], "index": [0, "i"],
                             "space": "vmem", "itemsize": 4},
                            {"name": "x_packed", "shape": [512, 128],
                             "block": None, "index": None,
                             "space": "any", "itemsize": 4},
                        ]),
}

# KC5 twin: out shape [96,128] tiles into 3 blocks but grid covers 3 —
# make the gap real by keeping 4 blocks of output with a 3-step grid.
_SELFTEST_BROKEN_METAS["KC5"]["out"] = {
    "shape": [128, 128], "block": [32, 128], "index": ["i", 0],
    "itemsize": 4}

_SELFTEST_GOOD_SOURCE = '''
def kernel_stream(cols_smem, x_any, out_ref, scratch, sems):
    def copy(j, w, r):
        rr = w * wave + r
        g = cols_smem[j, rr]
        return pltpu.make_async_copy(
            x_any.at[g], scratch.at[rr], sems.at[w % ring, r])

    def issue(j, w):
        jax.lax.fori_loop(
            0, wave, lambda r, _: (copy(j, w, r).start(), 0)[1], 0)

    def wait(j, w):
        jax.lax.fori_loop(
            0, wave, lambda r, _: (copy(j, w, r).wait(), 0)[1], 0)

    def slot_body(j, acc):
        for p in range(min(ring - 1, n_waves)):
            issue(j, p)

        def wave_body(w, carry):
            @pl.when(w + ring - 1 < n_waves)
            def _():
                issue(j, w + ring - 1)
            wait(j, w)
            return carry

        jax.lax.fori_loop(0, n_waves, wave_body, 0)
        return acc + jnp.zeros((8, 16), dtype=jnp.float32)

    out_ref[...] = slot_body(0, 0)
'''

_SELFTEST_BROKEN_SOURCES = {
    "KC3": _SELFTEST_GOOD_SOURCE.replace(
        "(copy(j, w, r).wait(), 0)[1]", "0"),
    "KC4": _SELFTEST_GOOD_SOURCE.replace(
        "dtype=jnp.float32", "dtype=jnp.bfloat16"),
}


def selftest() -> Tuple[bool, List[str]]:
    """Inline good/broken twins — host-only, no jax import, runnable
    from any cwd (the doctor KCERT probe's first half)."""
    lines: List[str] = []
    ok = True

    good = check_meta(_SELFTEST_GOOD_META)
    if good:
        ok = False
        lines.append("selftest GOOD meta produced findings: " +
                     "; ".join(f.format() for f in good))
    else:
        lines.append("good meta clean")
    for rule, meta in sorted(_SELFTEST_BROKEN_METAS.items()):
        fired = {f.rule for f in check_meta(meta)}
        if rule not in fired:
            ok = False
            lines.append(f"selftest broken meta for {rule} did not "
                         f"fire (got {sorted(fired) or 'nothing'})")
        else:
            lines.append(f"{rule} fires on its broken meta")

    pts = [(2, 16, 16), (1, 8, 8), (4, 16, 16)]
    good_src = analyze_kernel_source(_SELFTEST_GOOD_SOURCE,
                                     "<good>", stream_points=pts)
    if good_src:
        ok = False
        lines.append("selftest GOOD source produced findings: " +
                     "; ".join(f.format() for f in good_src))
    else:
        lines.append("good source clean (schedule recognized + "
                     "simulated at 3 ring points)")
    for rule, src in sorted(_SELFTEST_BROKEN_SOURCES.items()):
        fired = {f.rule for f in analyze_kernel_source(
            src, f"<broken-{rule}>", stream_points=pts)}
        if rule not in fired:
            ok = False
            lines.append(f"selftest broken source for {rule} did not "
                         f"fire (got {sorted(fired) or 'nothing'})")
        else:
            lines.append(f"{rule} fires on its broken source")

    # The ring simulator itself must reject a broken schedule: issue
    # distance ring+1 reuses a slot while in flight.
    sim = simulate_ring(1, 8, 4)
    if sim:
        ok = False
        lines.append("simulator rejected the serial ring=1 schedule")
    else:
        lines.append("simulator accepts ring=1..4 canonical "
                     "schedules")
    return ok, lines


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _print_manifest(manifest: dict) -> None:
    for k in manifest["kernels"]:
        for rule in RULE_IDS:
            v = k["rules"][rule]
            mark = "ok  " if v["status"] == "pass" else "FAIL"
            print(f"[{mark}] {k['name']} {rule}: {v['detail']}")
    counts = manifest["counts"]
    print(f"kernels: {counts['kernels']}  points: {counts['points']}  "
          f"rule verdicts passing: {counts['rules_pass']}/"
          f"{counts['kernels'] * len(RULE_IDS)}")


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="graft_kcert", description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_MANIFEST)
    ap.add_argument("--check", action="store_true",
                    help="do not write; fail on any violation OR "
                         "drift against the checked-in manifest")
    ap.add_argument("--selftest", action="store_true",
                    help="run the inline good/broken twins (host-"
                         "only) and exit")
    ap.add_argument("--fixture", action="append", default=[],
                    help="verify a planted-broken-kernel fixture "
                         "fires its expected rule (repeatable)")
    ap.add_argument("--paths", nargs="+", default=None,
                    help="certify these kernel files and exit "
                         "nonzero on any finding")
    ap.add_argument("--ledger", default=None,
                    help="also append the kind=kcert verdict-count "
                         "record to this ledger directory")
    args = ap.parse_args(argv)

    if args.selftest:
        ok, lines = selftest()
        for ln in lines:
            print(ln)
        print("selftest passed" if ok else "SELFTEST FAILED")
        return 0 if ok else 1

    if args.fixture:
        rc = 0
        for path in args.fixture:
            ok, detail = verify_fixture(path)
            print(("ok   " if ok else "FAIL ") + detail)
            rc = rc or (0 if ok else 1)
        return rc

    if args.paths:
        findings = certify_paths(args.paths)
        for f in findings:
            print(f.format())
        if findings:
            print(f"kcert: {len(findings)} finding(s) in "
                  f"{len(args.paths)} file(s)", file=sys.stderr)
            return 1
        print("kcert: paths certify clean", file=sys.stderr)
        return 0

    manifest = run_kernels(out_path=args.out, write=not args.check,
                           ledger_dir=args.ledger,
                           record=bool(args.ledger))
    _print_manifest(manifest)

    rc = 0 if manifest["ok"] else 1
    if args.check:
        try:
            with open(args.out, encoding="utf-8") as fh:
                checked_in = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"no readable checked-in manifest at {args.out}: "
                  f"{e}")
            return 1
        drift = manifest_drift(checked_in, manifest)
        for d in drift:
            print(f"drift: {d}")
        if drift:
            print(f"kernel drift against {args.out} — rerun `python "
                  f"-m arrow_matrix_tpu.analysis kernels` and commit "
                  f"the refreshed manifest")
            rc = 1
    else:
        print(f"manifest: {args.out}")
    print("kernel certification passed" if rc == 0
          else "KERNEL CERTIFICATION FAILED")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
