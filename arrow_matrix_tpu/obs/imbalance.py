"""Per-shard load-imbalance accounting from packed format metadata.

The paper's imbalance bound is structural: arrow decomposition caps
every block at ``width`` columns, so the max/mean per-shard compute
ratio is bounded by construction — but ELL-family padding can still
inflate a shard's *gathered slots* well past its nonzeros (the
layout-padding law, PERFORMANCE.md: up to 8x from slot alignment
alone).  This module turns the packed arrays' own metadata (degree
masks, value stacks, slot shapes — ops/{ell,sell,hyb,arrow_blocks})
into three first-class metrics per algorithm:

  * ``shard_nnz_max_over_mean``  — the paper's imbalance bound, as
    measured on the shards the runtime actually built;
  * ``shard_rows_max_over_mean`` — row-count skew (ragged tails);
  * ``padded_slot_waste``        — fraction of gathered slots that are
    padding (slots are THE cost of the gather kernels).

Each of the five parallel algorithms exposes ``shard_report()``
returning the summary below; ``account_imbalance`` records it.  The
per-unit fetches read only the small metadata arrays (degree vectors,
or one pass over value stacks at build scale) — this is a diagnostics
path, opt-in from the CLIs via ``--mem_report``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from arrow_matrix_tpu.obs import flight


def _max_over_mean(values: Sequence[float]) -> Optional[float]:
    arr = np.asarray(values, dtype=np.float64)
    if arr.size == 0:
        return None
    mean = float(arr.mean())
    if mean <= 0:
        return None
    return float(arr.max()) / mean


def summarize_units(rows, nnz, slots, units: str = "shard"
                    ) -> Dict[str, Any]:
    """Imbalance summary over per-unit (rows, nnz, slots) arrays.

    ``units`` names what one entry is ("device", "block-row", "tier",
    "level-shard") — the finest compute granularity the layout
    exposes; contiguous-run device sharding means unit skew bounds
    device skew.
    """
    rows = [int(v) for v in np.asarray(rows, dtype=np.int64).ravel()]
    nnz = [int(v) for v in np.asarray(nnz, dtype=np.int64).ravel()]
    slots = [int(v) for v in np.asarray(slots, dtype=np.int64).ravel()]
    slots_total = sum(slots)
    nnz_total = sum(nnz)
    padded = [s - z for s, z in zip(slots, nnz)]
    return {
        "units": units,
        "n_units": len(nnz),
        "rows": rows,
        "nnz": nnz,
        "slots": slots,
        # Realized per-unit padding — graft-lens prices these slots
        # (every padded slot still streams a full granule line), so the
        # shard report names WHICH tier/shard pays the waste.
        "padded_slots": padded,
        "padded_slot_waste_per_unit": [
            (p / s if s else None) for p, s in zip(padded, slots)],
        "rows_total": sum(rows),
        "nnz_total": nnz_total,
        "slots_total": slots_total,
        "nnz_max_over_mean": _max_over_mean(nnz),
        "rows_max_over_mean": _max_over_mean(rows),
        "padded_slot_waste": ((slots_total - nnz_total) / slots_total
                              if slots_total else None),
    }


def shard_report_for(obj) -> Optional[Dict[str, Any]]:
    """The orchestration's own per-shard load report, or None when it
    exposes none (mirrors ``ideal_bytes_for`` / ``predicted_bytes_for``)."""
    fn = getattr(obj, "shard_report", None)
    if fn is None:
        return None
    return fn()


def account_imbalance(algorithm: str, obj,
                      registry=None) -> Optional[Dict[str, Any]]:
    """Record one orchestration's shard-imbalance metrics.

    Returns the shard report (with ``algorithm`` added) or None when
    the object has no ``shard_report``.
    """
    rep = shard_report_for(obj)
    if rep is None:
        return None
    rep = dict(rep, algorithm=algorithm)
    if registry is not None:
        registry.gauge("shard_count", algorithm=algorithm).set(
            rep["n_units"])
        registry.gauge("shard_nnz_total", algorithm=algorithm).set(
            rep["nnz_total"])
        registry.gauge("shard_slots_total", algorithm=algorithm).set(
            rep["slots_total"])
        if rep["nnz_max_over_mean"] is not None:
            registry.gauge("shard_nnz_max_over_mean",
                           algorithm=algorithm).set(
                rep["nnz_max_over_mean"])
        if rep["rows_max_over_mean"] is not None:
            registry.gauge("shard_rows_max_over_mean",
                           algorithm=algorithm).set(
                rep["rows_max_over_mean"])
        if rep["padded_slot_waste"] is not None:
            registry.gauge("padded_slot_waste",
                           algorithm=algorithm).set(
                rep["padded_slot_waste"])
    flight.record("imbalance", algorithm,
                  n_units=rep["n_units"],
                  nnz_max_over_mean=rep["nnz_max_over_mean"],
                  padded_slot_waste=rep["padded_slot_waste"])
    return rep


def format_imbalance_report(rep: Dict[str, Any]) -> str:
    """Human-readable lines for the CLIs' ``--mem_report``."""
    def f(v, spec=".3f"):
        return "n/a" if v is None else format(v, spec)

    return "\n".join([
        f"per-shard load balance ({rep['n_units']} {rep['units']}"
        f" units):",
        f"  nnz   total {rep['nnz_total']}, max/mean "
        f"{f(rep['nnz_max_over_mean'])} (paper imbalance bound)",
        f"  rows  total {rep['rows_total']}, max/mean "
        f"{f(rep['rows_max_over_mean'])}",
        f"  slots total {rep['slots_total']}, padding waste "
        f"{f(rep['padded_slot_waste'])}",
    ])
