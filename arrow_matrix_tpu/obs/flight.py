"""graft-flight: a bounded ring of recent obs events, flushed to disk
so a wedged run leaves a diagnosable artifact.

bench.py's candidate subprocesses die by SIGKILL when their timeout
expires (a wedged PJRT transfer is uninterruptible by signals), so
nothing in-process runs at the moment of death.  The recorder therefore
flushes EAGERLY: every ``record`` rewrites the artifact via an atomic
tmp+rename (the ring is bounded, so a flush is one small JSON write).
The on-disk state is at most one event behind the process when the
kill lands — a "blackbox" in the avionics sense, not a log.

Wiring: ``install()`` sets the process-global recorder; the existing
Tracer (span completion) and MetricsRegistry (every counter/gauge/
histogram event) feed it automatically through the module-level
``record`` hook, which is a no-op until a recorder is installed.  The
last compiled-executable memory report (obs/memview) is kept whole —
it is exactly what diagnoses an upload wedging mid-transfer.

Inspect artifacts with ``graft_trace blackbox <path-or-dir>``.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import contextvars
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from arrow_matrix_tpu.sync import guarded_by, witnessed
from arrow_matrix_tpu.utils.artifacts import atomic_write_json

#: Default ring capacity: enough for every phase span + per-iteration
#: metric of a bench candidate with room to spare, small enough that
#: the eager per-event flush stays a one-page write.
DEFAULT_CAPACITY = 256

# -- request-scoped correlation context (graft-pulse) -----------------------
#
# The serving runtime processes many requests through one shared
# tracer/flight/metrics pipeline; without a shared key their streams
# cannot be joined back into one per-request story.  The context lives
# here (not in obs/pulse.py) because flight is the dependency-free spine
# every other obs module already imports: the recorder stamps events,
# the tracer stamps spans, pulse re-exports the API.  contextvars makes
# the correlation survive both the worker-thread handoff inside one
# request and interleaved requests on different threads.

_REQUEST_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "amt_request_ctx", default=None)


def current_request() -> Optional[Dict[str, str]]:
    """The active request correlation context — a dict with
    ``request_id`` (and ``tenant`` when known) — or None outside any
    request scope."""
    return _REQUEST_CTX.get()


@contextlib.contextmanager
def request_context(request_id: str,
                    tenant: Optional[str] = None,
                    **extra: Optional[str]) -> Iterator[None]:
    """Scope every flight event / tracer span / pulse observation made
    inside the body to one request (or one batch of requests — a
    batched key like ``"r0001+r0002"`` names every member).

    Nested scopes MERGE-INHERIT: keys of the enclosing context that the
    inner scope does not override stay visible, so a fleet-level
    ``trace_id`` stamped at the worker's wire entry survives the
    scheduler re-entering the context for the same request (graft-xray
    rides on exactly this).  Extra keyword correlation keys (e.g.
    ``trace_id``, ``parent_span``) are stamped as strings; None values
    are skipped, never stored.
    """
    base = current_request()
    ctx: Dict[str, str] = dict(base) if base else {}
    ctx["request_id"] = str(request_id)
    if tenant is not None:
        ctx["tenant"] = str(tenant)
    for key, value in extra.items():
        if value is not None:
            ctx[key] = str(value)
    token = _REQUEST_CTX.set(ctx)
    try:
        yield
    finally:
        _REQUEST_CTX.reset(token)


@guarded_by("_lock", node="flight_recorder",
            attrs=("events", "dropped", "sealed",
                   "last_memory_report"))
class FlightRecorder:
    """Bounded in-memory ring of obs events with eager disk flush."""

    def __init__(self, path: Optional[str] = None,
                 capacity: int = DEFAULT_CAPACITY,
                 autoflush: bool = True):
        self.path = path
        self.capacity = capacity
        self.events: collections.deque = collections.deque(maxlen=capacity)
        self.autoflush = autoflush and path is not None
        self.sealed: Optional[str] = None
        self.last_memory_report: Optional[Dict[str, Any]] = None
        self.dropped = 0
        # graft-serve records from the always-on worker thread while
        # the submitting thread records admission events: ring append,
        # dropped accounting, and the snapshot-for-flush must be
        # mutually exclusive or a flush can serialize a half-updated
        # ring.  (RLock: seal() flushes while already holding it.)
        self._lock = witnessed("flight_recorder", threading.RLock())
        self.meta = {
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "created_unix": time.time(),
        }

    def record(self, kind: str, name: str, **data) -> None:
        """Append one event (and flush, when a path is configured).
        Events are stamped with the recording thread's name and, inside
        a :func:`request_context` scope, the request id/tenant — the
        correlation keys graft-pulse joins streams on."""
        ev: Dict[str, Any] = {"ts": time.time(), "kind": kind,
                              "name": name,
                              "thread": threading.current_thread().name}
        ctx = current_request()
        if ctx is not None:
            ev.update(ctx)
        if data:
            ev["data"] = data
        with self._lock:
            if len(self.events) == self.capacity:
                self.dropped += 1
            self.events.append(ev)
            if self.autoflush:
                self.flush()

    def note_memory_report(self, report: Dict[str, Any]) -> None:
        """Keep the latest per-executable memory report whole (the ring
        holds it as an event too, but a wedge postmortem wants the full
        breakdown, not whatever survived the ring)."""
        with self._lock:
            self.last_memory_report = dict(report)
        self.record("memreport", report.get("algorithm", "unknown"),
                    measured_bytes=report.get("measured_bytes"),
                    ratio=report.get("ratio"))

    def seal(self, reason: str) -> None:
        """Final flush with the termination reason.  Idempotent — the
        first seal wins (an excepthook seal must not be overwritten by
        the atexit seal that follows it)."""
        with self._lock:
            if self.sealed is None:
                self.sealed = reason
                self.flush()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "meta": self.meta,
                "sealed": self.sealed,
                "dropped": self.dropped,
                "last_memory_report": self.last_memory_report,
                "events": list(self.events),
            }

    def flush(self) -> Optional[str]:
        """Atomically rewrite the artifact; returns its path (None when
        no path is configured).  Write failures are swallowed — the
        recorder must never take down the run it is observing.  The
        tmp name carries the writing thread's id so two threads
        flushing concurrently cannot interleave one tmp file."""
        if self.path is None:
            return None
        snap = self.snapshot()
        try:
            # fsync=False: the black box flushes on EVERY event — the
            # crash modes it defends against (SIGKILL, excepthook) keep
            # the page cache, and an fsync per event would tax the run
            # it observes.
            atomic_write_json(self.path, snap, fsync=False)
        except OSError:
            pass
        return self.path


_RECORDER: Optional[FlightRecorder] = None


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def set_recorder(rec: Optional[FlightRecorder]) -> None:
    global _RECORDER
    _RECORDER = rec


def record(kind: str, name: str, **data) -> None:
    """Module-level hook used by Tracer/MetricsRegistry: no-op until a
    recorder is installed, so the obs layer pays nothing by default."""
    if _RECORDER is not None:
        _RECORDER.record(kind, name, **data)


def install(path: str, capacity: int = DEFAULT_CAPACITY
            ) -> FlightRecorder:
    """Install the process-global recorder writing to ``path`` and hook
    process termination: unhandled exceptions seal with the error,
    normal interpreter exit seals as "exit".  (A SIGKILL runs neither —
    that is what the eager per-event flush is for.)"""
    rec = FlightRecorder(path, capacity=capacity)
    set_recorder(rec)
    prev_hook = sys.excepthook

    def _seal_on_exception(exc_type, exc, tb):
        rec.seal(f"exception: {exc_type.__name__}: {exc}")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _seal_on_exception
    atexit.register(rec.seal, "exit")
    rec.flush()
    return rec


def load(path: str) -> Dict[str, Any]:
    """Read one flight artifact back."""
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def newest_artifact(directory: str) -> Optional[str]:
    """The most recently written ``*.json`` artifact under
    ``directory`` (non-recursive), or None."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    best: Optional[str] = None
    best_mt = -1.0
    for name in names:
        if not name.endswith(".json"):
            continue
        p = os.path.join(directory, name)
        try:
            mt = os.path.getmtime(p)
        except OSError:
            continue
        if mt > best_mt:
            best, best_mt = p, mt
    return best


def format_events(snapshot: Dict[str, Any],
                  last: Optional[int] = None) -> List[str]:
    """Human-readable lines for ``graft_trace blackbox``."""
    events = snapshot.get("events", [])
    if last is not None:
        events = events[-last:]
    meta = snapshot.get("meta", {})
    sealed = (snapshot.get("sealed")
              or "NO (process killed or still running)")
    lines = [f"flight recorder: pid={meta.get('pid')} "
             f"argv={' '.join(meta.get('argv', []))[:120]}",
             f"sealed: {sealed}; dropped={snapshot.get('dropped', 0)}"]
    t0 = events[0]["ts"] if events else 0.0
    for ev in events:
        data = ev.get("data")
        extra = (" " + " ".join(f"{k}={v}" for k, v in data.items())
                 if data else "")
        lines.append(f"  +{ev['ts'] - t0:9.3f}s [{ev['kind']:>8}] "
                     f"{ev['name']}{extra}")
    rep = snapshot.get("last_memory_report")
    if rep:
        lines.append(f"last memory report: {json.dumps(rep)}")
    return lines
