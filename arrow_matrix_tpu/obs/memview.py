"""Trace-time HBM memory accounting vs the format's static predictor.

``account_memory`` is the memory twin of ``account_collectives``
(obs/comm.py): it lowers+compiles a jitted entry point (compiles are
cached, so accounting a step that already ran is free), reads the
backend's per-executable memory breakdown via
``compiled.memory_analysis()`` — argument / output / temp /
generated-code bytes, all PER DEVICE — and, when the orchestration
exposes a ``predicted_hbm_bytes(k)`` model, records the
measured/predicted ratio as a first-class metric.  The ratio is the
run-level statement of the paper's memory claim: ~1.0 means the
compiled executable is resident at exactly the bytes the format
metadata (nnz, widths, padding slots) predicts; large ratios mean the
lowering materializes something the algorithm doesn't require — an
OOM-in-waiting at protocol scale (the round-1/2 postmortems' ~1.3 GB
uploads wedging the tunnel are exactly this failure mode, bench.py).

Not every backend exposes ``memory_analysis`` (and some raise
``Unimplemented``): the fallback computes argument/output bytes from
the executable's avals instead, flagged ``source="avals"`` with temp
and generated-code bytes unknown (None) — degraded, never absent.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from arrow_matrix_tpu.obs import flight


def tree_device_bytes(*trees) -> int:
    """Total bytes of every array leaf in the given pytrees, computed
    from shape metadata only (no device transfer).  Non-array leaves
    (None, scalars, ints in route tables' aux data) contribute zero."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(trees):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        total += int(size) * np.dtype(dtype).itemsize
    return total


def _aval_bytes(avals) -> int:
    total = 0
    for a in avals:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(
            dtype).itemsize
    return total


def memory_report(jitted_fn, *args, **kwargs) -> Dict[str, Any]:
    """Per-executable memory breakdown of one jitted entry point.

    Returns ``{"source", "argument_bytes", "output_bytes",
    "temp_bytes", "generated_code_bytes", "alias_bytes",
    "total_bytes"}``.  ``source`` is ``"memory_analysis"`` when the
    backend exposed the compiled stats, ``"avals"`` for the fallback
    (argument/output from abstract values; temp/generated-code None).
    ``total_bytes`` sums every known component — the executable's
    device-resident footprint for one call.
    """
    lowered = jitted_fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    try:
        ma = compiled.memory_analysis()
        report = {
            "source": "memory_analysis",
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
    except Exception:
        # Unimplemented on this backend/jaxlib: fall back to the
        # executable's abstract values — still per-device for the
        # arguments/outputs, just blind to XLA temporaries.
        in_avals = getattr(compiled, "in_avals", None) or ()
        out_avals = getattr(compiled, "out_avals", None)
        if out_avals is None:
            out_avals = ()
        report = {
            "source": "avals",
            "argument_bytes": _aval_bytes(in_avals),
            "output_bytes": _aval_bytes(out_avals),
            "temp_bytes": None,
            "generated_code_bytes": None,
            "alias_bytes": None,
        }
    # Aliased (donated) buffers are counted inside argument bytes and
    # reused for outputs — do not double-charge them in the footprint.
    known = [report["argument_bytes"], report["output_bytes"],
             report["temp_bytes"], report["generated_code_bytes"]]
    total = sum(v for v in known if v is not None)
    if report["alias_bytes"]:
        total -= report["alias_bytes"]
    report["total_bytes"] = max(int(total), 0)
    return report


def predicted_bytes_for(obj, k: int, itemsize: int = 4,
                        repl: int = 1) -> Optional[int]:
    """The orchestration's own static per-shard HBM model for one step
    at feature width ``k``, or None when it has no model.

    ``repl`` is the 2.5D planning multiplier (graft-repl): at
    replication c the per-device operator slice AND carriage grow
    exactly ×c (c-fold coarser block shards), so a c=1 executor's
    model predicts the replicated footprint as ``base × c`` — the
    number ``auto_repl`` certifies against the HBM budget before
    anything is built.  Executors without the ``repl`` kwarg (older
    models) fall back to the same ×c scaling applied outside."""
    fn = getattr(obj, "predicted_hbm_bytes", None)
    if fn is None:
        return None
    repl = max(int(repl), 1)
    try:
        return int(fn(k, itemsize=itemsize, repl=repl))
    except TypeError:
        return int(fn(k, itemsize=itemsize)) * repl


def request_bytes_for(obj, k: int, itemsize: int = 4,
                      repl: int = 1) -> Optional[int]:
    """The *incremental* per-shard HBM bytes a request of feature
    width ``k`` adds on top of the executor's resident operator —
    the per-request admission price graft-serve charges against its
    live HBM accountant.  Executors exposing ``carriage_hbm_bytes``
    (parallel/multi_level.py) answer directly; otherwise the price is
    the difference of the static model at k and at 0 (the resident
    operator alone).  None when the executor has no model at all —
    the caller must then admit pessimistically or loudly."""
    fn = getattr(obj, "carriage_hbm_bytes", None)
    if fn is not None:
        return int(fn(k, itemsize=itemsize, repl=repl))
    full = predicted_bytes_for(obj, k, itemsize=itemsize, repl=repl)
    base = predicted_bytes_for(obj, 0, itemsize=itemsize, repl=repl)
    if full is None or base is None:
        return None
    return max(int(full) - int(base), 0)


def largest_fitting_repl(base_bytes: int, budget_bytes: int,
                         choices=(1, 2, 4, 8)) -> int:
    """Largest replication factor whose predicted ×c footprint fits
    the per-device HBM budget (always at least 1 — c=1 is the
    unreplicated baseline, not a plan choice).  The memreport CLI
    prints this per executable; ``obs/comm.auto_repl`` applies the
    same certificate plus divisibility and the T(c) time model."""
    best = 1
    for c in sorted(set(int(c) for c in choices)):
        if c >= 1 and base_bytes * c <= budget_bytes:
            best = max(best, c)
    return best


def account_memory(algorithm: str, jitted_fn, *args,
                   predicted_bytes: Optional[int] = None,
                   registry=None, **kwargs) -> Dict[str, Any]:
    """Account one jitted entry point's per-device HBM bytes.

    Returns ``{"algorithm", "report" (full memory_report dict),
    "measured_bytes", "predicted_bytes", "ratio", "source"}``.
    ``measured_bytes`` is the executable's total device-resident
    footprint; ``ratio`` is None when no predictor was supplied or the
    prediction is zero.
    """
    report = memory_report(jitted_fn, *args, **kwargs)
    measured = report["total_bytes"]
    ratio = None
    if predicted_bytes:
        ratio = measured / predicted_bytes

    if registry is not None:
        registry.gauge("hbm_argument_bytes", algorithm=algorithm).set(
            report["argument_bytes"])
        registry.gauge("hbm_output_bytes", algorithm=algorithm).set(
            report["output_bytes"])
        if report["temp_bytes"] is not None:
            registry.gauge("hbm_temp_bytes", algorithm=algorithm).set(
                report["temp_bytes"])
        if report["generated_code_bytes"] is not None:
            registry.gauge("hbm_generated_code_bytes",
                           algorithm=algorithm).set(
                report["generated_code_bytes"])
        registry.gauge("hbm_measured_bytes", algorithm=algorithm).set(
            measured)
        if predicted_bytes is not None:
            registry.gauge("hbm_predicted_bytes",
                           algorithm=algorithm).set(predicted_bytes)
        if ratio is not None:
            registry.gauge("hbm_vs_predicted_ratio",
                           algorithm=algorithm).set(ratio)

    out = {
        "algorithm": algorithm,
        "report": report,
        "measured_bytes": measured,
        "predicted_bytes": predicted_bytes,
        "ratio": ratio,
        "source": report["source"],
    }
    # The flight recorder keeps the latest report whole: an upload that
    # wedges the tunnel mid-transfer is diagnosed by exactly this
    # breakdown (what was being made resident, and how big).
    rec = flight.get_recorder()
    if rec is not None:
        rec.note_memory_report({
            "algorithm": algorithm, "measured_bytes": measured,
            "predicted_bytes": predicted_bytes, "ratio": ratio,
            **report})
    return out


def format_memory_report(rep: Dict[str, Any]) -> str:
    """Human-readable lines for the CLIs' ``--mem_report``."""
    r = rep["report"]

    def mb(v):
        return "n/a" if v is None else f"{v / 2**20:.2f} MiB"

    lines = [
        f"per-device executable memory ({rep['source']}):",
        f"  arguments      {mb(r['argument_bytes'])}",
        f"  outputs        {mb(r['output_bytes'])}",
        f"  temporaries    {mb(r['temp_bytes'])}",
        f"  generated code {mb(r['generated_code_bytes'])}",
        f"  total          {mb(rep['measured_bytes'])}",
    ]
    if rep["ratio"] is not None:
        lines.append(
            f"measured vs format-model prediction: "
            f"{rep['measured_bytes']} / {rep['predicted_bytes']} bytes "
            f"= {rep['ratio']:.2f}x")
    return "\n".join(lines)
