"""graft-lens: per-level compute profiling for the folded operator.

``tools/profile_tpu.py`` proved the philosophy — break the opaque
iteration into its constituent device programs — as a loose script.
This module promotes it into the obs stack as a library: profile one
structure's fold step per degree-ladder tier, per carriage dtype, pair
every measurement with the STATIC counters of ``obs/costmodel.py``
(nnz / rows / streamed bytes straight off the realized SELL tiers),
optionally split DMA-stream wait from accumulate time via a ring-depth
sweep (``ring=1`` serializes the copies the deep ring overlaps), and
fit/score the per-level-family cost model.

The resulting profile document is the contract everything downstream
consumes: ``fit_from_profile`` → a :class:`~.costmodel.CostModel` for
the tune compute screen, ``ratio_points`` → the measured/predicted
calibration records the ledger bands (``kind="lens"``),
``attribution_fractions`` → graft-xray's per-class compute
subdivision, ``explain_gap`` → the per-level answer to "where did the
bf16 regression land".

All timing goes through the shared ``obs/tracer.py`` helpers — one
honest way to time async-dispatch work (graft-lint R7).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from arrow_matrix_tpu.obs.costmodel import (
    CostModel,
    GRANULE,
    ITEMSIZE,
    fit_cost_model,
    schedule_family,
    tier_family,
    tier_stream_bytes,
)
LENS_PROFILE_SCHEMA = 1

#: Acceptance tolerance: per-level attribution must cover the measured
#: full iteration within this relative gap (ISSUE 18 criterion).
LENS_COVERAGE_TOL = 0.10

#: Calibration band for measured/predicted ratios — the ledger gate
#: re-declares the same band on its side (``ledger/gate.py``).
LENS_RATIO_MIN = 0.5
LENS_RATIO_MAX = 2.0

#: A level whose marginal (prefix-difference) time is under this
#: fraction of the full iteration is below the harness's differencing
#: resolution: it cannot meaningfully move the iteration time and its
#: measured/predicted ratio is noise, so it is tagged
#: ``below_resolution`` and excluded from the fit and the calibration
#: ratios (its ms still counts toward attribution/coverage).
LENS_RESOLUTION_FRAC = 0.05


def _resolve_kernel(kernel: str, k: int, platform: str) -> str:
    if kernel != "auto":
        return kernel
    from arrow_matrix_tpu.ops.pallas_sell import supported_feature_width
    return "pallas" if (platform == "tpu"
                        and supported_feature_width(k)) else "xla"


def _tier_static(sell, t: int, k: int, *, kernel: str,
                 feature_dtype: Optional[str],
                 schedule=None) -> Dict[str, Any]:
    """Static counter row for one realized SELL tier — same fields
    :func:`~.costmodel.tier_counters` derives from the fingerprint, but
    read off the concrete operator the profile actually ran.  A
    graft-synth ``schedule`` override for tier ``t`` refines the
    family key (``kernel:fam@rbN``) and the priced carriage, exactly
    as ``costmodel.tier_counters`` does — so a scheduled profile fits
    the same per-level family keys the tune screen predicts with."""
    cols = sell.cols[t]
    m_t, n_t = int(cols.shape[0]), int(cols.shape[1])
    if sell.deg is not None:
        nnz = int(np.asarray(sell.deg[t]).sum())
    elif sell.data is not None:
        nnz = int(np.count_nonzero(np.asarray(sell.data[t])))
    else:
        nnz = m_t * n_t
    ov = None
    for e in (schedule or []):
        if int(e.get("tier", -1)) == t:
            ov = e
            break
    if ov is None:
        family = f"{kernel}:{tier_family(m_t)}"
    else:
        family = schedule_family(kernel, m_t,
                                 int(ov.get("row_block", 256)))
        feature_dtype = ov.get("carriage", feature_dtype)
    itemsize = ITEMSIZE.get(feature_dtype, 4)
    granule = GRANULE if kernel == "pallas" else 1
    return {
        "tier": t,
        "family": family,
        "rows": n_t,
        "nnz": nnz,
        "slots": m_t * n_t,
        "slot_width": m_t,
        "padded_slots": m_t * n_t - nnz,
        "streamed_bytes": tier_stream_bytes(m_t, n_t, k,
                                            itemsize=itemsize,
                                            granule=granule),
    }


def _tier_launches(multi, sell, x, k: int, *, kernel: str,
                   feature_dtype: Optional[str],
                   kernel_opts: Dict[str, Any]):
    """Yield ``(tier, fn, prefix, single)`` per non-empty tier, where
    ``fn`` is the EXACT production kernel entry point the fold step
    dispatches (``sell_spmm_t`` / ``sell_spmm_t_pallas``), ``prefix``
    the sub-SellMatrix holding tiers ``0..tier`` and ``single`` the
    one-tier sub.  Attribution times the PREFIX programs and takes
    successive differences: every prefix pays the same fixed
    per-program cost (chain bump, shared feature decode, loop
    overhead), so the difference isolates the tier's marginal compute
    and the tier sum telescopes to the full multi-tier program
    instead of over-counting the fixed cost once per level."""
    from arrow_matrix_tpu.ops.sell import SellMatrix

    def sub_upto(j: int) -> SellMatrix:
        # row_starts holds starts only (tier t ends at the next start,
        # the last at n_rows), so the prefix through tier j ends at
        # row_starts[j + 1] when one exists.
        end = (int(sell.row_starts[j + 1])
               if j + 1 < len(sell.row_starts) else int(sell.n_rows))
        return SellMatrix(
            cols=tuple(sell.cols[:j + 1]),
            data=(tuple(sell.data[:j + 1])
                  if sell.data is not None else None),
            deg=(tuple(sell.deg[:j + 1])
                 if sell.deg is not None else None),
            n_rows=end,
            row_starts=tuple(int(r) for r in sell.row_starts[:j + 1]))

    for t, cols in enumerate(sell.cols):
        m_t, n_t = int(cols.shape[0]), int(cols.shape[1])
        if m_t == 0:
            continue
        single = SellMatrix(
            cols=(cols,),
            data=(sell.data[t],) if sell.data is not None else None,
            deg=(sell.deg[t],) if sell.deg is not None else None,
            n_rows=n_t, row_starts=(0,))
        if kernel == "pallas":
            from arrow_matrix_tpu.ops.pallas_sell import (
                sell_spmm_t_pallas,
            )
            opts = {kk: v for kk, v in kernel_opts.items()
                    if kk != "feature_dtype"}
            fn = jax_jit(functools.partial(
                sell_spmm_t_pallas, feature_dtype=feature_dtype,
                **opts))
        else:
            from arrow_matrix_tpu.ops.sell import sell_spmm_t
            from arrow_matrix_tpu.parallel.multi_level import (
                gather_budget_for,
            )
            gb = gather_budget_for(multi.dense_budget)
            fn = jax_jit(functools.partial(sell_spmm_t,
                                           gather_budget=gb))
        yield t, fn, sub_upto(t), single


def jax_jit(fn):
    import jax
    return jax.jit(fn)


def _chain_sampler(raw_fn, x, iters: int):
    """Compile-and-warm one chained measurement of ``raw_fn(x)`` —
    ``iters`` iterations inside ONE ``lax.scan`` program
    (``tracer.chained_sampler`` underneath, so the dispatch+fetch
    round-trip is subtracted) — and return its zero-arg sampler.

    A same-shape program (the full fold step) feeds its output back
    as the next carry; a shape-changing one (a tier-prefix launch)
    threads a runtime-valued, numerically negligible bump of its
    output back into the carry instead — either way every iteration
    depends on the previous one, so the compiler can neither hoist
    the call out of the scan nor dead-code it.
    """
    import jax
    import jax.numpy as jnp

    from arrow_matrix_tpu.obs.tracer import chained_sampler

    def body(carry, _):
        out = raw_fn(carry)
        if out.shape == carry.shape and out.dtype == carry.dtype:
            return out, None
        bump = (out.astype(jnp.float32).sum()
                * jnp.float32(1e-30)).astype(carry.dtype)
        return carry + bump, None

    @functools.partial(jax.jit, static_argnames="n")
    def run(x0, n):
        return jax.lax.scan(body, x0, None, length=n)[0]

    return chained_sampler(lambda x0, n: run(x0, n=n), x, iters)


def _sweep_min(samplers: Dict[str, Any], repeats: int = 5
               ) -> Dict[str, float]:
    """Minimum ms per program over ``repeats`` interleaved sampling
    sweeps.  At the µs/iteration scale of a single tier prefix, host
    load drift is the dominant error; sweeping every program once per
    round puts the drift on whole rounds, and the per-program minimum
    — the classic noise-robust timing estimator — discards it."""
    best: Dict[str, float] = {}
    for _ in range(max(repeats, 1)):
        for name, sample in samplers.items():
            ms = sample()
            if name not in best or ms < best[name]:
                best[name] = ms
    return best


def profile_fold(levels, width: int, k: int, *,
                 kernel: str = "auto",
                 feature_dtypes: Sequence[str] = ("f32",),
                 iters: int = 20,
                 ring_sweep: bool = False,
                 kernel_opts: Optional[Dict[str, Any]] = None,
                 growth: float = 1.2,
                 fold_align: Optional[int] = None,
                 registry=None) -> Dict[str, Any]:
    """Profile one structure's folded step per tier and carriage dtype.

    Builds the fold executor once per dtype, times the full jitted
    step, then attributes each tier as the DIFFERENCE between the
    production kernel run on tiers ``0..t`` and on tiers ``0..t-1``
    (the fold step is a linear sum of per-tier programs, so the
    telescoped per-level times should cover the full step —
    ``coverage`` records how well they do; differencing cancels the
    fixed per-program cost that a naive one-launch-per-tier
    measurement over-counts once per level).  With ``ring_sweep`` and the pallas kernel, each tier is
    re-timed at ``ring=1``: the excess over the deep-ring time is the
    DMA wait the ring was hiding, stored per level family.

    Every number is a CHAINED on-device measurement (``iters``
    iterations inside one ``lax.scan`` program, dispatch round-trip
    subtracted — the ``obs.tracer.chained_iteration_ms`` discipline):
    the full step is ONE dispatch while per-tier attribution would pay
    one dispatch per level, so per-call walls would double-count
    launch overhead once per tier — fatal at small-structure scale
    where dispatch rivals compute.  Chaining amortizes it on both
    sides instead of modeling it.

    Returns the lens profile document (schema 1) that every other
    graft-lens entry point consumes.
    """
    import jax

    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.tune.fingerprint import (
        fingerprint_hash,
        structure_fingerprint,
    )
    from arrow_matrix_tpu.utils.graphs import random_dense

    platform = jax.default_backend()
    kernel = _resolve_kernel(kernel, k, platform)
    kopts = dict(kernel_opts or {})
    fp = structure_fingerprint(levels, width, np.float32,
                               growth=growth, slot_align=fold_align)
    doc: Dict[str, Any] = {
        "schema": LENS_PROFILE_SCHEMA,
        "kind": "lens_profile",
        "structure_hash": fingerprint_hash(fp),
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "width": int(width),
        "k": int(k),
        "kernel": kernel,
        "iters": int(iters),
        "kernel_opts": kopts,
        "dtypes": {},
    }
    for fd in feature_dtypes:
        feature_dtype = None if fd == "f32" else fd
        multi = MultiLevelArrow(
            levels, width, mesh=None, fmt="fold",
            kernel="pallas_sell" if kernel == "pallas" else "xla",
            kernel_opts=kopts or None, feature_dtype=feature_dtype,
            fold_growth=growth, fold_align=fold_align)
        doc["n"] = int(multi.n)
        sell = multi.blocks[0]
        x = multi.set_features(random_dense(multi.n, k, seed=3))

        # Compile/warm every chain program first, then sample them in
        # interleaved sweeps (_sweep_min) so host load drift cannot
        # bias one program against another.  "floor" is the
        # shape-changing chain's own per-iteration cost (scan step +
        # carry bump, no kernel) — the base of the prefix telescoping,
        # so the chain's own cost never lands on a level.
        samplers = {
            "full": _chain_sampler(lambda c: multi._step(
                c, multi.fwd, multi.bwd, multi.blocks), x, iters),
            "floor": _chain_sampler(lambda c: c[:1, :1], x, iters),
        }
        launches = list(_tier_launches(
            multi, sell, x, k, kernel=kernel,
            feature_dtype=feature_dtype, kernel_opts=kopts))
        # The ring sweep re-times SINGLE-tier subs, whose tier index
        # collapses to 0 — a graft-synth schedule keyed by original
        # tier index would misalign there, so scheduled profiles skip
        # the DMA-wait split (their ring depths are already per-tier).
        do_ring = (ring_sweep and kernel == "pallas"
                   and not kopts.get("schedule"))
        for t, fn, prefix, single in launches:
            samplers[f"prefix{t}"] = _chain_sampler(
                functools.partial(fn, prefix), x, iters)
            if do_ring:
                from arrow_matrix_tpu.ops.pallas_sell import (
                    sell_spmm_t_pallas,
                )
                samplers[f"deep{t}"] = _chain_sampler(
                    functools.partial(fn, single), x, iters)
                opts1 = {kk: v for kk, v in kopts.items()
                         if kk not in ("feature_dtype", "ring")}
                samplers[f"ring1_{t}"] = _chain_sampler(
                    functools.partial(
                        sell_spmm_t_pallas, single, ring=1,
                        feature_dtype=feature_dtype, **opts1),
                    x, iters)
        best = _sweep_min(samplers)
        full_ms = best["full"]
        floor_ms = max(best["floor"], 0.0)
        if registry is not None:
            registry.record("call_time_ms", full_ms,
                            call=f"lens_full_{fd}", dtype=fd)
        tiers: List[Dict[str, Any]] = []
        for t, cols in enumerate(sell.cols):
            tiers.append(_tier_static(
                sell, t, k, kernel=kernel,
                feature_dtype=feature_dtype,
                schedule=kopts.get("schedule")))
        dma_wait: Dict[str, List[float]] = {}
        prev_ms = floor_ms
        for t, fn, prefix, single in launches:
            cur = best[f"prefix{t}"]
            ms = max(cur - prev_ms, 0.0)
            prev_ms = max(cur, prev_ms)
            tiers[t]["measured_ms"] = float(ms)
            if registry is not None:
                registry.record("call_time_ms", ms,
                                call=f"lens_tier{t}_{fd}", dtype=fd)
            if do_ring:
                ms1 = best[f"ring1_{t}"]
                tiers[t]["ring1_ms"] = float(ms1)
                wait = max(float(ms1) - float(best[f"deep{t}"]), 0.0)
                tiers[t]["dma_wait_ms"] = wait
                dma_wait.setdefault(tiers[t]["family"], []).append(wait)
        attributed = sum(t.get("measured_ms", 0.0) for t in tiers)
        resolution_ms = max(float(floor_ms),
                            LENS_RESOLUTION_FRAC * float(full_ms))
        for tr in tiers:
            if (tr.get("measured_ms") is not None
                    and tr["measured_ms"] < resolution_ms):
                tr["below_resolution"] = True
        entry = {
            "full_ms": float(full_ms),
            "chain_floor_ms": float(floor_ms),
            "resolution_ms": float(resolution_ms),
            "attributed_ms": float(attributed),
            "coverage": float(attributed / full_ms) if full_ms else 0.0,
            "tiers": tiers,
            "dma_wait_ms": {f: float(np.mean(v))
                            for f, v in sorted(dma_wait.items())},
        }
        doc["dtypes"][fd] = entry
    return doc


# ---------------------------------------------------------------------------
# Model fit / score over a profile
# ---------------------------------------------------------------------------

def fit_from_profile(profile: Dict[str, Any],
                     dtypes: Optional[Sequence[str]] = None
                     ) -> CostModel:
    """Fit the per-level-family model from one profile's measured
    tiers.  By default ALL carriage dtypes feed one joint fit — the
    f32/bf16 pair varies ``streamed_bytes`` at fixed nnz/rows, which
    is exactly the leverage that separates the byte coefficient from
    the accumulate coefficients."""
    points: List[Dict[str, Any]] = []
    waits: Dict[str, List[float]] = {}
    for fd, entry in profile["dtypes"].items():
        if dtypes is not None and fd not in dtypes:
            continue
        for t in entry["tiers"]:
            if t.get("measured_ms") and not t.get("below_resolution"):
                points.append(t)
        for fam, w in entry.get("dma_wait_ms", {}).items():
            waits.setdefault(fam, []).append(float(w))
    return fit_cost_model(
        points,
        structure_hash=str(profile.get("structure_hash", "")),
        platform=str(profile.get("platform", "")),
        dma_wait_ms={f: float(np.mean(v)) for f, v in waits.items()})


def ratio_points(profile: Dict[str, Any], model: CostModel
                 ) -> List[Dict[str, Any]]:
    """Measured/predicted ratio per measured tier point (plus one
    full-iteration point per dtype) — the first-class calibration
    metric the ledger records and the gate bands."""
    out: List[Dict[str, Any]] = []
    for fd, entry in profile["dtypes"].items():
        total_pred = 0.0
        for t in entry["tiers"]:
            measured = float(t.get("measured_ms") or 0.0)
            if measured <= 0.0 or t.get("below_resolution"):
                continue
            pred = model.predict_point(t["family"], t["nnz"],
                                       t["rows"], t["streamed_bytes"])
            total_pred += pred
            out.append({
                "dtype": fd, "tier": t["tier"], "family": t["family"],
                "measured_ms": measured, "predicted_ms": pred,
                "ratio": measured / pred if pred > 0 else float("inf"),
            })
        full = float(entry["full_ms"])
        if total_pred > 0 and full > 0:
            out.append({
                "dtype": fd, "tier": None, "family": "full",
                "measured_ms": full, "predicted_ms": total_pred,
                "ratio": full / total_pred,
            })
    return out


def attribution_fractions(profile: Dict[str, Any], dtype: str
                          ) -> Dict[str, float]:
    """Per-level fractions of the measured full iteration for one
    carriage dtype, normalized to sum to 1 (the remainder the tier
    sum does not cover lands in ``other``) — graft-xray's compute
    segment subdivides by these."""
    entry = profile["dtypes"][dtype]
    full = float(entry["full_ms"])
    if full <= 0.0:
        return {}
    out: Dict[str, float] = {}
    for t in entry["tiers"]:
        ms = float(t.get("measured_ms") or 0.0)
        if ms > 0.0:
            out[f"L{t['tier']}:{t['family'].split(':')[1]}"] = ms / full
    covered = sum(out.values())
    if covered > 1.0:  # timing noise: renormalize over the tier sum
        out = {lbl: v / covered for lbl, v in out.items()}
    else:
        out["other"] = 1.0 - covered
    return out


def explain_gap(profile: Dict[str, Any], *, base: str = "f32",
                other: str = "bf16",
                model: Optional[CostModel] = None) -> Dict[str, Any]:
    """Attribute the ``other``−``base`` full-iteration gap per level.

    Names the dominant per-level delta, and — when a model is given —
    classifies it into a segment: the gather/stream term (γ·Δbytes:
    the byte volume CHANGES between carriages) versus the
    decode/accumulate residual (the cast + unpack work the byte model
    cannot see), versus DMA wait (the ring-sweep split).
    """
    eb = profile["dtypes"][base]
    eo = profile["dtypes"][other]
    gap = float(eo["full_ms"]) - float(eb["full_ms"])
    deltas: Dict[str, float] = {}
    gather_delta: Dict[str, float] = {}
    for tb, to in zip(eb["tiers"], eo["tiers"]):
        label = f"L{tb['tier']}:{tb['family'].split(':')[1]}"
        d = (float(to.get("measured_ms") or 0.0)
             - float(tb.get("measured_ms") or 0.0))
        if to.get("measured_ms") or tb.get("measured_ms"):
            deltas[label] = d
        if model is not None:
            gamma = model.coeffs.get(to["family"], {}).get(
                "streamed_bytes", 0.0)
            gather_delta[label] = gamma * (
                float(to["streamed_bytes"]) - float(tb["streamed_bytes"]))
    wait_b = sum(eb.get("dma_wait_ms", {}).values())
    wait_o = sum(eo.get("dma_wait_ms", {}).values())
    if wait_b or wait_o:
        deltas["dma_wait"] = wait_o - wait_b
    if not deltas:
        return {"gap_ms": gap, "per_level": {}, "dominant": None,
                "dominant_segment": None, "note": "no measured tiers"}
    dominant = max(deltas, key=lambda lbl: abs(deltas[lbl]))
    if dominant == "dma_wait":
        segment = "dma-wait"
        note = (f"{other} vs {base}: dominant delta is DMA wait "
                f"({deltas[dominant]:+.3f} ms)")
    else:
        segment = "decode/accumulate"
        g = gather_delta.get(dominant)
        if g is not None and abs(g) >= 0.5 * abs(deltas[dominant]) > 0:
            segment = "gather-bytes"
        note = (f"{other} vs {base}: dominant delta at {dominant} "
                f"({deltas[dominant]:+.3f} ms of {gap:+.3f} ms gap), "
                f"segment: {segment}")
    return {"gap_ms": gap, "per_level": deltas,
            "gather_delta_ms": gather_delta or None,
            "dominant": dominant, "dominant_segment": segment,
            "note": note}


def predict_profile_iter_ms(profile: Dict[str, Any], model: CostModel,
                            dtype: str = "f32") -> float:
    """Model-predicted full-iteration ms for one profile point — the
    sum over its static tier counters (convenience for check/doctor)."""
    entry = profile["dtypes"][dtype]
    return model.predict_tiers(
        [t for t in entry["tiers"] if t["slot_width"] > 0])


# ---------------------------------------------------------------------------
# Ledger emission
# ---------------------------------------------------------------------------

def record_profile(profile: Dict[str, Any],
                   model: Optional[CostModel] = None,
                   directory: Optional[str] = None) -> List[str]:
    """Sink one profile (and, with a model, its calibration ratios) as
    ``kind="lens"`` ledger records.

    Millisecond metrics record with the default host-load stamp like
    every other timing emitter; ratio metrics record with
    ``host_load=None`` — a measured/predicted ratio is load-invariant
    (both sides ran under the same load), and normalizing it would
    skew the baseline median the drift band is taken over.
    """
    from arrow_matrix_tpu.ledger import store as ledger_store

    sh = str(profile.get("structure_hash", ""))
    kern = profile.get("kernel", "?")
    if (profile.get("kernel_opts") or {}).get("schedule"):
        # A graft-synth scheduled profile is a distinct measurement
        # series: same structure, different programs — its metrics
        # must not share baselines with the uniform-knob profile.
        kern = f"{kern}-synth"
    k = int(profile.get("k", 0))
    ids: List[str] = []

    def _rec(metric, value, unit, **extra):
        rid = ledger_store.record(
            "lens", metric, round(float(value), 6),
            directory=directory, unit=unit, structure_hash=sh,
            knobs={"kernel": kern, "k": k,
                   "width": int(profile.get("width", 0)), **extra},
            **({"host_load": None} if unit == "ratio" else {}))
        if rid:
            ids.append(rid)

    for fd, entry in profile["dtypes"].items():
        _rec(f"lens_full_ms_{kern}_{fd}_k{k}", entry["full_ms"], "ms",
             feature_dtype=fd)
        for t in entry["tiers"]:
            if t.get("measured_ms"):
                _rec(f"lens_tier{t['tier']}_ms_{kern}_{fd}_k{k}",
                     t["measured_ms"], "ms", feature_dtype=fd,
                     tier=t["tier"], family=t["family"])
        _rec(f"lens_coverage_{kern}_{fd}_k{k}", entry["coverage"],
             "ratio", feature_dtype=fd)
    if model is not None:
        for p in ratio_points(profile, model):
            tier = "full" if p["tier"] is None else f"t{p['tier']}"
            _rec(f"lens_ratio_{kern}_{p['dtype']}_k{k}_{tier}",
                 p["ratio"], "ratio", feature_dtype=p["dtype"],
                 family=p["family"])
    return ids


def check_profile(profile: Dict[str, Any],
                  model: Optional[CostModel] = None,
                  coverage_tol: float = LENS_COVERAGE_TOL
                  ) -> List[str]:
    """Problem strings for one profile (+model): schema drift,
    attribution that fails to cover the measured iteration, ratios
    outside the calibration band.  Empty list == healthy."""
    problems: List[str] = []
    if profile.get("schema") != LENS_PROFILE_SCHEMA:
        problems.append(
            f"lens profile schema {profile.get('schema')} != "
            f"{LENS_PROFILE_SCHEMA}")
        return problems
    if not profile.get("dtypes"):
        problems.append("lens profile has no dtype entries")
    for fd, entry in profile.get("dtypes", {}).items():
        full = float(entry.get("full_ms") or 0.0)
        if not np.isfinite(full) or full <= 0.0:
            problems.append(f"{fd}: non-positive full_ms {full}")
            continue
        cov = float(entry.get("coverage") or 0.0)
        if abs(cov - 1.0) > coverage_tol:
            problems.append(
                f"{fd}: per-level attribution covers {cov:.3f} of the "
                f"measured iteration (|1-cov| > {coverage_tol})")
        measured = [t for t in entry.get("tiers", ())
                    if t.get("measured_ms")]
        if not measured:
            problems.append(f"{fd}: no measured tiers")
    if model is not None:
        for p in ratio_points(profile, model):
            r = p["ratio"]
            if not (LENS_RATIO_MIN <= r <= LENS_RATIO_MAX):
                where = ("full" if p["tier"] is None
                         else f"tier {p['tier']}")
                problems.append(
                    f"{p['dtype']} {where}: measured/predicted ratio "
                    f"{r:.3f} outside [{LENS_RATIO_MIN}, "
                    f"{LENS_RATIO_MAX}]")
    return problems
